#!/usr/bin/env python
"""Synthetic ResNet-50 training benchmark, the TPU-native mirror of the
reference's headline harness
(``/root/reference/examples/tensorflow2/tensorflow2_synthetic_benchmark.py``:
ResNet-50, synthetic ImageNet batches, SGD, DistributedGradientTape).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Baseline: the reference's published 4x4-GPU tf_cnn_benchmarks figure,
1656.82 images/sec over 16 Pascal GPUs = 103.55 images/sec/GPU
(``/root/reference/docs/benchmarks.rst:30-43``; see BASELINE.md).
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import ResNet50

BASELINE_IMG_PER_SEC_PER_CHIP = 1656.82 / 16  # docs/benchmarks.rst:30-43


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=128,
                        help="per-chip batch size")
    parser.add_argument("--num-iters", type=int, default=10)
    parser.add_argument("--num-warmup", type=int, default=3)
    parser.add_argument("--fp32", action="store_true",
                        help="compute in float32 instead of bfloat16")
    args = parser.parse_args()

    hvd.init()
    n = hvd.size()
    axis = hvd.axis_name()
    mesh = hvd.mesh()

    model = ResNet50(num_classes=1000,
                     dtype=jnp.float32 if args.fp32 else jnp.bfloat16,
                     axis_name=axis)
    rng = jax.random.PRNGKey(0)
    images_host = np.random.default_rng(0).standard_normal(
        (n * args.batch_size, 224, 224, 3), dtype=np.float32)
    labels_host = np.random.default_rng(1).integers(
        0, 1000, size=(n * args.batch_size,))

    variables = model.init(rng, jnp.zeros((1, 224, 224, 3), jnp.float32),
                           train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # Reference benchmark uses plain SGD lr=0.01; gradient sync through the
    # framework's DistributedOptimizer (allreduce average over the mesh).
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    opt_state = tx.init(params)

    def train_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats}, images, train=True,
                mutable=["batch_stats"])
            one_hot = jax.nn.one_hot(labels, 1000)
            loss = -jnp.mean(jnp.sum(one_hot * jax.nn.log_softmax(logits), -1))
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_stats, new_opt, loss

    sharded_step = jax.jit(jax.shard_map(
        train_step, mesh=mesh,
        in_specs=(P(), P(), P(), P(axis), P(axis)),
        out_specs=(P(), P(), P(), P()),
        check_vma=False))

    data_sharding = NamedSharding(mesh, P(axis))
    images = jax.device_put(images_host, data_sharding)
    labels = jax.device_put(labels_host, data_sharding)
    params = jax.device_put(params, NamedSharding(mesh, P()))
    batch_stats = jax.device_put(batch_stats, NamedSharding(mesh, P()))
    opt_state = jax.device_put(opt_state, NamedSharding(mesh, P()))

    for _ in range(args.num_warmup):
        params, batch_stats, opt_state, loss = sharded_step(
            params, batch_stats, opt_state, images, labels)
    jax.block_until_ready(loss)

    start = time.perf_counter()
    for _ in range(args.num_iters):
        params, batch_stats, opt_state, loss = sharded_step(
            params, batch_stats, opt_state, images, labels)
    jax.block_until_ready(loss)
    elapsed = time.perf_counter() - start

    total_images = args.num_iters * args.batch_size * n
    img_per_sec_per_chip = total_images / elapsed / n
    print(json.dumps({
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": round(img_per_sec_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec_per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
