#!/usr/bin/env python
"""Synthetic ResNet-50 training benchmark, the TPU-native mirror of the
reference's headline harness
(``/root/reference/examples/tensorflow2/tensorflow2_synthetic_benchmark.py``:
ResNet-50, synthetic ImageNet batches, SGD, DistributedGradientTape).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N, ...}

plus honesty fields the old harness lacked:
  * ``mfu`` — model FLOPs utilization: per-chip training FLOPs per step
    (XLA's own ``cost_analysis()`` of the compiled program, with an analytic
    ResNet-50 fallback) divided by step time and the chip's peak bf16
    FLOP/s. ``null`` when the chip's peak is unknown (e.g. CPU).
  * ``step_time_ms`` — {mean, p50, min, max} over timed WINDOWS of chained
    steps (each window: several steps dispatched back-to-back with a data
    dependency — step i+1 consumes step i's outputs — then one device
    sync). Round-3 measured with a host sync per step, which on a
    remote-tunnel rig adds the tunnel round trip (~75-95 ms measured) to
    every step and once recorded a 4 ms "step" when a sync returned early
    — the chained window is how steady-state training actually runs and
    cannot hide a slow step (the chain serializes them) or invent a fast
    one (min is a window mean).
  * ``loss_first``/``loss_last``/``loss_decreased`` — the optimizer must
    actually be training; a harness that times a broken step is timing
    nothing.
  * ``baseline`` — what ``vs_baseline`` compares against, spelled out: the
    reference's only published absolute throughput is tf_cnn_benchmarks
    ResNet-101 on 2017-era Pascal GPUs, 1656.82 images/sec over 16 GPUs =
    103.55 images/sec/GPU (``/root/reference/docs/benchmarks.rst:30-43``).
    A modern TPU chip beating a 2017 GPU by a large factor is expected, not
    impressive — the honest headline metric is ``mfu`` and the scaling
    efficiency harness (``scaling_bench.py``).

Performance notes (round-4): params/batch-stats/opt-state buffers are
donated (``donate_argnums``), so the update writes in place instead of
copying ~300 MB of state per step.
"""

import argparse
import json
import os
import subprocess
import sys
import time

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # The TPU plugin force-selects itself via jax.config at interpreter
    # start even under JAX_PLATFORMS=cpu; pin the config back so CPU smoke
    # runs never claim (and possibly hang on) the real backend.
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import ResNet50

BASELINE_IMG_PER_SEC_PER_CHIP = 1656.82 / 16  # docs/benchmarks.rst:30-43
BASELINE_DESC = ("reference tf_cnn_benchmarks ResNet-101, 16x Pascal GPU "
                 "(2017), 103.55 images/sec/GPU; docs/benchmarks.rst:30-43")

# ResNet-50 @ 224x224: ~4.1 GMACs forward = 8.2 GFLOPs; backward ~2x forward
# => ~24.6 GFLOPs per image per training step. Used only when XLA's
# cost_analysis is unavailable.
ANALYTIC_RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 8.2e9

# Peak dense bf16 FLOP/s per chip, by jax device_kind (public TPU specs).
PEAK_BF16_FLOPS = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def chip_peak_flops(device) -> float | None:
    kind = device.device_kind
    if kind in PEAK_BF16_FLOPS:
        return PEAK_BF16_FLOPS[kind]
    for name, peak in PEAK_BF16_FLOPS.items():
        if kind.startswith(name) or name.startswith(kind):
            return peak
    return None


# Clean-exit backend probe: claims the backend, runs one matmul, exits.
# Run as a subprocess so a claim failure (or hang) never poisons the main
# process's jax state. NEVER timeout-killed: killing a process mid-claim is
# what wedges the remote tunnel in the first place.
_PROBE = """
import os
import jax, jax.numpy as jnp
if os.environ.get("JAX_PLATFORMS") == "cpu":
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
jax.devices()
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print("BACKEND_PROBE_OK", flush=True)
"""


def wait_for_backend(max_wait_s: float) -> bool:
    """Wait (bounded) for the accelerator backend to answer a clean-exit
    probe. Round 4 lost its only hardware perf artifact because ``hvd.init``
    crashed once against a transiently wedged tunnel (VERDICT r4 weak #3);
    this is the reference's elastic transient-retry posture
    (``/root/reference/horovod/common/elastic.py:151-174``) applied to our
    own tooling. Returns True when a probe succeeds, False on budget
    exhaustion (caller proceeds and lets the real error surface)."""
    import tempfile

    deadline = time.monotonic() + max_wait_s
    attempt = 0
    while True:
        attempt += 1
        t0 = time.monotonic()
        # Detached probe, polled against the deadline, output to a temp
        # file (an undrained PIPE would deadlock a chatty probe AND die on
        # SIGPIPE when we exit — a mid-claim kill, the one thing that must
        # never happen). A probe still hanging at the deadline is left to
        # exit cleanly on its own and we report failure — the caller must
        # then NOT claim the backend itself.
        with tempfile.NamedTemporaryFile("w+", suffix=".probe",
                                         delete=False) as logf:
            proc = subprocess.Popen(
                [sys.executable, "-c", _PROBE], start_new_session=True,
                stdout=logf, stderr=subprocess.STDOUT, text=True)
        while proc.poll() is None:
            if time.monotonic() >= deadline:
                print(f"[bench] probe {attempt} still hanging at the "
                      f"--max-wait deadline; leaving it to exit on its own",
                      file=sys.stderr, flush=True)
                return False
            time.sleep(2)
        with open(logf.name) as f:
            out = f.read()
        try:
            # the probe exited: its log served its purpose — don't let
            # repeated attempts litter the temp dir with .probe files
            # (only a still-hanging probe keeps its file, above)
            os.unlink(logf.name)
        except OSError:
            pass
        took = time.monotonic() - t0
        if "BACKEND_PROBE_OK" in out:
            if attempt > 1:
                print(f"[bench] backend ready after {attempt} probes",
                      file=sys.stderr, flush=True)
            return True
        tail = out.strip().splitlines()
        print(f"[bench] probe {attempt} failed in {took:.0f}s: "
              f"{tail[-1][:160] if tail else 'no output'}",
              file=sys.stderr, flush=True)
        # stop if the remaining budget cannot fit a meaningful probe
        # (sleeping exactly to the deadline would spawn one doomed probe)
        if time.monotonic() + 30.0 >= deadline:
            return False
        time.sleep(min(120.0, deadline - time.monotonic() - 30.0))


def _microbench_mesh():
    """Shared setup for the host-side microbenches (--dispatch-bench /
    --cycle-bench / --pipeline-bench): virtual 8-chip CPU mesh, no
    accelerator probe, ``hvd`` initialized. Factored out of the per-bench
    copies (ISSUE 3 satellite)."""
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import horovod_tpu as hvd
    hvd.init()
    return hvd, hvd.size()


def _median_ms(one_round, iters: int, divisor: int = 1) -> float:
    """Median wall time (ms) per unit over 5 chunks of back-to-back
    rounds (each chunk, like a training loop's steady state, is timed
    around a burst of rounds); two untimed rounds warm compile/plan
    caches first. ``divisor`` converts a round into per-call/per-tensor
    units."""
    jax.block_until_ready(one_round())
    jax.block_until_ready(one_round())
    chunks = 5
    per = max(1, iters // chunks)
    times = []
    for _ in range(chunks):
        t0 = time.perf_counter()
        for _ in range(per):
            outs = one_round()
        jax.block_until_ready(outs)
        times.append((time.perf_counter() - t0) / (per * divisor))
    return float(np.median(times) * 1e3)


def _pipeline_summary():
    """Overlap figures persisted into EVERY bench payload (ISSUE 6
    satellite): the perf trajectory must track whether communication is
    actually hidden, not just wall time."""
    import horovod_tpu as hvd
    p = hvd.fusion_stats()["pipeline"]
    return {
        "overlap_ratio": round(p["overlap_ratio"], 3),
        "inflight_peak": int(p["inflight_peak"]),
        "slot_occupancy": round(p["slot_occupancy"], 3),
        "device_wait_ms": round(p["device_wait_ms"], 3),
    }


def run_dispatch_bench(args) -> None:
    """Per-call eager dispatch overhead microbench (CPU backend, virtual
    8-chip mesh): repeated same-signature ``grouped_allreduce`` with the
    dispatch plan cache off vs on. The payload is deliberately tiny so the
    Python dispatch between XLA launches — mode probing, bundle
    canonicalization, mesh hashing, fusion bucketing, negotiation/autotune
    bookkeeping — dominates the wall time; this is exactly the steady-state
    latency the plan cache (ops/dispatch_cache.py, the ResponseCache HIT
    twin) removes. Prints ONE JSON line; ``value`` is the percent reduction
    in per-call wall time."""
    import jax.numpy as jnp  # noqa: F811 - local for clarity

    from horovod_tpu.ops import dispatch_cache

    hvd, n = _microbench_mesh()
    size = args.dispatch_size
    tensors = [
        hvd.per_rank([jnp.full((size,), float((r + 1) * (i + 1)), jnp.float32)
                      for r in range(n)])
        for i in range(args.dispatch_tensors)
    ]

    def one_call():
        return hvd.grouped_allreduce(tensors, op=hvd.Sum)

    prev = os.environ.get("HVD_CACHE_CAPACITY")
    try:
        os.environ["HVD_CACHE_CAPACITY"] = "0"
        ref_out = [np.asarray(o) for o in one_call()]
        off_ms = _median_ms(one_call, args.dispatch_iters)
        os.environ["HVD_CACHE_CAPACITY"] = "1024"
        dispatch_cache.reset()
        on_out = [np.asarray(o) for o in one_call()]
        on_ms = _median_ms(one_call, args.dispatch_iters)
        stats = dispatch_cache.stats()
    finally:
        if prev is None:
            os.environ.pop("HVD_CACHE_CAPACITY", None)
        else:
            os.environ["HVD_CACHE_CAPACITY"] = prev

    numerics_match = all(np.allclose(a, b) for a, b in zip(ref_out, on_out))
    reduction = (off_ms - on_ms) / off_ms * 100.0 if off_ms else 0.0
    print(json.dumps({
        "metric": "eager_dispatch_plan_cache_reduction",
        "value": round(reduction, 1),
        "unit": "% reduction in per-call eager dispatch wall time",
        "cache_off": {"ms_per_call": round(off_ms, 4)},
        "cache_on": {"ms_per_call": round(on_ms, 4),
                     "stats": stats},
        "numerics_match": bool(numerics_match),
        "pipeline_overlap": _pipeline_summary(),
        "baseline": "same-signature grouped_allreduce, plan cache disabled "
                    "via HVD_CACHE_CAPACITY=0 (the pre-cache dispatch path)",
        "config": {"op": "grouped_allreduce", "tensors": args.dispatch_tensors,
                   "elems_per_tensor": size, "dtype": "float32",
                   "iters": args.dispatch_iters, "n_chips": n,
                   "backend": jax.devices()[0].platform},
    }))


def run_cycle_bench(args) -> None:
    """Cross-call fusion scheduler microbench (CPU backend, virtual 8-chip
    mesh): N small per-tensor ``allreduce_async`` + synchronize, scheduler
    ON (queued submissions coalesce into one grouped flush through the
    plan cache) vs OFF (``HVD_CYCLE_TIME=0``: every async call dispatches
    its own collective immediately — the pre-scheduler behavior). This is
    the reference's headline mechanism (the background cycle fusing
    independently-submitted small tensors, operations.cc:385-806) applied
    to the eager per-parameter gradient loop. Prints ONE JSON line;
    ``value`` is the percent reduction in per-tensor wall time."""
    import jax.numpy as jnp  # noqa: F811 - local for clarity

    from horovod_tpu.ops import dispatch_cache, fusion_cycle

    hvd, n = _microbench_mesh()
    count = args.cycle_tensors
    elems = args.cycle_size // 4  # float32 -> 4 bytes/elem
    tensors = [
        hvd.per_rank([jnp.full((elems,), float((r + 1) * (i + 1)),
                               jnp.float32) for r in range(n)])
        for i in range(count)
    ]

    def one_round():
        handles = [hvd.allreduce_async(t, op=hvd.Sum) for t in tensors]
        return [h.synchronize() for h in handles]

    def set_mode(on: bool) -> None:
        # ON: both cycle knobs pinned long so every flush comes from the
        # synchronize (deterministic full-coalesce measurement) — a
        # mid-chunk timer fire on a share-throttled CI box would
        # otherwise split batches and add preemption noise; the timer
        # path itself is covered by tests/test_fusion_cycle.py.
        os.environ["HVD_CYCLE_TIME"] = "500" if on else "0"
        os.environ["HVD_PENDING_CYCLE_TIME"] = "500"

    def timed_chunk(per):
        t0 = time.perf_counter()
        for _ in range(per):
            outs = one_round()
        jax.block_until_ready(outs)
        return (time.perf_counter() - t0) / (per * count)

    prev = {k: os.environ.get(k)
            for k in ("HVD_CYCLE_TIME", "HVD_PENDING_CYCLE_TIME")}
    try:
        # ABBA-interleaved on/off chunks (the --metrics-bench method,
        # adopted after the sequential version read 10-16% against its
        # 40% floor on slower boxes even at baseline — box drift between
        # the two long mode blocks swamped the scheduler's own delta):
        # both modes see the same load drift pair by pair, alternating
        # which side of the pair runs first, so the comparison measures
        # the scheduler, not the box. Plans for both modes coexist in
        # the dispatch cache after one warm round each.
        dispatch_cache.reset()
        fusion_cycle.reset()
        set_mode(False)  # immediate per-call dispatch (still plan-cached
        # — this measures the scheduler's win on top of PR 1's cache)
        ref_out = [np.asarray(o) for o in one_round()]
        set_mode(True)
        on_out = [np.asarray(o) for o in one_round()]
        chunks = max(args.cycle_iters // 5, 4)
        per = 5
        on_times, off_times = [], []
        for i in range(chunks):
            order = ((False, True) if i % 2 == 0 else (True, False))
            for on in order:
                set_mode(on)
                (on_times if on else off_times).append(timed_chunk(per))
        stats = hvd.fusion_stats()
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    off_ms = float(np.median(off_times) * 1e3)
    on_ms = float(np.median(on_times) * 1e3)
    numerics_match = all(np.allclose(a, b) for a, b in zip(ref_out, on_out))
    reduction = (off_ms - on_ms) / off_ms * 100.0 if off_ms else 0.0
    print(json.dumps({
        "metric": "eager_cycle_fusion_reduction",
        "value": round(reduction, 1),
        "unit": "% reduction in per-tensor async allreduce wall time",
        "scheduler_off": {"ms_per_tensor": round(off_ms, 4)},
        "scheduler_on": {"ms_per_tensor": round(on_ms, 4),
                         "fusion_stats": {
                             k: stats[k] for k in (
                                 "flushes", "flushed_tensors", "dispatches",
                                 "tensors_per_flush", "coalesce_ratio")}},
        "numerics_match": bool(numerics_match),
        "pipeline_overlap": _pipeline_summary(),
        "coalesce_ratio": round(stats["coalesce_ratio"], 2),
        "baseline": "same per-tensor allreduce_async loop with "
                    "HVD_CYCLE_TIME=0 (immediate dispatch, scheduler off; "
                    "dispatch plan cache ON in both modes), strictly "
                    "ABBA-interleaved chunks so box drift cancels",
        "config": {"op": "allreduce_async", "tensors": count,
                   "bytes_per_tensor": args.cycle_size, "dtype": "float32",
                   "iters": args.cycle_iters, "n_chips": n,
                   "backend": jax.devices()[0].platform},
    }))


def run_metrics_bench(args) -> None:
    """Metrics-overhead microbench (docs/metrics.md overhead contract):
    the SAME per-tensor ``allreduce_async`` + synchronize stream as
    --cycle-bench — the path carrying the registry's hot instruments
    (fusion flush/enqueue counters, pending gauge, dispatch-cache hits,
    KV ops when a service runs) — timed with the registry force-ENABLED
    vs force-DISABLED in strictly interleaved A/B chunks, so box drift
    cancels. Prints ONE JSON line; ``value`` is the percent overhead of
    metrics ON over OFF (ci.sh gates <= 3%)."""
    import jax.numpy as jnp  # noqa: F811 - local for clarity

    from horovod_tpu import metrics as _metrics

    hvd, n = _microbench_mesh()
    count = args.metrics_tensors
    elems = args.metrics_size // 4  # float32 -> 4 bytes/elem
    tensors = [
        hvd.per_rank([jnp.full((elems,), float((r + 1) * (i + 1)),
                               jnp.float32) for r in range(n)])
        for i in range(count)
    ]

    def one_round():
        handles = [hvd.allreduce_async(t, op=hvd.Sum) for t in tensors]
        return [h.synchronize() for h in handles]

    def timed_chunk(per):
        t0 = time.perf_counter()
        for _ in range(per):
            outs = one_round()
        jax.block_until_ready(outs)
        return (time.perf_counter() - t0) / (per * count)

    prev = {k: os.environ.get(k)
            for k in ("HVD_CYCLE_TIME", "HVD_PENDING_CYCLE_TIME")}
    try:
        # Cycle knobs pinned long (the --cycle-bench rationale): every
        # flush comes from the synchronize trigger, so a mid-chunk
        # timer fire on a share-throttled CI box cannot split batches
        # and swamp the nanoseconds under measurement.
        os.environ["HVD_CYCLE_TIME"] = "500"
        os.environ["HVD_PENDING_CYCLE_TIME"] = "500"
        # warm compile/plan caches in both modes
        _metrics.set_enabled(True)
        on_ref = [np.asarray(o) for o in one_round()]
        _metrics.set_enabled(False)
        off_ref = [np.asarray(o) for o in one_round()]
        chunks = max(args.metrics_iters // 5, 5)
        per = 5
        on_times, off_times = [], []
        for i in range(chunks):
            # ABBA interleave: alternate which mode runs first in each
            # pair, so warm-up/throttling drift within a pair cancels
            # instead of systematically flattering the second side
            order = ((False, True) if i % 2 == 0 else (True, False))
            for enabled in order:
                _metrics.set_enabled(enabled)
                (on_times if enabled else off_times).append(
                    timed_chunk(per))
    finally:
        _metrics.set_enabled(None)
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    off_ms = float(np.median(off_times) * 1e3)
    on_ms = float(np.median(on_times) * 1e3)
    overhead = (on_ms - off_ms) / off_ms * 100.0 if off_ms else 0.0
    numerics_match = all(np.allclose(a, b)
                         for a, b in zip(on_ref, off_ref))
    print(json.dumps({
        "metric": "metrics_registry_overhead",
        "value": round(overhead, 2),
        "unit": "% per-tensor wall-time overhead of HVD_METRICS=1 vs 0",
        "metrics_off": {"ms_per_tensor": round(off_ms, 4)},
        "metrics_on": {"ms_per_tensor": round(on_ms, 4)},
        "numerics_match": bool(numerics_match),
        "baseline": "identical allreduce_async stream, registry "
                    "force-disabled (hot instruments no-op), strictly "
                    "interleaved A/B chunks",
        "config": {"op": "allreduce_async", "tensors": count,
                   "bytes_per_tensor": args.metrics_size,
                   "chunks": chunks, "rounds_per_chunk": per,
                   "n_chips": n,
                   "backend": jax.devices()[0].platform},
    }))


def run_conformance_bench(args) -> None:
    """Conformance-recorder overhead microbench (docs/conformance.md cost
    contract): the SAME per-tensor ``allreduce_async`` + synchronize
    stream as --metrics-bench — every synchronize-triggered flush feeds
    the recorder a ``flush`` event, and every cold dispatch a
    ``plan_store`` — timed with the recorder force-ENABLED vs
    force-DISABLED in strictly ABBA-interleaved chunks, so box drift
    cancels. Prints ONE JSON line; ``value`` is the percent overhead of
    HVD_CONFORMANCE=1 over 0 (ci.sh gates <= 3%)."""
    import jax.numpy as jnp  # noqa: F811 - local for clarity

    from horovod_tpu import conformance as _conformance

    hvd, n = _microbench_mesh()
    count = args.conformance_tensors
    elems = args.conformance_size // 4  # float32 -> 4 bytes/elem
    tensors = [
        hvd.per_rank([jnp.full((elems,), float((r + 1) * (i + 1)),
                               jnp.float32) for r in range(n)])
        for i in range(count)
    ]

    def one_round():
        handles = [hvd.allreduce_async(t, op=hvd.Sum) for t in tensors]
        return [h.synchronize() for h in handles]

    def timed_chunk(per):
        t0 = time.perf_counter()
        for _ in range(per):
            outs = one_round()
        jax.block_until_ready(outs)
        return (time.perf_counter() - t0) / (per * count)

    prev = {k: os.environ.get(k)
            for k in ("HVD_CYCLE_TIME", "HVD_PENDING_CYCLE_TIME")}
    try:
        # Cycle knobs pinned long (the --cycle-bench rationale): every
        # flush comes from the synchronize trigger, so a mid-chunk timer
        # fire on a share-throttled CI box cannot split batches and
        # swamp the nanoseconds under measurement. Pinned knobs are also
        # the recorder's own comparability precondition
        # (docs/conformance.md "What the flush hash covers").
        os.environ["HVD_CYCLE_TIME"] = "500"
        os.environ["HVD_PENDING_CYCLE_TIME"] = "500"
        # warm compile/plan caches in both modes
        _conformance.set_enabled(True)
        on_ref = [np.asarray(o) for o in one_round()]
        _conformance.set_enabled(False)
        off_ref = [np.asarray(o) for o in one_round()]
        chunks = max(args.conformance_iters // 5, 5)
        per = 5
        on_times, off_times = [], []
        for i in range(chunks):
            # ABBA interleave: alternate which mode runs first in each
            # pair, so warm-up/throttling drift within a pair cancels
            # instead of systematically flattering the second side
            order = ((False, True) if i % 2 == 0 else (True, False))
            for enabled in order:
                _conformance.set_enabled(enabled)
                (on_times if enabled else off_times).append(
                    timed_chunk(per))
        stats = _conformance.conformance_stats()
    finally:
        _conformance.set_enabled(None)
        _conformance.reset()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    off_ms = float(np.median(off_times) * 1e3)
    on_ms = float(np.median(on_times) * 1e3)
    overhead = (on_ms - off_ms) / off_ms * 100.0 if off_ms else 0.0
    numerics_match = all(np.allclose(a, b)
                         for a, b in zip(on_ref, off_ref))
    print(json.dumps({
        "metric": "conformance_recorder_overhead",
        "value": round(overhead, 2),
        "unit": "% per-tensor wall-time overhead of HVD_CONFORMANCE=1 vs 0",
        "conformance_off": {"ms_per_tensor": round(off_ms, 4)},
        "conformance_on": {"ms_per_tensor": round(on_ms, 4),
                           "events": stats["events"],
                           "by_stream": stats["by_stream"]},
        "numerics_match": bool(numerics_match),
        "baseline": "identical allreduce_async stream, recorder "
                    "force-disabled (every hook one cached-bool read + "
                    "early return), strictly ABBA-interleaved chunks",
        "config": {"op": "allreduce_async", "tensors": count,
                   "bytes_per_tensor": args.conformance_size,
                   "chunks": chunks, "rounds_per_chunk": per,
                   "n_chips": n,
                   "backend": jax.devices()[0].platform},
    }))


def run_pipeline_bench(args) -> None:
    """Pipelined flush executor + chunk pipeline microbench (CPU backend,
    virtual 8-chip mesh): a stream of LARGE (default 4 MiB) per-tensor
    ``allreduce_async`` submissions that the scheduler coalesces into one
    flush per round — the cycle scheduler's steady state for a training
    step's gradients. OFF = ``HVD_MAX_INFLIGHT_FLUSHES=1`` (the
    synchronous executor: the flush runs inline on the triggering thread
    and its whole multi-MiB fused buffer is ONE monolithic wire program —
    the PR-2 behavior). ON = 2 in-flight slots +
    ``HVD_PIPELINE_THRESHOLD``/``HVD_PIPELINE_CHUNKS`` chunking: the
    fused buffer dispatches as back-to-back chunk programs whose
    collectives pipeline across the per-device execution queues while the
    executor overlaps the next flush's fuse with in-flight collectives.
    Fuse and split stages are identical work in both modes; the measured
    delta is the wire-stage granularity (plus executor overhead, charged
    against the pipelined side). Prints ONE JSON line; ``value`` is the
    percent reduction in per-round wall time."""
    import jax.numpy as jnp  # noqa: F811 - local for clarity

    from horovod_tpu.ops import dispatch_cache, fusion_cycle

    hvd, n = _microbench_mesh()
    count = args.pipeline_tensors
    elems = args.pipeline_size // 4  # float32 -> 4 bytes/elem
    tensors = [
        hvd.per_rank([jnp.full((elems,), float(r + 1) * 0.5 ** i,
                               jnp.float32) for r in range(n)])
        for i in range(count)
    ]

    def one_round():
        handles = [hvd.allreduce_async(t, op=hvd.Sum) for t in tensors]
        return [h.synchronize() for h in handles]

    knobs = ("HVD_CYCLE_TIME", "HVD_PENDING_CYCLE_TIME",
             "HVD_FUSION_THRESHOLD", "HVD_MAX_INFLIGHT_FLUSHES",
             "HVD_PIPELINE_THRESHOLD", "HVD_PIPELINE_CHUNKS")
    prev = {k: os.environ.get(k) for k in knobs}
    try:
        # both modes: timer quiet and fusion threshold unreachable, so
        # every round's submissions coalesce into ONE synchronize-
        # triggered flush with an identical composition — only the
        # executor and the wire-program granularity differ.
        os.environ["HVD_CYCLE_TIME"] = "500"
        os.environ["HVD_PENDING_CYCLE_TIME"] = "500"
        os.environ["HVD_FUSION_THRESHOLD"] = str(1 << 30)
        os.environ["HVD_MAX_INFLIGHT_FLUSHES"] = "1"
        dispatch_cache.reset()
        fusion_cycle.reset()
        ref_out = [np.asarray(o) for o in one_round()]
        off_ms = _median_ms(one_round, args.pipeline_iters)
        os.environ["HVD_MAX_INFLIGHT_FLUSHES"] = "2"
        os.environ["HVD_PIPELINE_THRESHOLD"] = str(args.pipeline_size)
        os.environ["HVD_PIPELINE_CHUNKS"] = str(args.pipeline_chunks)
        dispatch_cache.reset()
        fusion_cycle.reset()
        on_out = [np.asarray(o) for o in one_round()]
        on_ms = _median_ms(one_round, args.pipeline_iters)
        stats = hvd.fusion_stats()
        cache_stats = dispatch_cache.stats()
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    numerics_match = all(np.allclose(a, b) for a, b in zip(ref_out, on_out))
    reduction = (off_ms - on_ms) / off_ms * 100.0 if off_ms else 0.0
    print(json.dumps({
        "metric": "eager_pipeline_flush_reduction",
        "value": round(reduction, 1),
        "unit": "% reduction in wall time per stream of large async "
                "allreduces",
        "synchronous": {"ms_per_round": round(off_ms, 4)},
        "pipelined": {"ms_per_round": round(on_ms, 4),
                      "pipeline": stats["pipeline"],
                      "chunked_plan_builds": cache_stats["chunked_builds"]},
        "numerics_match": bool(numerics_match),
        "pipeline_overlap": _pipeline_summary(),
        "overlap_ratio": round(stats["pipeline"]["overlap_ratio"], 3),
        "slot_occupancy": round(stats["pipeline"]["slot_occupancy"], 3),
        "baseline": "same large-tensor allreduce_async stream with "
                    "HVD_MAX_INFLIGHT_FLUSHES=1 (synchronous flush "
                    "executor, monolithic wire programs — the "
                    "pre-pipeline behavior)",
        "config": {"op": "allreduce_async", "tensors": count,
                   "bytes_per_tensor": args.pipeline_size,
                   "chunks": args.pipeline_chunks, "dtype": "float32",
                   "iters": args.pipeline_iters, "n_chips": n,
                   "backend": jax.devices()[0].platform},
    }))


def run_overlap_bench(args) -> None:
    """Flush-level overlap microbench (CPU backend, virtual 8-chip mesh):
    a stream of medium async allreduces where EVERY submission is its own
    threshold-triggered flush — the multi-flush stream the pipelined
    executor exists for. The ``--pipeline-bench`` stream coalesces each
    round into ONE synchronize-triggered flush, which by construction can
    never hold two flushes in flight (BENCH_r08/r09's ``overlap_ratio:
    0.0`` was the metric honestly reporting that workload, compounded by
    post-retirement depth sampling — ISSUE 6). Chunking is disabled so
    the measured effect is purely flush k+1 dispatching while flush k's
    collective is in flight. Prints ONE JSON line; ci.sh gates
    ``overlap_ratio > 0`` with >= 2 slots."""
    import jax.numpy as jnp  # noqa: F811 - local for clarity

    from horovod_tpu.ops import dispatch_cache, fusion_cycle

    hvd, n = _microbench_mesh()
    count = args.overlap_tensors
    elems = args.overlap_size // 4  # float32 -> 4 bytes/elem
    tensors = [
        hvd.per_rank([jnp.full((elems,), float(r + 1) * 0.25 ** i,
                               jnp.float32) for r in range(n)])
        for i in range(count)
    ]

    def one_round():
        handles = [hvd.allreduce_async(t, op=hvd.Sum) for t in tensors]
        return [h.synchronize() for h in handles]

    knobs = ("HVD_CYCLE_TIME", "HVD_PENDING_CYCLE_TIME",
             "HVD_FUSION_THRESHOLD", "HVD_MAX_INFLIGHT_FLUSHES",
             "HVD_PIPELINE_THRESHOLD")
    prev = {k: os.environ.get(k) for k in knobs}
    try:
        # timer quiet; threshold of 1 byte = every submission drains its
        # own flush at enqueue; chunking off (threshold unreachable) so
        # only flush-level overlap differs between the modes.
        os.environ["HVD_CYCLE_TIME"] = "500"
        os.environ["HVD_PENDING_CYCLE_TIME"] = "500"
        os.environ["HVD_FUSION_THRESHOLD"] = "1"
        os.environ["HVD_PIPELINE_THRESHOLD"] = str(1 << 30)
        os.environ["HVD_MAX_INFLIGHT_FLUSHES"] = "1"
        dispatch_cache.reset()
        fusion_cycle.reset()
        ref_out = [np.asarray(o) for o in one_round()]
        off_ms = _median_ms(one_round, args.overlap_iters)
        os.environ["HVD_MAX_INFLIGHT_FLUSHES"] = str(args.overlap_slots)
        dispatch_cache.reset()
        fusion_cycle.reset()
        on_out = [np.asarray(o) for o in one_round()]
        on_ms = _median_ms(one_round, args.overlap_iters)
        stats = hvd.fusion_stats()
        summary = _pipeline_summary()
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    numerics_match = all(np.allclose(a, b) for a, b in zip(ref_out, on_out))
    reduction = (off_ms - on_ms) / off_ms * 100.0 if off_ms else 0.0
    print(json.dumps({
        "metric": "eager_flush_overlap_ratio",
        "value": summary["overlap_ratio"],
        "unit": "fraction of flushes dispatched while >=1 earlier flush "
                "was still in flight on device",
        "wall_time_reduction_pct": round(reduction, 1),
        "synchronous": {"ms_per_round": round(off_ms, 4)},
        "pipelined": {"ms_per_round": round(on_ms, 4),
                      "pipeline": stats["pipeline"]},
        "numerics_match": bool(numerics_match),
        "pipeline_overlap": summary,
        "baseline": "same per-flush allreduce_async stream with "
                    "HVD_MAX_INFLIGHT_FLUSHES=1 (synchronous flush "
                    "executor; chunking disabled in both modes)",
        "config": {"op": "allreduce_async", "tensors": count,
                   "bytes_per_tensor": args.overlap_size,
                   "slots": args.overlap_slots, "dtype": "float32",
                   "iters": args.overlap_iters, "n_chips": n,
                   "backend": jax.devices()[0].platform},
    }))


def _step_bench_case(kind, hvd, n, args):
    """One eager data-parallel training setup: returns (label, local_fn,
    state0 host trees, sharded inputs, grad_bytes). ``local_fn`` is the
    jitted shard_map'd LOCAL backward (no collectives inside): per-rank
    gradients come back stacked on the leading rank axis, exactly a
    PerRank layout — gradient sync then happens EAGERLY through
    DistributedOptimizer, which is the path under test."""
    import jax.numpy as jnp  # noqa: F811 - local for clarity
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = hvd.mesh()
    axis = hvd.axis_name()
    batch = args.step_batch

    if kind == "resnet50":
        from horovod_tpu.models import ResNet50
        num_classes = 100
        img = args.step_image_size
        # ResNet-50 (the repo's benchmark workhorse): ~95 MB of f32
        # gradients — squarely in the bucketing regime (several 64 MiB
        # production buckets; several 16 MiB bench buckets). Tiny input
        # resolution keeps the conv compute CI-sized without shrinking
        # the gradient payload, which is what this bench stresses.
        model = ResNet50(num_classes=num_classes, dtype=jnp.float32,
                         axis_name=None)  # BN stats stay rank-local
        x_host = np.random.default_rng(0).standard_normal(
            (n * batch, img, img, 3)).astype(np.float32)
        y_host = np.random.default_rng(1).integers(
            0, num_classes, size=(n * batch,))
        variables = model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, img, img, 3), jnp.float32),
                               train=True)
        params0 = variables["params"]
        stats0 = variables["batch_stats"]

        def local(p, stats_i, x_i, y_i):
            def loss_fn(p):
                logits, mut = model.apply(
                    {"params": p, "batch_stats": stats_i}, x_i,
                    train=True, mutable=["batch_stats"])
                one_hot = jax.nn.one_hot(y_i, num_classes)
                loss = -jnp.mean(jnp.sum(
                    one_hot * jax.nn.log_softmax(logits), -1))
                return loss, mut["batch_stats"]
            (loss, new_stats), g = jax.value_and_grad(
                loss_fn, has_aux=True)(p)
            return g, new_stats, loss
    else:
        from horovod_tpu.models import TransformerConfig, TransformerLM
        seq = args.step_seq_len
        # vocab-heavy LM: the 32k-vocab embedding + lm_head gradients
        # (~33 MB each) put the ~75 MB grad tree in the bucketing
        # regime while the 2-layer trunk keeps CI compute small
        cfg = TransformerConfig(vocab_size=32768, num_layers=2,
                                num_heads=8, d_model=256, d_ff=1024,
                                max_seq_len=seq, dtype=jnp.float32)
        model = TransformerLM(cfg)
        x_host = np.random.default_rng(0).integers(
            0, cfg.vocab_size, size=(n * batch, seq))
        y_host = x_host  # next-token objective shifts inside the loss
        params0 = model.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, seq), jnp.int32))["params"]
        stats0 = {}

        def local(p, stats_i, x_i, y_i):
            del stats_i

            def loss_fn(p):
                logits = model.apply({"params": p}, x_i)
                tgt = jax.nn.one_hot(y_i[:, 1:], cfg.vocab_size)
                return -jnp.mean(jnp.sum(
                    tgt * jax.nn.log_softmax(logits[:, :-1]), -1))
            loss, g = jax.value_and_grad(loss_fn)(p)
            return g, {}, loss

    def shard_fn(p, stats, x_i, y_i):
        stats_i = jax.tree.map(lambda a: a[0], stats)
        g, new_stats, loss = local(p, stats_i, x_i, y_i)
        return (jax.tree.map(lambda a: a[None], g),
                jax.tree.map(lambda a: a[None], new_stats),
                loss[None])

    local_fn = jax.jit(jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P(axis)),
        check_vma=False))
    x = jax.device_put(x_host, NamedSharding(mesh, P(axis)))
    y = jax.device_put(y_host, NamedSharding(mesh, P(axis)))
    grad_bytes = sum(int(np.prod(l.shape)) * 4
                     for l in jax.tree.leaves(params0))
    return local_fn, params0, stats0, x, y, grad_bytes


def _run_step_mode(hvd, local_fn, params0, stats0, x, y, bucket_bytes,
                   iters):
    """One timing pass of the eager DP step (HVD_BUCKET_BYTES pinned):
    per-step wall times with every step materialized to completion (all
    updated param leaves ready — the reference's eager
    ``optimizer.step()`` semantics, where per-bucket completion
    pipelining lands), plus the params after the warmup step from the
    fixed init (numerics probe) and the overlap summary."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.ops import dispatch_cache, fusion_cycle

    mesh = hvd.mesh()
    axis = hvd.axis_name()
    os.environ["HVD_BUCKET_BYTES"] = str(bucket_bytes)
    dispatch_cache.reset()
    fusion_cycle.reset()
    n = hvd.size()

    params = jax.device_put(params0, NamedSharding(mesh, P()))
    stats = jax.device_put(
        jax.tree.map(lambda a: np.broadcast_to(a[None], (n,) + a.shape),
                     stats0),
        NamedSharding(mesh, P(axis)))
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    opt = jax.device_put(tx.init(params0), NamedSharding(mesh, P()))
    state = {"params": params, "stats": stats, "opt": opt}

    def one_step():
        g, state["stats"], loss = local_fn(
            state["params"], state["stats"], x, y)
        gt = jax.tree.map(lambda a: hvd.PerRank(a), g)
        updates, state["opt"] = tx.update(gt, state["opt"],
                                          state["params"])
        state["params"] = optax.apply_updates(state["params"], updates)
        return loss

    # warmup (compiles this mode's fuse/wire plans); materializing it
    # doubles as the numerics probe — params after ONE step from init
    one_step()
    step1 = [np.asarray(l) for l in jax.tree.leaves(state["params"])]
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        one_step()
        jax.block_until_ready(jax.tree.leaves(state["params"]))
        times.append((time.perf_counter() - t0) * 1e3)
    return times, step1, _pipeline_summary()


def _grad_sync_ms(hvd, grads_pr, bucket_bytes, iters=7):
    """Median latency (ms) of syncing the model's ACTUAL gradient tree to
    device completion — the mechanism's direct measurement: bucketed
    dispatch pipelines fuse/wire/split across buckets, so time-to-ready
    drops even where the 2-core CI box can't run comm and compute
    concurrently. Robust where chained-step wall time is noise-bound."""
    from horovod_tpu.ops import dispatch_cache, fusion_cycle
    from horovod_tpu.ops.compression import Compression
    from horovod_tpu.ops.reduce_ops import ReduceOp
    from horovod_tpu.optim import _allreduce_tree

    os.environ["HVD_BUCKET_BYTES"] = str(bucket_bytes)
    dispatch_cache.reset()
    fusion_cycle.reset()

    def sync():
        out = _allreduce_tree(
            grads_pr, op=ReduceOp.AVERAGE, process_set=None,
            compression=Compression.none, prescale_factor=1.0,
            postscale_factor=1.0, axis_name=None)
        jax.block_until_ready(jax.tree.leaves(out))

    sync()
    sync()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        sync()
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def run_step_bench(args) -> None:
    """End-to-end eager data-parallel step-time benchmark (CPU backend,
    virtual 8-chip mesh) for the bucketed backward-pass overlap (ISSUE 6
    tentpole b): per step, a jitted shard_map program computes LOCAL
    per-rank gradients (no collectives in the program), then
    ``DistributedOptimizer`` syncs them eagerly — whole-tree
    (``HVD_BUCKET_BYTES=0``, one grouped allreduce: the pre-bucketing
    behavior) vs bucketed (each size-bounded bucket its own flushed async
    grouped allreduce overlapping the next bucket's fuse and the update
    math). Models: ``models/`` ResNet-50 and TransformerLM. Prints ONE
    JSON line; ci.sh gates numerics parity and bucketed-not-slower on
    the ResNet model. Step time is end-to-end (backward + sync + update),
    not a collective microbench."""
    hvd, n = _microbench_mesh()
    knobs = ("HVD_BUCKET_BYTES", "HVD_CYCLE_TIME", "HVD_PENDING_CYCLE_TIME")
    prev = {k: os.environ.get(k) for k in knobs}
    models = {}
    try:
        # timer quiet: every bucket flush comes from the explicit
        # "bucket" trigger (deterministic composition, no mid-step
        # timer fires on a loaded CI box). Chunking stays at its
        # DEFAULT in both modes — the whole-tree baseline legitimately
        # leans on PR-3 chunk pipelining (pinning it off would triple
        # the baseline's sync time and flatter the bucketing win).
        # Caveat: two in-flight chunked collectives on the 2-core XLA
        # CPU emulation occasionally land a schedule that slows every
        # bucketed step of one PROCESS ~1.5-2x (~1 in 4 runs observed;
        # whole-tree mode in the same run unaffected) — ci.sh retries
        # the gate in a fresh process, and docs/pipeline.md documents
        # the interaction.
        os.environ["HVD_CYCLE_TIME"] = "500"
        os.environ["HVD_PENDING_CYCLE_TIME"] = "500"
        for kind in ("resnet50", "transformer"):
            local_fn, params0, stats0, x, y, grad_bytes = _step_bench_case(
                kind, hvd, n, args)
            # interleaved A/B/A/B passes, per-mode median over the
            # pooled per-step samples: both modes see the same load
            # drift (a 2-core CI box emulating 8 chips swings 30%
            # run-to-run; back-to-back mode blocks would charge the
            # drift to whichever mode ran second)
            base_t1, base_params, _ = _run_step_mode(
                hvd, local_fn, params0, stats0, x, y, 0, args.step_iters)
            if kind == "transformer":
                # step-1 params from fixed init: the GSPMD lane's
                # numerics reference (same model, init, data, optimizer)
                transformer_step1 = base_params
            bkt_t1, bkt_params, overlap = _run_step_mode(
                hvd, local_fn, params0, stats0, x, y,
                args.step_bucket_bytes, args.step_iters)
            base_t2, _, _ = _run_step_mode(
                hvd, local_fn, params0, stats0, x, y, 0, args.step_iters)
            bkt_t2, _, _ = _run_step_mode(
                hvd, local_fn, params0, stats0, x, y,
                args.step_bucket_bytes, args.step_iters)
            base_ms = float(np.median(base_t1 + base_t2))
            bkt_ms = float(np.median(bkt_t1 + bkt_t2))
            match = all(np.allclose(a, b)
                        for a, b in zip(base_params, bkt_params))
            # gradient-sync latency on the model's real grad tree
            from jax.sharding import NamedSharding, PartitionSpec as P
            mesh, axisn = hvd.mesh(), hvd.axis_name()
            g, _, _ = local_fn(
                jax.device_put(params0, NamedSharding(mesh, P())),
                jax.device_put(
                    jax.tree.map(lambda a: np.broadcast_to(
                        a[None], (n,) + a.shape), stats0),
                    NamedSharding(mesh, P(axisn))), x, y)
            grads_pr = jax.tree.map(lambda a: hvd.PerRank(a), g)
            sync_whole = _grad_sync_ms(hvd, grads_pr, 0)
            sync_bkt = _grad_sync_ms(hvd, grads_pr,
                                     args.step_bucket_bytes)
            from horovod_tpu.optim import _bucket_layout
            n_buckets = len(_bucket_layout(
                [int(np.prod(l.shape)) * 4
                 for l in jax.tree.leaves(params0)],
                args.step_bucket_bytes))
            models[kind] = {
                "whole_tree_ms_per_step": round(base_ms, 3),
                "bucketed_ms_per_step": round(bkt_ms, 3),
                "reduction_pct": round(
                    (base_ms - bkt_ms) / base_ms * 100.0, 1) if base_ms
                    else 0.0,
                "grad_sync_whole_ms": round(sync_whole, 3),
                "grad_sync_bucketed_ms": round(sync_bkt, 3),
                "grad_sync_reduction_pct": round(
                    (sync_whole - sync_bkt) / sync_whole * 100.0, 1)
                    if sync_whole else 0.0,
                "numerics_match": bool(match),
                "grad_bytes": grad_bytes,
                "buckets": n_buckets,
                "pipeline_overlap": overlap,
            }
        # GSPMD execution mode of the same TransformerLM (ISSUE 16):
        # cached-program fast path vs the retrace-per-call status quo
        models["gspmd"] = _gspmd_step_lane(hvd, n, args, transformer_step1)
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    print(json.dumps({
        "metric": "bucketed_backward_step_time_reduction",
        "value": models["resnet50"]["reduction_pct"],
        "unit": "% reduction in end-to-end eager DP step time, ResNet-50 "
                "(bucketed backward vs whole-tree allreduce)",
        "models": models,
        "pipeline_overlap": models["resnet50"]["pipeline_overlap"],
        "numerics_match": bool(all(m["numerics_match"]
                                   for m in models.values())),
        "baseline": "identical eager DP step with HVD_BUCKET_BYTES=0 "
                    "(whole gradient pytree as one post-backward grouped "
                    "allreduce — the pre-ISSUE-6 DistributedOptimizer "
                    "behavior)",
        "config": {"bucket_bytes": args.step_bucket_bytes,
                   "batch_per_chip": args.step_batch,
                   "image_size": args.step_image_size,
                   "seq_len": args.step_seq_len,
                   "iters": args.step_iters, "n_chips": n,
                   "backend": jax.devices()[0].platform},
    }))


def _gspmd_step_lane(hvd, n, args, eager_step1):
    """GSPMD execution mode of the step bench's TransformerLM: the whole
    train step — global-batch loss, backward, ``DistributedOptimizer``
    update riding the partitioner passthrough — is ONE jit program.
    Uncached builds a FRESH ``jax.jit`` wrapper per step (the
    retrace-per-call status quo MULTICHIP_r05 measured at 8.8 s); cached
    builds a fresh ``hvd.cached_step`` wrapper per step, which replays
    the recorded executable from the signature cache
    (ops/gspmd_cache.py). Numerics gate: step-1 params must match the
    eager-DP transformer lane (same init, data, and optimizer — eager's
    rank-averaged local-mean gradient IS the GSPMD global-mean
    gradient), and the cached step-1 params must match the uncached
    ones."""
    import optax
    import jax.numpy as jnp  # noqa: F811 - local for clarity
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models import TransformerConfig, TransformerLM
    from horovod_tpu.ops import dispatch_cache, gspmd_cache

    mesh, axis = hvd.mesh(), hvd.axis_name()
    batch, seq = args.step_batch, args.step_seq_len
    # keep in sync with the kind == "transformer" eager lane above
    cfg = TransformerConfig(vocab_size=32768, num_layers=2,
                            num_heads=8, d_model=256, d_ff=1024,
                            max_seq_len=seq, dtype=jnp.float32)
    model = TransformerLM(cfg)
    x_host = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(n * batch, seq))
    params0 = model.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, seq), jnp.int32))["params"]
    x = jax.device_put(x_host, NamedSharding(mesh, P(axis)))
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))

    def make_step():
        # re-executed per step: structurally-identical fresh closures,
        # the per-call retrace pattern the signature cache exists to kill
        def train_step(params, opt, x):
            def loss_fn(p):
                logits = model.apply({"params": p}, x)
                tgt = jax.nn.one_hot(x[:, 1:], cfg.vocab_size)
                return -jnp.mean(jnp.sum(
                    tgt * jax.nn.log_softmax(logits[:, :-1]), -1))
            loss, g = jax.value_and_grad(loss_fn)(params)
            updates, new_opt = tx.update(g, opt, params)
            return optax.apply_updates(params, updates), new_opt, loss
        return train_step

    params = jax.device_put(params0, NamedSharding(mesh, P()))
    opt = jax.device_put(tx.init(params0), NamedSharding(mesh, P()))
    dispatch_cache.reset()
    gspmd_cache.reset_stats()

    # uncached (status quo): fresh jit wrapper per step — every step pays
    # trace+lower+compile. 2 steps bound the lane's wall-time cost; the
    # per-step times are compile-dominated and low-variance.
    uncached, state, step1 = [], (params, opt), None
    for i in range(2):
        t0 = time.perf_counter()
        p2, o2, loss = jax.jit(make_step())(state[0], state[1], x)
        jax.block_until_ready(loss)
        uncached.append((time.perf_counter() - t0) * 1e3)
        if i == 0:
            step1 = [np.asarray(l) for l in jax.tree.leaves(p2)]
        state = (p2, o2)

    # cached: a fresh cached_step wrapper per step — the first records,
    # every later one must replay with zero retraces
    cached, state, retraces, cold_ms = [], (params, opt), 0, None
    cached_step1 = None
    for i in range(args.step_iters + 1):
        s = gspmd_cache.cached_step(make_step())
        t0 = time.perf_counter()
        p2, o2, loss = s(state[0], state[1], x)
        jax.block_until_ready(loss)
        ms = (time.perf_counter() - t0) * 1e3
        if i == 0:
            cold_ms = ms
            cached_step1 = [np.asarray(l) for l in jax.tree.leaves(p2)]
        else:
            cached.append(ms)
            retraces += s.traces
        state = (p2, o2)

    unc_ms = float(np.median(uncached))
    warm_ms = float(np.median(cached))
    hits = dispatch_cache.stats()["hits_by_source"].get("gspmd", 0)
    # fp reassociation across execution modes: tolerance, not bitwise
    match_eager = (len(step1) == len(eager_step1) and all(
        np.allclose(a, b, rtol=1e-4, atol=1e-6)
        for a, b in zip(step1, eager_step1)))
    match_cached = all(np.allclose(a, b)
                       for a, b in zip(step1, cached_step1))
    return {
        "uncached_ms_per_step": round(unc_ms, 3),
        "cached_warm_ms_per_step": round(warm_ms, 3),
        "cold_record_ms": round(cold_ms, 3),
        "reduction_pct": round((unc_ms - warm_ms) / unc_ms * 100.0, 1)
            if unc_ms else 0.0,
        "warm_retraces": retraces,
        "cache_hits": hits,
        "numerics_match": bool(match_eager and match_cached),
        "cache": gspmd_cache.stats(),
        "baseline": "fresh jax.jit wrapper per step (retrace-per-call "
                    "status quo; jit keys on function object identity)",
    }


def _capture_bench_case(hvd, n, args):
    """Dispatch-bound eager DP transformer step for --capture-bench: a
    deep-but-narrow TransformerLM whose gradient tree has MANY small
    leaves (the per-parameter regime MULTICHIP_r05 showed drowning in
    eager dispatch), local backward jitted with no collectives inside —
    gradient sync through DistributedOptimizer's bucketed stream is the
    path capture records and replays."""
    import jax.numpy as jnp  # noqa: F811 - local for clarity
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.models import TransformerConfig, TransformerLM

    mesh = hvd.mesh()
    axis = hvd.axis_name()
    batch = args.capture_batch
    seq = args.capture_seq_len
    cfg = TransformerConfig(vocab_size=args.capture_vocab,
                            num_layers=args.capture_layers,
                            num_heads=4, d_model=args.capture_dmodel,
                            d_ff=4 * args.capture_dmodel,
                            max_seq_len=seq, dtype=jnp.float32)
    model = TransformerLM(cfg)
    x_host = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(n * batch, seq))
    params0 = model.init(jax.random.PRNGKey(0),
                         jnp.zeros((1, seq), jnp.int32))["params"]

    def local(p, x_i):
        def loss_fn(p):
            logits = model.apply({"params": p}, x_i)
            tgt = jax.nn.one_hot(x_i[:, 1:], cfg.vocab_size)
            return -jnp.mean(jnp.sum(
                tgt * jax.nn.log_softmax(logits[:, :-1]), -1))
        loss, g = jax.value_and_grad(loss_fn)(p)
        return g, loss

    def shard_fn(p, x_i):
        g, loss = local(p, x_i)
        return jax.tree.map(lambda a: a[None], g), loss[None]

    local_fn = jax.jit(jax.shard_map(
        shard_fn, mesh=mesh, in_specs=(P(), P(axis)),
        out_specs=(P(axis), P(axis)), check_vma=False))
    x = jax.device_put(x_host, NamedSharding(mesh, P(axis)))
    grad_bytes = sum(int(np.prod(l.shape)) * 4
                     for l in jax.tree.leaves(params0))
    n_leaves = len(jax.tree.leaves(params0))
    return local_fn, params0, x, grad_bytes, n_leaves


def _run_capture_mode(hvd, local_fn, params0, x, capture_on, iters,
                      bucket_a, bucket_b):
    """One pass of the eager DP step with HVD_STEP_CAPTURE pinned:
    3 warmup steps (with capture on: record @1, compile the whole-step
    program + first replay @2), ``iters`` timed steps, then a FORCED
    DIVERGENCE phase — the bucket layout flips mid-run, so the replay
    must fall back to eager with correct results. The step is jitted
    backward → EAGER bucketed gradient sync (the
    ``allreduce_gradients_transform`` stage under test — the one part of
    an eager-DP step that cannot compile into the user's jit) → jitted
    optimizer update, so the measured delta is the dispatch machinery
    capture removes, not eager arithmetic around it. Returns (per-step
    times, final param leaves, capture stats)."""
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from horovod_tpu.ops import dispatch_cache, fusion_cycle

    os.environ["HVD_STEP_CAPTURE"] = "1" if capture_on else "0"
    os.environ["HVD_BUCKET_BYTES"] = str(bucket_a)
    dispatch_cache.reset()
    fusion_cycle.reset()
    mesh = hvd.mesh()

    params = jax.device_put(params0, NamedSharding(mesh, P()))
    sync_tx = hvd.allreduce_gradients_transform()
    sync_state = sync_tx.init(params0)
    inner = optax.sgd(0.01, momentum=0.9)
    opt = jax.device_put(inner.init(params0), NamedSharding(mesh, P()))
    state = {"params": params, "opt": opt}

    @jax.jit
    def apply_update(p, synced, o):
        updates, o = inner.update(synced, o, p)
        return optax.apply_updates(p, updates), o

    def one_step():
        g, loss = local_fn(state["params"], x)
        gt = jax.tree.map(lambda a: hvd.PerRank(a), g)
        synced, _ = sync_tx.update(gt, sync_state)
        state["params"], state["opt"] = apply_update(
            state["params"], synced, state["opt"])
        return loss

    for _ in range(3):
        one_step()
    jax.block_until_ready(jax.tree.leaves(state["params"]))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        one_step()
        jax.block_until_ready(jax.tree.leaves(state["params"]))
        times.append((time.perf_counter() - t0) * 1e3)
    # forced divergence: a different bucket layout changes the stream —
    # the captured plan must invalidate and the steps stay correct
    os.environ["HVD_BUCKET_BYTES"] = str(bucket_b)
    for _ in range(2):
        one_step()
    jax.block_until_ready(jax.tree.leaves(state["params"]))
    stats = hvd.fusion_stats()["capture"]
    leaves = [np.asarray(l) for l in jax.tree.leaves(state["params"])]
    return times, leaves, stats


def run_capture_bench(args) -> None:
    """Step capture-and-replay benchmark (CPU backend, virtual 8-chip
    mesh; ISSUE 8 tentpole): end-to-end eager DP transformer step —
    jitted local backward, bucketed DistributedOptimizer gradient sync —
    with ``HVD_STEP_CAPTURE`` off (the eager per-flush path: every
    bucket pays enqueue/flush/fuse/wire/split dispatch) vs on (step 1
    records the flush stream, later steps replay the whole step's
    collective work as ONE cached jitted program). Both modes end with a
    forced-divergence phase (bucket layout flips mid-run) proving the
    replay falls back to eager with correct results — the final params
    must match across modes INCLUDING the fallback steps. Prints ONE
    JSON line; ``value`` is the percent step-time reduction."""
    hvd, n = _microbench_mesh()
    knobs = ("HVD_STEP_CAPTURE", "HVD_BUCKET_BYTES", "HVD_CYCLE_TIME",
             "HVD_PENDING_CYCLE_TIME", "HVD_PIPELINE_THRESHOLD")
    prev = {k: os.environ.get(k) for k in knobs}
    try:
        # timer quiet: every flush comes from the deterministic "bucket"
        # trigger, so the recorded stream is stable run-to-run
        os.environ["HVD_CYCLE_TIME"] = "500"
        os.environ["HVD_PENDING_CYCLE_TIME"] = "500"
        # 1 MiB chunk threshold in BOTH modes: the eager flushes sit far
        # below it either way, but the captured program's step-fused
        # wire buffer crosses it — the multi-MiB monolithic reduction is
        # measurably slower than its chunked pieces on the CPU mesh
        # (the PR-3 finding, which step fusion would otherwise re-create)
        os.environ["HVD_PIPELINE_THRESHOLD"] = str(1 << 20)
        local_fn, params0, x, grad_bytes, n_leaves = _capture_bench_case(
            hvd, n, args)
        bucket_a = args.capture_bucket_bytes
        # 4x, not 2x: the deep-narrow default tree is dominated by
        # leaves that sit alone in their bucket at 2x too, which would
        # leave the layout (and so the stream) unchanged — no divergence
        bucket_b = 4 * bucket_a
        # interleaved A/B/A/B passes (same rationale as --step-bench:
        # both modes see the same CI load drift)
        eager_t1, eager_params, _ = _run_capture_mode(
            hvd, local_fn, params0, x, False, args.capture_iters,
            bucket_a, bucket_b)
        cap_t1, cap_params, cap_stats = _run_capture_mode(
            hvd, local_fn, params0, x, True, args.capture_iters,
            bucket_a, bucket_b)
        eager_t2, _, _ = _run_capture_mode(
            hvd, local_fn, params0, x, False, args.capture_iters,
            bucket_a, bucket_b)
        cap_t2, _, cap_stats2 = _run_capture_mode(
            hvd, local_fn, params0, x, True, args.capture_iters,
            bucket_a, bucket_b)
        eager_ms = float(np.median(eager_t1 + eager_t2))
        cap_ms = float(np.median(cap_t1 + cap_t2))
        match = all(np.allclose(a, b, atol=1e-5)
                    for a, b in zip(eager_params, cap_params))
        from horovod_tpu.ops import dispatch_cache
        cache_stats = dispatch_cache.stats()
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    reduction = (eager_ms - cap_ms) / eager_ms * 100.0 if eager_ms else 0.0
    # BOTH capture passes' lifecycle counters, summed AND per-pass — a
    # single pass's numbers would let the other pass regress silently
    replayed_by_pass = [int(cap_stats["replayed_steps"]),
                        int(cap_stats2["replayed_steps"])]
    fallbacks_by_pass = [int(cap_stats["fallbacks"]),
                         int(cap_stats2["fallbacks"])]
    print(json.dumps({
        "metric": "step_capture_replay_step_time_reduction",
        "value": round(reduction, 1),
        "unit": "% reduction in end-to-end eager DP step time, "
                "TransformerLM (captured whole-step replay vs the eager "
                "per-flush path)",
        "eager": {"ms_per_step": round(eager_ms, 3)},
        "captured": {"ms_per_step": round(cap_ms, 3),
                     "capture_pass1": cap_stats,
                     "capture": cap_stats2,
                     # each pass resets the dispatch cache, so these
                     # cover the FINAL capture pass only (cross-check
                     # them against capture/cap_stats2, not the sums)
                     "final_pass_hits_by_source":
                         cache_stats["hits_by_source"],
                     "final_pass_step_plan_builds":
                         cache_stats["step_builds"]},
        "numerics_match": bool(match),
        # the forced mid-run bucket-layout flip: the replay must have
        # fallen back (counted, in EVERY capture pass) and the final
        # params still matched
        "divergence": {"fallbacks": sum(fallbacks_by_pass),
                       "fallbacks_by_pass": fallbacks_by_pass,
                       "invalidations": int(cap_stats["invalidations"])
                       + int(cap_stats2["invalidations"]),
                       "numerics_match": bool(match)},
        "replayed_steps": sum(replayed_by_pass),
        "replayed_steps_by_pass": replayed_by_pass,
        "pipeline_overlap": _pipeline_summary(),
        "baseline": "identical eager DP step with HVD_STEP_CAPTURE=0 "
                    "(bucketed per-flush dispatch through the fusion "
                    "cycle + pipelined executor — the pre-capture "
                    "behavior)",
        "config": {"model": "TransformerLM",
                   "vocab": args.capture_vocab,
                   "layers": args.capture_layers,
                   "d_model": args.capture_dmodel,
                   "seq_len": args.capture_seq_len,
                   "batch_per_chip": args.capture_batch,
                   "bucket_bytes": bucket_a,
                   "divergence_bucket_bytes": bucket_b,
                   "grad_bytes": grad_bytes, "grad_leaves": n_leaves,
                   "iters": args.capture_iters, "n_chips": n,
                   "backend": jax.devices()[0].platform},
    }))


def run_protocol_child(args) -> None:
    """One world of the protocol-scalability sweep, in a FRESH process
    whose XLA_FLAGS seeded exactly ``--protocol-child`` virtual devices
    (the parent sets that; one interpreter cannot re-initialize the CPU
    backend at three device counts). Boots a loopback world, runs
    warm-up + steady-state negotiated steps, and prints ONE JSON line of
    per-rank registry deltas: KV ops, busy negotiation rounds, round
    latency, response-cache hits/misses (docs/negotiation.md)."""
    import jax.numpy as jnp  # noqa: F811 - local for clarity

    import horovod_tpu as hvd
    from horovod_tpu import metrics as _hvd_metrics

    n = args.protocol_child
    cached = str(args.protocol_cache).strip().lower() not in (
        "0", "false", "no", "off", "")
    extra = {
        "HVD_RESPONSE_CACHE": "1" if cached else "0",
        "HVD_HIER_NEGOTIATION": "auto" if args.protocol_hier == "auto"
        else args.protocol_hier,
        # Many rank threads time-slicing a 2-core CI box can starve a
        # watchdog thread past the 30 s production default (compile
        # storms; the flat lane's 16-way gather pressure) — that is CPU
        # starvation of the emulation, not a protocol death; give the
        # bench worlds a budget that scales with world size
        "HVD_HEALTH_TIMEOUT": str(max(60, 2 * n)),
    }
    tensors = args.protocol_tensors
    warmup, steady = args.protocol_warmup, args.protocol_steps

    def _delta_sum(delta, name):
        return sum(v for (nm, _labels), v in delta.items() if nm == name)

    def body():
        r = hvd.rank()

        def one_step(step):
            outs = []
            for i in range(tensors):
                outs.append(hvd.allreduce(
                    jnp.full((16,), float(r + 1), jnp.float32),
                    op=hvd.Sum, name=f"pb{i}"))
            return outs

        expect = float(sum(range(1, n + 1)))
        ok = True
        for s in range(warmup):
            outs = one_step(s)
            ok = ok and all(np.allclose(np.asarray(o), expect)
                            for o in outs)
        s0 = _hvd_metrics.snapshot()
        t0 = time.perf_counter()
        for s in range(steady):
            outs = one_step(warmup + s)
        wall = time.perf_counter() - t0
        s1 = _hvd_metrics.snapshot()
        ok = ok and all(np.allclose(np.asarray(o), expect) for o in outs)
        d = _hvd_metrics.delta(s1, s0)
        from horovod_tpu import engine_service
        svc = engine_service.get_service()
        return {
            "ok": bool(ok),
            "transport": type(svc.transport).__name__,
            "kv_ops": _delta_sum(d, "hvd_kv_ops_total"),
            "rounds": _delta_sum(d, "hvd_negotiation_rounds_total"),
            "round_s_sum": _delta_sum(d, "hvd_negotiation_round_seconds_sum"),
            "round_s_count": _delta_sum(
                d, "hvd_negotiation_round_seconds_count"),
            "rc_hits": _delta_sum(d, "hvd_response_cache_hits_total"),
            "rc_misses": _delta_sum(d, "hvd_response_cache_misses_total"),
            "steady_wall_s": wall,
        }

    with hvd.loopback.world(n, extra_env=extra) as w:
        per_rank = [o.result for o in w.run(body)]

    capture_parity = None
    if args.protocol_capture_parity:
        # ISSUE-13 acceptance: the world also completes capture-on/off
        # parity training steps (PR-8 negotiate_step replay at scale).
        def parity_world(capture):
            env = dict(extra, HVD_STEP_CAPTURE="1" if capture else "0")
            with hvd.loopback.world(n, extra_env=env) as w2:
                def pbody():
                    r = hvd.rank()
                    vals = []
                    for step in range(3):
                        hvd.step_marker()
                        hs = [hvd.allreduce_async(
                                  jnp.full((4,), float(r + i + step)),
                                  op=hvd.Sum, name=f"cp{i}")
                              for i in range(2)]
                        vals.append([np.asarray(h.result()).tobytes()
                                     for h in hs])
                    hvd.step_marker()
                    return vals
                return [o.result for o in w2.run(pbody)]
        on, off = parity_world(True), parity_world(False)
        capture_parity = bool(all(a == b for a, b in zip(on, off)))

    steps = steady * max(1, len(per_rank))
    kv_per_rank_step = [p["kv_ops"] / steady for p in per_rank]
    rounds = sum(p["rounds"] for p in per_rank)
    round_sum = sum(p["round_s_sum"] for p in per_rank)
    round_count = sum(p["round_s_count"] for p in per_rank)
    hits = sum(p["rc_hits"] for p in per_rank)
    misses = sum(p["rc_misses"] for p in per_rank)
    print(json.dumps({
        "world": n,
        "cached": cached,
        "transport": per_rank[0]["transport"],
        "numerics_match": all(p["ok"] for p in per_rank),
        "steady_steps": steady,
        "tensors_per_step": tensors,
        # per-rank KV ops per steady step: the curve the ci gate reads
        "kv_ops_per_rank_step_mean": round(
            float(np.mean(kv_per_rank_step)), 3),
        "kv_ops_per_rank_step_max": round(
            float(np.max(kv_per_rank_step)), 3),
        "busy_rounds_per_rank_step": round(
            rounds / (steps or 1), 4),
        "round_latency_ms_mean": round(
            (round_sum / round_count * 1e3) if round_count else 0.0, 3),
        "cache_hit_rate": round(hits / (hits + misses), 4)
        if (hits + misses) else None,
        "steady_ms_per_step": round(float(np.median(
            [p["steady_wall_s"] for p in per_rank])) / steady * 1e3, 2),
        "capture_parity": capture_parity,
    }), flush=True)


def run_protocol_bench(args) -> None:
    """Protocol-scalability sweep (ROADMAP; ISSUE 13 — BENCH_r13):
    negotiation round latency, per-rank KV ops/step, and response-cache
    hit rate vs world ∈ --protocol-worlds, each world in a FRESH
    subprocess with its own virtual-device count, in two modes: today's
    flat uncached protocol vs hierarchy + coordinator ResponseCache.
    Prints ONE JSON line; ``value`` is the cached-mode per-rank KV
    ops/step growth factor from the smallest to the largest world —
    ≈1.0 means steady-state control-plane cost is independent of world
    size (ci.sh gates this plus the flat-mode latency-growth bound)."""
    worlds = sorted({int(w) for w in args.protocol_worlds.split(",") if w})
    results: dict = {}
    skipped_flat: list = []
    for world in worlds:
        for mode, (cache, hier) in (("flat", ("0", "0")),
                                    ("cached", ("1", "auto"))):
            if mode == "flat" and world > args.protocol_flat_max:
                # no silent caps: flat rounds grow superlinearly on the
                # CPU emulation (world=16 already measures ~0.8 s/round
                # here); world=64 flat would run for hours. The cached
                # lane still covers it; the skip is recorded.
                skipped_flat.append(world)
                print(f"protocol-bench: skipping flat mode at world="
                      f"{world} (> --protocol-flat-max="
                      f"{args.protocol_flat_max})", file=sys.stderr)
                continue
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count={world}")
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--protocol-child", str(world),
                   "--protocol-cache", cache,
                   "--protocol-hier", hier,
                   "--protocol-steps", str(args.protocol_steps),
                   "--protocol-warmup", str(args.protocol_warmup),
                   "--protocol-tensors", str(args.protocol_tensors)]
            if cache == "1" and world >= 64:
                cmd.append("--protocol-capture-parity")
            proc = subprocess.run(
                cmd, env=env, capture_output=True, text=True,
                timeout=1800, cwd=os.path.dirname(os.path.abspath(__file__)))
            if proc.returncode != 0:
                raise RuntimeError(
                    f"protocol child world={world} mode={mode} failed:\n"
                    f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
            payload = json.loads(proc.stdout.strip().splitlines()[-1])
            results.setdefault(str(world), {})[mode] = payload
    lo, hi = str(worlds[0]), str(worlds[-1])
    cached_lo = results[lo]["cached"]["kv_ops_per_rank_step_mean"]
    cached_hi = results[hi]["cached"]["kv_ops_per_rank_step_mean"]
    # growth of steady-state per-rank control-plane traffic with world;
    # both sides are idle-heartbeat-only when the cache serves (busy
    # rounds are zero), so a tiny denominator means "already flat"
    kv_growth = (cached_hi / cached_lo) if cached_lo else 0.0
    flat_lat = {w: results[w]["flat"]["round_latency_ms_mean"]
                for w in results if "flat" in results[w]}
    hit_rates = {w: results[w]["cached"]["cache_hit_rate"]
                 for w in results}
    print(json.dumps({
        "metric": "protocol_scalability",
        "value": round(kv_growth, 3) if kv_growth is not None else None,
        "unit": f"cached per-rank KV-ops/step growth world {lo} -> {hi} "
                "(1.0 = flat in world)",
        "numerics_match": all(
            results[w][m]["numerics_match"]
            for w in results for m in results[w]),
        "worlds": results,
        "cache_hit_rate_by_world": hit_rates,
        "flat_round_latency_ms_by_world": flat_lat,
        "baseline": "flat KVTransport with HVD_RESPONSE_CACHE=0 at each "
                    "world (today's protocol)",
        "flat_mode_skipped_at": skipped_flat,
        "config": {"steps": args.protocol_steps,
                   "warmup": args.protocol_warmup,
                   "tensors_per_step": args.protocol_tensors,
                   "worlds": worlds,
                   "flat_max": args.protocol_flat_max},
    }))


def _pctl(samples, q):
    return float(np.percentile(np.asarray(samples), q)) * 1e3


def _latency_summary(samples) -> dict:
    return {"p50": round(_pctl(samples, 50), 3),
            "p95": round(_pctl(samples, 95), 3),
            "p99": round(_pctl(samples, 99), 3),
            "n": len(samples)}


def run_serve_bench(args) -> None:
    """Multi-tenant inference-serving QoS benchmark (CPU backend,
    virtual 8-chip mesh; ISSUE 12 tentpole): a continuous-batching
    serving driver over ``models/transformer.py`` issuing
    ``grouped_allreduce``/``allgather`` streams from two tenant process
    sets — a high-priority SERVE tenant (chips 0-3; per-request
    transformer grad-sync + activation gather, latency measured per
    request) and a low-priority BULK tenant (chips 4-7; a background
    thread keeping a deep async backlog that drives total pending bytes
    past ``HVD_FUSION_MAX_PENDING`` and its own unacked bytes past a
    shed quota). Phases interleave unloaded/loaded passes (box drift
    cancels) with QoS ON, then repeat the loaded passes with QoS OFF
    for the contrast. Prints ONE JSON line; ``value`` is the
    high-priority tenant's loaded p99 as a multiple of its unloaded p99
    with QoS on (ci.sh gates <= SERVE_P99_MULT, default 2.0), plus shed
    counters, backpressure evidence, slot shares, and a check that the
    ``hvd_qos_*`` series are live in the Prometheus scrape."""
    import threading

    import jax.numpy as jnp  # noqa: F811 - local for clarity

    from horovod_tpu import metrics as _hvd_metrics
    from horovod_tpu import qos as _hvd_qos
    from horovod_tpu.ops import dispatch_cache, fusion_cycle

    os.environ["HVD_DYNAMIC_PROCESS_SETS"] = "1"
    hvd, n = _microbench_mesh()
    assert n >= 8, f"serve bench needs the 8-chip CPU mesh, got {n}"

    knobs = ("HVD_QOS", "HVD_CYCLE_TIME", "HVD_PENDING_CYCLE_TIME",
             "HVD_FUSION_THRESHOLD", "HVD_FUSION_MAX_PENDING",
             "HVD_QOS_WINDOW")
    prev = {k: os.environ.get(k) for k in knobs}

    serve_ps = hvd.add_process_set([0, 1, 2, 3])
    bulk_ps = hvd.add_process_set([4, 5, 6, 7])
    m = 4  # tenant pset size

    # SERVE tenant payload: the real TransformerLM parameter tree (a
    # per-request gradient sync in a continuous-batching server) plus an
    # activation allgather — the grouped_allreduce/allgather stream the
    # ROADMAP names.
    from horovod_tpu.models import TransformerConfig, TransformerLM
    cfg = TransformerConfig(vocab_size=args.serve_vocab, num_layers=2,
                            num_heads=4, d_model=args.serve_dmodel,
                            d_ff=2 * args.serve_dmodel,
                            max_seq_len=32, dtype=jnp.float32)
    model = TransformerLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    leaves = [l for l in jax.tree.leaves(params)]
    serve_tensors = [
        hvd.per_rank([jnp.asarray(l) * float(r + 1) for r in range(m)],
                     process_set=serve_ps)
        for l in leaves]
    serve_bytes = sum(int(np.prod(l.shape)) * 4 for l in leaves)
    act = jnp.ones((args.serve_batch, cfg.d_model), jnp.float32)
    # numerics probe: sum over ranks of leaf * (r+1) = leaf * 10
    probe_leaf = np.asarray(leaves[0]) * float(sum(range(1, m + 1)))

    bulk_elems = args.serve_bulk_size // 4
    bulk_tensors = [
        hvd.per_rank([jnp.full((bulk_elems,), float(r + i + 1),
                               jnp.float32) for r in range(m)],
                     process_set=bulk_ps)
        for i in range(args.serve_bulk_tensors)]
    # bursts rotate over a few prescale factors = a few distinct fusion
    # queue signatures: bulk pending bytes then accumulate ACROSS queues
    # (each below the threshold) until the global
    # HVD_FUSION_MAX_PENDING backpressure drain fires — the "drives the
    # engine past the pending cap" evidence — while every drained batch
    # stays burst-sized, so the serve tenant's head-of-line blocking is
    # one small batch, not one giant backlog flush.
    _BULK_SIGNATURES = 6

    def serve_request(tag):
        t0 = time.perf_counter()
        h = hvd.grouped_allreduce_async(serve_tensors, op=hvd.Sum,
                                        process_set=serve_ps)
        hg = hvd.allgather_async(act, process_set=serve_ps)
        outs = hvd.synchronize(h)
        gathered = hvd.synchronize(hg)
        jax.block_until_ready([outs[0], gathered])
        return time.perf_counter() - t0, outs

    shed_seen = [0]
    bursts = [0]

    def bulk_flood(stop_evt):
        outstanding = []

        def reap(h):
            try:
                hvd.synchronize(h)
            except hvd.QosAdmissionError:
                shed_seen[0] += 1

        while not stop_evt.is_set():
            outstanding.append(hvd.grouped_allreduce_async(
                bulk_tensors, op=hvd.Sum, process_set=bulk_ps,
                prescale_factor=float(1 + bursts[0] % _BULK_SIGNATURES)))
            bursts[0] += 1
            if len(outstanding) >= args.serve_bulk_depth:
                reap(outstanding.pop(0))
            if args.serve_bulk_pace > 0:
                # paced arrivals (a continuous-batching producer, not a
                # GIL-starving busy loop); the engine still saturates —
                # the reap depth keeps a standing backlog
                time.sleep(args.serve_bulk_pace)
        for h in outstanding:
            reap(h)

    def measure_phase(requests, loaded):
        stop_evt = threading.Event()
        t = None
        if loaded:
            t = threading.Thread(target=bulk_flood, args=(stop_evt,),
                                 daemon=True)
            t.start()
            time.sleep(0.2)  # let the backlog build before measuring
        lat = []
        last_outs = None
        for i in range(requests):
            dt, last_outs = serve_request(i)
            lat.append(dt)
        if t is not None:
            stop_evt.set()
            t.join(timeout=120)
        return lat, last_outs

    def warm_bulk(seconds):
        """Run the flood solo so every bulk plan signature/composition
        compiles BEFORE measurement — a first-touch XLA compile under a
        measured serve request would charge a one-time cost to the
        steady-state tail."""
        stop_evt = threading.Event()
        t = threading.Thread(target=bulk_flood, args=(stop_evt,),
                             daemon=True)
        t.start()
        time.sleep(seconds)
        stop_evt.set()
        t.join(timeout=120)
        hvd.fusion_flush()

    try:
        # timer quiet (every flush from threshold/synchronize triggers);
        # small fusion threshold so the bulk backlog drains into many
        # modest batches (bounded head-of-line blocking); small global
        # pending cap so the bulk tenant demonstrably drives the engine
        # past HVD_FUSION_MAX_PENDING (backpressure flushes fire).
        burst_bytes = args.serve_bulk_tensors * args.serve_bulk_size
        os.environ["HVD_CYCLE_TIME"] = "500"
        os.environ["HVD_PENDING_CYCLE_TIME"] = "500"
        # threshold = 2 bursts: a signature's queue threshold-drains at a
        # STABLE two-burst composition (one plan per signature, warmed
        # below); pending still accumulates across the rotating
        # signatures to the global cap, so backpressure drains fire too
        # (those produce the 1-burst composition — also warmed).
        os.environ["HVD_FUSION_THRESHOLD"] = str(2 * burst_bytes)
        os.environ["HVD_FUSION_MAX_PENDING"] = str(
            (_BULK_SIGNATURES - 1) * burst_bytes)
        os.environ["HVD_QOS"] = "1"
        _hvd_qos.reset()
        # the serve tenant carries its own (generous, never-engaging)
        # block quota: a quota'd tenant gets the bounded non-stalling
        # backpressure drain when it crosses the global pending cap — a
        # quota-less tenant keeps the legacy producer-stalling
        # flush_all, which is exactly the tail-latency inversion this
        # workload measures (docs/qos.md "Interactions")
        hvd.set_qos(serve_ps, priority=1, weight=4.0,
                    pending_bytes_quota=64 << 20, policy="block")
        hvd.set_qos(bulk_ps, priority=0, weight=1.0,
                    pending_bytes_quota=args.serve_quota, policy="shed")

        dispatch_cache.reset()
        fusion_cycle.reset()
        # warm compile/plan caches for both tenants. Bulk flush batches
        # can carry 1, 2, or 3 bursts (threshold drains at 2; the
        # bounded backpressure drain can spare a 2-burst queue whose
        # next burst then threshold-drains at 3; 4+ is unreachable — a
        # 3-burst queue alone exceeds the half-cap drain target), so
        # compile every (signature x composition) plan OFF the clock: a
        # first-touch XLA compile (~200 ms) under a measured serve
        # request would otherwise charge a one-time cost to the
        # steady-state tail (observed as 10-20x p99 outliers).
        for sig in range(_BULK_SIGNATURES):
            for k in (1, 2, 3):
                hvd.grouped_allreduce(bulk_tensors * k, op=hvd.Sum,
                                      process_set=bulk_ps,
                                      prescale_factor=float(1 + sig))
        warm_bulk(1.0)
        _, warm_outs = measure_phase(2, loaded=False)
        numerics_match = np.allclose(np.asarray(warm_outs[0]), probe_leaf)

        # interleaved unloaded/loaded passes, QoS ON
        r = args.serve_requests
        unl1, _ = measure_phase(r, loaded=False)
        load1, outs1 = measure_phase(r, loaded=True)
        unl2, _ = measure_phase(r, loaded=False)
        load2, outs2 = measure_phase(r, loaded=True)
        numerics_match = bool(
            numerics_match
            and np.allclose(np.asarray(outs1[0]), probe_leaf)
            and np.allclose(np.asarray(outs2[0]), probe_leaf))
        stats_on = hvd.fusion_stats()
        scrape = _hvd_metrics.prometheus_text()
        qos_series_live = all(
            f"{name}{{" in scrape
            for name in ("hvd_qos_granted_bytes_total",
                         "hvd_qos_slot_share", "hvd_qos_shed_total"))
        wait_series = ("hvd_qos_admission_wait_seconds_count{" in scrape)
        sheds_on = int(sum(stats_on["qos"]["shed"].values()))
        shares = {t: round(v["share"], 3)
                  for t, v in stats_on["qos"].get("tenants", {}).items()}

        # contrast passes, QoS OFF (same load, single-tenant FIFO; the
        # dispatch-plan cache stays warm — plans are mode-independent,
        # so the contrast charges the scheduler, not recompiles)
        os.environ["HVD_QOS"] = "0"
        fusion_cycle.reset()
        measure_phase(2, loaded=False)
        off1, outs_off = measure_phase(r, loaded=True)
        off2, _ = measure_phase(r, loaded=True)
        numerics_match = bool(
            numerics_match
            and np.allclose(np.asarray(outs_off[0]), probe_leaf))
        stats_off = hvd.fusion_stats()
    finally:
        try:
            hvd.remove_process_set(serve_ps)
            hvd.remove_process_set(bulk_ps)
        except Exception:
            pass
        _hvd_qos.reset()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    unloaded = unl1 + unl2
    loaded_on = load1 + load2
    loaded_off = off1 + off2
    p99_unloaded = _pctl(unloaded, 99)
    p99_on = _pctl(loaded_on, 99)
    p99_off = _pctl(loaded_off, 99)
    ratio_on = p99_on / p99_unloaded if p99_unloaded else 0.0
    ratio_off = p99_off / p99_unloaded if p99_unloaded else 0.0
    backpressure = int(stats_on["flushes"]["backpressure"]
                       + stats_off["flushes"]["backpressure"])
    print(json.dumps({
        "metric": "serve_qos_p99_protection",
        "value": round(ratio_on, 3),
        "unit": "x multiple of the high-priority tenant's unloaded p99 "
                "grad-sync latency while the bulk tenant saturates the "
                "engine (QoS on; lower is better, 1.0 = full protection)",
        "qos_on": {
            "unloaded_ms": _latency_summary(unloaded),
            "loaded_ms": _latency_summary(loaded_on),
            "p99_protection_ratio": round(ratio_on, 3),
            "shed_total": sheds_on,
            "slot_share": shares,
        },
        "qos_off": {
            "loaded_ms": _latency_summary(loaded_off),
            "p99_protection_ratio": round(ratio_off, 3),
        },
        "qos_off_vs_on_p99": round(p99_off / p99_on, 2) if p99_on else None,
        "bulk": {"bursts": bursts[0], "sheds_observed": shed_seen[0],
                 "bytes_per_burst": args.serve_bulk_tensors
                 * args.serve_bulk_size,
                 "depth": args.serve_bulk_depth,
                 "quota": args.serve_quota},
        "backpressure_flushes": backpressure,
        "qos_series_in_scrape": bool(qos_series_live and wait_series),
        "numerics_match": bool(numerics_match),
        "baseline": "the same serve-request stream measured unloaded "
                    "(no bulk traffic) with QoS on; the qos_off block "
                    "repeats the loaded passes with HVD_QOS=0 (the "
                    "single-tenant FIFO pipeline) for contrast",
        "config": {"serve_pset": [0, 1, 2, 3], "bulk_pset": [4, 5, 6, 7],
                   "serve_grad_bytes": serve_bytes,
                   "serve_leaves": len(leaves),
                   "requests_per_phase": r,
                   "serve_class": {"priority": 1, "weight": 4.0},
                   "bulk_class": {"priority": 0, "weight": 1.0,
                                  "quota": args.serve_quota,
                                  "policy": "shed"},
                   "fusion_threshold": 2 * args.serve_bulk_tensors
                   * args.serve_bulk_size,
                   "fusion_max_pending": (_BULK_SIGNATURES - 1)
                   * args.serve_bulk_tensors * args.serve_bulk_size,
                   "bulk_signatures": _BULK_SIGNATURES,
                   "n_chips": n,
                   "backend": jax.devices()[0].platform},
    }))


def run_elastic_bench(args):
    """Elastic autoscaling as a measured scenario (docs/elastic.md;
    ISSUE 14 — BENCH_r14). Two loopback phases:

    * **churn** (world 4, all graceful, at_round-keyed so re-form
      latency cannot skew the schedule): preempt 4->3 (COLD: the shape
      was never shelved) -> scale-up 3->4 -> preempt 4->3 again (WARM:
      plans shelved at the grow, the coordinator ResponseCache re-armed
      after one digest round). Cold and warm are the IDENTICAL
      transition (same worlds, same graceful mechanism, same tensors),
      so ``value`` = warm/cold mean step time over the first
      post-re-form window isolates exactly the shape-keyed cache
      survival; a graceful preemption must also lose ZERO steps.
    * **abrupt** (world 3): a scheduled spot reclaim (remove) and a
      hard crash — the watchdog-detected paths — gate the recovery
      budget and the <=1-step crash loss.

    SLOs come off the rank-0 step log plus the ``hvd_elastic_*``
    registry (events by kind, re-form histogram, steps-lost counter,
    warm-reuse counter)."""
    from horovod_tpu.loopback.engine import _seed_xla_device_flags

    world_n = args.elastic_world
    _seed_xla_device_flags(world_n + 1)

    from horovod_tpu.utils import faults
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.loopback import elastic_run

    # Fast failure detection for the abrupt phase: the 30 s production
    # watchdog default would dominate every recovery measurement. The
    # timeout keeps headroom over GIL pauses (rank threads compiling XLA
    # programs on a small CI box can starve a beat thread for ~2 s).
    extra_env = {
        "HVD_RESPONSE_CACHE": "1",
        "HVD_HEALTH_INTERVAL": "0.3",
        "HVD_HEALTH_TIMEOUT": "4",
    }
    sleep_s = args.elastic_step_sleep
    n_tensors = args.elastic_tensors

    def phase(spec, hosts, np_, min_np, max_np, total_steps):
        os.environ["HVD_FAULT_SPEC"] = spec
        faults.refresh()
        disco = FixedHosts(dict(hosts))
        box = {}
        fired: list = []

        def body():
            import horovod_tpu as _hvd
            _hvd.init()
            state = _hvd.elastic.JaxState(step=0, log=[])

            @_hvd.elastic.run
            def train(state):
                from horovod_tpu import metrics as _metrics
                from horovod_tpu.utils import envs as _envs
                while state.step < total_steps:
                    out = _hvd.allreduce(jnp.ones(2), op=_hvd.Sum,
                                         name="w")
                    # several stable-named tensors per step: the
                    # post-re-form window measures real negotiation
                    # traffic (cold: wire rounds until the caches
                    # re-arm; warm: local serving), not the pacing sleep
                    p1 = 0.0
                    for j in range(n_tensors):
                        probe = _hvd.allreduce(
                            jnp.arange(8.0) + 1.0 + j, op=_hvd.Sum,
                            name=f"probe{j}")
                        if j == 0:
                            p1 = float(np.asarray(probe).reshape(-1)[1])
                    world = int(float(np.asarray(out).reshape(-1)[0]))
                    if _hvd.rank() == 0:
                        warm = {"plan": 0, "step": 0, "response": 0}
                        for li, v in \
                                _metrics.ELASTIC_WARM_REUSE.series(
                                    ).items():
                            k = dict(li).get("kind")
                            if k in warm:
                                warm[k] = int(v)
                        busy = int(sum(
                            _metrics.NEGOTIATION_ROUNDS.series(
                                ).values()))
                        state.log = state.log + [(
                            time.monotonic(), state.step, world, p1,
                            warm["plan"] + warm["step"],
                            warm["response"],
                            int(_metrics.ELASTIC_STEPS_LOST.value()),
                            _envs.get_int(_envs.ELASTIC_ROUND, -1),
                            busy)]
                    state.step += 1
                    time.sleep(sleep_s)
                    state.commit()
                return state.log

            log = train(state)
            if _hvd.rank() == 0:
                box["log"] = log
            return 0

        results, ok = elastic_run(
            body, np=np_, min_np=min_np, max_np=max_np,
            discovery=disco, timeout=180, extra_env=extra_env,
            churn_events=fired)
        return (box.get("log") or [], fired, ok,
                results.error_message)

    def transitions(log, window):
        evs = []
        for i in range(1, len(log)):
            (tp, sp, wp, _pp, warm_p, resp_p, lost_p, _rp,
             busy_p) = log[i - 1]
            (tc, sc, wc, _pc, warm_c, resp_c, lost_c, _rc,
             busy_c) = log[i]
            if wc == wp:
                continue
            # the phase: consecutive rows at the new world from here
            phase_dts = []
            for j in range(i, len(log) - 1):
                if log[j + 1][2] != wc:
                    break
                phase_dts.append(log[j + 1][0] - log[j][0])
            win = phase_dts[:window]
            # steady tail of the SAME phase (caches armed, serving
            # locally): normalizing the post-re-form window by it
            # cancels the box's phase-scale contention drift — a raw
            # wall-clock window swings ~1.5x run to run on a shared
            # 2-core box, drowning the re-arm signal
            tail = phase_dts[window:]
            steady = (sum(tail[-window:]) / len(tail[-window:])
                      if len(tail) >= 2 else None)
            post = (sum(win) / len(win)) if win else None
            # BUSY negotiation rounds spent over the same window: the
            # deterministic face of warm-vs-cold (a cold re-form pays
            # wire rounds per tensor until the caches re-arm; a warm
            # one serves locally after the digest round) — wall-clock
            # ratios on a shared CI box swing with contention, counts
            # do not
            wend = min(i + window, len(log) - 1)
            while wend > i and log[wend][2] != wc:
                wend -= 1
            window_busy = (log[wend][8] - busy_c) if wend > i else None
            evs.append({
                "from_world": wp, "to_world": wc, "at_step": sc,
                "recovery_s": round(tc - tp, 3),
                "steps_lost": lost_c - lost_p,
                "warm_plan_reuses": warm_c - warm_p,
                "warm_response_confirms": resp_c - resp_p,
                "post_step_ms": round(1e3 * post, 2) if post else None,
                "steady_step_ms": round(1e3 * steady, 2)
                if steady else None,
                "post_vs_steady": round(post / steady, 3)
                if post and steady else None,
                "window_busy_rounds": window_busy,
            })
        return evs

    def rows_of(log):
        if not log:
            return []
        t0 = log[0][0]
        return [[round(t - t0, 3), s, w, rd, warm, resp, lost, busy]
                for (t, s, w, _p, warm, resp, lost, rd, busy) in log]

    def numerics_of(log):
        return all(abs(p1 - 2.0 * world) < 1e-6
                   for (_t, _s, world, p1, *_rest) in log)

    t0 = time.monotonic()
    # Phase 1 — graceful churn, at_round-keyed: preempt(cold 4->3) ->
    # add(3->4, re-forms back into the shelved shape) -> preempt(warm
    # 4->3). Every event fires a fixed number of commits INSIDE the
    # round the previous event formed, so the schedule is immune to
    # re-form latency; all-graceful means no watchdog recovery variance
    # contaminates the warm/cold window comparison.
    e1, ek = args.elastic_e1, args.elastic_e2
    churn_spec = args.elastic_spec or (
        f"worker:preempt:rank={world_n - 1}:at_round=1:at_step={e1}"
        ":grace=30;"
        f"worker:add:rank=0:at_round=2:after={ek}:count=1;"
        f"worker:preempt:rank={world_n - 1}:at_round=3:after={ek}"
        ":grace=30")
    churn_hosts = {f"h{i}": 1 for i in range(world_n)}
    churn_log, churn_fired, churn_ok, churn_err = phase(
        churn_spec, churn_hosts, world_n, 2, world_n,
        args.elastic_steps)

    # Phase 2 — abrupt loss: ONE hard crash at a smaller world;
    # recovery runs the watchdog path (rank death -> silence detection
    # -> blacklist -> re-form -> restored last commit). A single event
    # keeps the phase deterministic — two interacting watchdog
    # recoveries (e.g. remove then crash) can overlap their re-forms on
    # a slow box; the abrupt-remove path keeps its coverage in
    # tests/test_elastic_churn.py.
    abrupt_spec = (
        f"worker:crash:rank=2:at_round=1:at_step={e1 + 4}")
    abrupt_log, abrupt_fired, abrupt_ok, abrupt_err = phase(
        abrupt_spec, {"a0": 1, "a1": 1, "a2": 1}, 3, 1, 3,
        args.elastic_abrupt_steps)
    elapsed = time.monotonic() - t0

    if not churn_ok or not churn_log or not abrupt_ok or not abrupt_log:
        print(json.dumps({
            "metric": "elastic_churn_warm_vs_cold",
            "value": None, "unit": "warm/cold re-form step-time ratio",
            "error": (churn_err or abrupt_err
                      or "no rank-0 log")[:500],
            "churn_ok": bool(churn_ok), "abrupt_ok": bool(abrupt_ok),
        }))
        return

    win = args.elastic_window
    churn_evs = transitions(churn_log, win)
    abrupt_evs = transitions(abrupt_log, win)
    shrinks = [e for e in churn_evs
               if (e["from_world"], e["to_world"])
               == (world_n, world_n - 1)]
    cold = shrinks[0] if shrinks else None
    warm_evt = shrinks[1] if len(shrinks) > 1 else None
    crash_evt = abrupt_evs[0] if abrupt_evs else None
    # The headline warm/cold metric is the DETERMINISTIC one: busy
    # wire rounds spent over the identical post-re-form window (cold
    # pays rounds per tensor until the caches re-arm; warm serves
    # locally after the digest round — measured 0 vs 14-17 on every
    # run). Wall-clock step-time ratios are recorded informationally:
    # on this repo's shared 2-core CI box they swing 0.6x-1.8x with
    # scheduler contention, drowning the very signal they would gate.
    ratio = None
    step_ratio = None
    if cold and warm_evt:
        wb = warm_evt.get("window_busy_rounds")
        cb = cold.get("window_busy_rounds")
        if wb is not None and cb:
            ratio = round(wb / cb, 3)
        if cold.get("post_step_ms") and warm_evt.get("post_step_ms"):
            step_ratio = round(
                warm_evt["post_step_ms"] / cold["post_step_ms"], 3)
    all_evs = churn_evs + abrupt_evs

    print(json.dumps({
        "metric": "elastic_churn_warm_vs_cold",
        "value": ratio,
        "unit": "warm/cold busy wire rounds over the first "
                f"{win}-step window after the two IDENTICAL graceful "
                f"{world_n}->{world_n - 1} re-forms (<1.0 = the "
                "shape-keyed shelve/restore left the warm re-form "
                "measurably less negotiation work; 0.0 = fully served "
                "locally). step_time_ratio carries the wall-clock "
                "twin, informational on a contended box",
        "step_time_ratio": step_ratio,
        "world": world_n,
        "schedule": {"churn": churn_spec, "abrupt": abrupt_spec},
        "events": all_evs,
        "churn_fired": [(e[1], e[2]) for e in churn_fired],
        "abrupt_fired": [(e[1], e[2]) for e in abrupt_fired],
        "cold_reform": cold,
        "warm_reform": warm_evt,
        "crash_reform": crash_evt,
        "recovery_s_max": max((e["recovery_s"] for e in all_evs),
                              default=None),
        "steps_total": len(churn_log) + len(abrupt_log),
        "elapsed_s": round(elapsed, 1),
        "numerics_ok": bool(numerics_of(churn_log)
                            and numerics_of(abrupt_log)),
        "fast_health": {"interval_s": 0.3, "timeout_s": 4.0},
        "rows": {"churn": rows_of(churn_log),
                 "abrupt": rows_of(abrupt_log)},
        "baseline": "the same run\'s FIRST graceful 4->3 re-form "
                    "(cold: the shape was never shelved) vs the SECOND "
                    "(warm: plans shelved at the grow, coordinator "
                    "cache re-armed after one digest round)",
    }))


def run_autoscale_bench(args):
    """Closed-loop elastic autoscaling end to end (docs/elastic.md
    "Autoscaler"; ISSUE 15 — BENCH_r15). Three loopback phases, none of
    them scripted — every membership change below is DECIDED by the
    ``HVD_AUTOSCALE`` policy from the metrics-registry sensors:

    * **load** (floor 2, ceiling 3): a fixed offered load shared by the
      world — heavy enough to breach the step-time SLO at the floor,
      under it at 3 — ramps in, breaches, then drops to idle. Gates:
      the policy scales UP within the latency budget of the breach
      starting (no script fired it), scales DOWN after sustained idle
      with ZERO steps lost (the PR-14 grace path), and the run ends at
      the floor.
    * **evict** (world 3): a fault-injected slow rank (``svc.exchange``
      delay, round-1-keyed so the replacement never inherits it) is
      blamed by the StragglerTracker windows, EVICTED through the grace
      window and replaced in the same re-form — the decision instrument
      names the planted rank, zero steps lost, warm shelves apply to
      the replacement's world.
    * **flap** (floor 2): an adversarial load alternating breach/idle
      faster than the hysteresis streaks — the oscillation bound: at
      most one membership decision over the whole phase (expected
      zero; +1 absorbs a pathological box stall aligning windows).
    """
    from horovod_tpu.loopback.engine import _seed_xla_device_flags

    _seed_xla_device_flags(4)

    from horovod_tpu.utils import faults
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.loopback import elastic_run

    base_env = {
        "HVD_HEALTH_INTERVAL": "0.3",
        "HVD_HEALTH_TIMEOUT": "6",
        "HVD_AUTOSCALE": "1",
        "HVD_AUTOSCALE_INTERVAL": "0.4",
        "HVD_AUTOSCALE_COOLDOWN": "3",
        "HVD_AUTOSCALE_GRACE": "30",
    }

    def phase(name, body_fn, hosts, np_, min_np, max_np, env, spec=None):
        os.environ.pop("HVD_FAULT_SPEC", None)
        if spec:
            os.environ["HVD_FAULT_SPEC"] = spec
        faults.refresh()
        disco = FixedHosts(dict(hosts))
        box, abox = {}, {}
        results, ok = elastic_run(
            body_fn(box), np=np_, min_np=min_np, max_np=max_np,
            discovery=disco, timeout=180,
            extra_env=dict(base_env, **env), autoscale_box=abox)
        return (box.get("log") or [], abox.get("decisions") or [], ok,
                results.error_message)

    def make_body(total, sleep_of, collect_warm=False):
        def factory(box):
            def body():
                import horovod_tpu as _hvd
                _hvd.init()
                state = _hvd.elastic.JaxState(step=0, log=[])

                @_hvd.elastic.run
                def train(state):
                    from horovod_tpu import metrics as _metrics
                    from horovod_tpu.ops import dispatch_cache
                    while state.step < total:
                        out = _hvd.allreduce(jnp.arange(4.0) + 1.0,
                                             op=_hvd.Sum, name="w")
                        # element 0 of sum(arange(4)+1) over `world`
                        # identical contributions is exactly world;
                        # element 1 is 2*world (the numerics check)
                        world = int(float(np.asarray(out).reshape(-1)[0]))
                        p1 = float(np.asarray(out).reshape(-1)[1])
                        if _hvd.rank() == 0:
                            state.log = state.log + [(
                                time.monotonic(), state.step, world, p1,
                                int(_metrics.ELASTIC_STEPS_LOST.value()),
                                dispatch_cache.stats()["warm_reuses"]
                                if collect_warm else 0)]
                        time.sleep(sleep_of(state.step, world))
                        state.step += 1
                        state.commit()
                    return state.log

                log = train(state)
                if _hvd.rank() == 0:
                    box["log"] = log
                return 0

            return body
        return factory

    def numerics_of(log):
        return all(abs(p1 - 2.0 * world) < 1e-6
                   for (_t, _s, world, p1, *_r) in log)

    t0 = time.monotonic()

    # -- phase 1: ramp -> breach -> idle ------------------------------------
    RAMP, BREACH_END, TOTAL = 8, 60, 230
    LOAD, LIGHT, SLO_MS = 0.60, 0.02, 220.0

    def load_sleep(step, world):
        if step < RAMP:
            return LIGHT
        if step < BREACH_END:
            return LOAD / max(world, 1)  # 300 ms at 2, 200 ms at 3
        return LIGHT

    load_log, load_dec, load_ok, load_err = phase(
        "load", make_body(TOTAL, load_sleep), {"l0": 1, "l1": 1},
        2, 2, 3, {
            "HVD_RESPONSE_CACHE": "1",
            "HVD_AUTOSCALE_SLO_MS": str(SLO_MS),
            "HVD_AUTOSCALE_BREACH_WINDOWS": "2",
            "HVD_AUTOSCALE_IDLE_WINDOWS": "3",
            "HVD_AUTOSCALE_IDLE_FACTOR": "0.6",
        })

    # -- phase 2: straggler eviction ----------------------------------------
    evict_log, evict_dec, evict_ok, evict_err = phase(
        "evict", make_body(46, lambda s, w: 0.0, collect_warm=True),
        {"e0": 1, "e1": 1, "e2": 1}, 3, 2, 4, {
            "HVD_RESPONSE_CACHE": "0",  # busy rounds feed the tracker
            "HVD_STRAGGLER_THRESHOLD": "0.15",
            "HVD_AUTOSCALE_EVICT_WINDOWS": "2",
        }, spec="svc.exchange:delay=0.4:rank=2:at_round=1")

    # -- phase 3: adversarial flapping --------------------------------------
    # Each load half must register as >= 1 policy window but flip before
    # the 3-window streak requirement: heavy = 3 steps x ~300 ms
    # (~2.2 windows at the 0.4 s interval), light = 25 steps x ~20 ms
    # (~1-2 windows with per-step overhead). Step-indexed, so the
    # pattern is rank-symmetric by construction.
    FLAP_HEAVY, FLAP_LIGHT = 3, 25
    FLAP_PERIOD = FLAP_HEAVY + FLAP_LIGHT
    FLAP_TOTAL = 4 * FLAP_PERIOD

    def flap_sleep(step, world):
        heavy = (step % FLAP_PERIOD) < FLAP_HEAVY
        return (LOAD / max(world, 1)) if heavy else LIGHT

    flap_log, flap_dec, flap_ok, flap_err = phase(
        "flap", make_body(FLAP_TOTAL, flap_sleep), {"f0": 1, "f1": 1},
        2, 2, 3, {
            "HVD_RESPONSE_CACHE": "1",
            "HVD_AUTOSCALE_SLO_MS": str(SLO_MS),
            "HVD_AUTOSCALE_BREACH_WINDOWS": "3",
            "HVD_AUTOSCALE_IDLE_WINDOWS": "3",
            "HVD_AUTOSCALE_IDLE_FACTOR": "0.6",
        })
    elapsed = time.monotonic() - t0

    err = None
    if not (load_ok and load_log):
        err = f"load phase: {load_err or 'no rank-0 log'}"
    elif not (evict_ok and evict_log):
        err = f"evict phase: {evict_err or 'no rank-0 log'}"
    elif not (flap_ok and flap_log):
        err = f"flap phase: {flap_err or 'no rank-0 log'}"
    if err is not None:
        print(json.dumps({"metric": "elastic_autoscale_closed_loop",
                          "value": None, "error": err[:500]}))
        return

    def acted(decisions):
        return [d for d in decisions if d["action"] != "hold"]

    # scale-up latency: breach start (first heavy step's wall time) to
    # the add decision — decisions and the step log share one monotonic
    # clock (driver and workers live in one loopback interpreter)
    breach_t0 = next((t for (t, s, *_r) in load_log if s >= RAMP), None)
    adds = [d for d in load_dec
            if d["action"] == "add" and d["reason"] == "slo-breach"]
    removes = [d for d in load_dec
               if d["action"] == "remove" and d["reason"] == "idle"]
    scale_up_latency_s = (round(adds[0]["t"] - breach_t0, 2)
                          if adds and breach_t0 is not None else None)
    load_worlds = [w for (_t, _s, w, *_r) in load_log]
    # steps lost across the idle scale-down (rank-0 counter deltas)
    down_lost = None
    for i in range(1, len(load_log)):
        if load_log[i][2] < load_log[i - 1][2]:
            down_lost = load_log[i][4] - load_log[i - 1][4]
    evicts = [d for d in evict_dec if d["action"] == "evict"]
    evict_worlds = [w for (_t, _s, w, *_r) in evict_log]

    print(json.dumps({
        "metric": "elastic_autoscale_closed_loop",
        "value": scale_up_latency_s,
        "unit": "seconds from SLO-breach load onset to the policy's "
                "un-scripted scale-up decision (sensor windows + "
                "hysteresis included); the other gates ride the "
                "phase blocks",
        "slo_ms": SLO_MS,
        "load": {
            "worlds": sorted(set(load_worlds)),
            "final_world": load_worlds[-1],
            "scale_up_latency_s": scale_up_latency_s,
            "scale_down_steps_lost": down_lost,
            "steps_lost_total": load_log[-1][4],
            "decisions": [(d["action"], d["reason"]) for d in
                          acted(load_dec)],
        },
        "evict": {
            "worlds": sorted(set(evict_worlds)),
            "final_world": evict_worlds[-1],
            "decisions": [(d["action"], d["reason"], d["rank"])
                          for d in acted(evict_dec)],
            "evicted_rank": evicts[0]["rank"] if evicts else None,
            "steps_lost_total": evict_log[-1][4],
            "warm_reuses": evict_log[-1][5],
        },
        "flap": {
            "decisions": [(d["action"], d["reason"]) for d in
                          acted(flap_dec)],
            "membership_decisions": len(acted(flap_dec)),
            "worlds": sorted(set(w for (_t, _s, w, *_r) in flap_log)),
        },
        "elapsed_s": round(elapsed, 1),
        "numerics_ok": bool(numerics_of(load_log)
                            and numerics_of(evict_log)
                            and numerics_of(flap_log)),
        "baseline": "PR-14 scripted churn: the identical membership "
                    "mechanics fired by a schedule; here every action "
                    "is policy-decided from the registry sensors",
    }))


def run_ckpt_recovery_bench(args):
    """Recovery-SLO lane for the checkpoint state plane
    (docs/checkpoint.md; BENCH_r18). For each model size, the IDENTICAL
    4->3->4 churn (graceful preempt, then a joiner that must be
    restored) runs twice:

    * **peer** — ``HVD_CKPT_PEER_RESTORE=1`` (the default): the joiner
      pulls per-rank shards from the survivors, so rank 0 serves only
      its ``1/len(survivors)`` share of the tree.
    * **broadcast** — ``HVD_CKPT_PEER_RESTORE=0``: the reference rank-0
      object broadcast, which re-syncs EVERY rank's full tree through
      rank 0.

    The gated numbers are the deterministic byte counters
    (``hvd_ckpt_restore_bytes_total{source=}``) measured as deltas from
    after the initial world formation (both lanes pay the same fresh
    broadcast there): peer must serve fewer rank-0 bytes than broadcast
    at EVERY size and its growth with model size must be sub-linear vs
    the broadcast baseline's. Wall-clock restore seconds ride along
    informationally — on a contended CI box they swing with scheduler
    noise. A final probe re-runs the smallest size with
    ``ckpt.shard_pull:error`` injected on every serve: the typed
    degraded path must fire exactly there and nowhere else."""
    from horovod_tpu.loopback.engine import _seed_xla_device_flags

    world_n = args.ckpt_recovery_world
    _seed_xla_device_flags(world_n + 1)

    from horovod_tpu.utils import faults
    from horovod_tpu.elastic.discovery import FixedHosts
    from horovod_tpu.loopback import elastic_run

    base_env = {
        "HVD_RESPONSE_CACHE": "1",
        "HVD_HEALTH_INTERVAL": "0.3",
        "HVD_HEALTH_TIMEOUT": "4",
        "HVD_METRICS": "1",
    }
    steps = args.ckpt_recovery_steps
    sleep_s = args.ckpt_recovery_step_sleep
    sizes = sorted(int(s) for s in
                   str(args.ckpt_recovery_sizes).split(","))
    churn_spec = (
        f"worker:preempt:rank={world_n - 1}:at_round=1:at_step=4"
        ":grace=30;"
        "worker:add:rank=0:at_round=2:after=4:count=1")

    # 8 equal param leaves: shards partition the FLATTENED tree by
    # leaf, so a single monolithic array would land whole in one
    # survivor's range and make rank 0's measured share degenerate
    n_parts = 8

    def lane(n_floats, peer_on, inject=None):
        spec = churn_spec + (";" + inject if inject else "")
        os.environ["HVD_FAULT_SPEC"] = spec
        faults.refresh()
        from horovod_tpu import metrics as _metrics
        _ckpt_insts = (_metrics.CKPT_RESTORE_BYTES,
                       _metrics.CKPT_PEER_SHARDS_PULLED,
                       _metrics.CKPT_DEGRADED_RESTORES,
                       _metrics.CKPT_RESTORE_SECONDS)
        # isolate this lane from earlier lanes in the same process
        _metrics.reset_all(*_ckpt_insts)
        box = {}

        def body():
            import horovod_tpu as _hvd
            from horovod_tpu import metrics as _metrics

            def tot(inst):
                # metric stores are per rank context (the joiner's pull
                # counters live on ITS thread's store): sum every store
                agg = {}
                for s in _metrics._all_stores():
                    for k, v in inst.series(s).items():
                        agg[k] = agg.get(k, 0) + v
                return agg

            _hvd.init()
            part = np.zeros(max(1, n_floats // n_parts), np.float32)
            state = _hvd.elastic.JaxState(
                params={f"w{i}": part.copy() for i in range(n_parts)},
                step=0, trans=0, lastw=0, p_ok=True)

            @_hvd.elastic.run
            def train(state):
                cap = steps * 4
                while state.step < cap and not (
                        state.step >= steps and state.trans >= 2):
                    if state.step == 0:
                        # founding ranks drop their formation-broadcast
                        # bytes from their OWN store so the lane counts
                        # only re-form restores; the joiner enters with
                        # the restored step > 0 and never resets — its
                        # pull counters are exactly what we measure
                        for inst in (
                                _metrics.CKPT_RESTORE_BYTES,
                                _metrics.CKPT_PEER_SHARDS_PULLED,
                                _metrics.CKPT_DEGRADED_RESTORES,
                                _metrics.CKPT_RESTORE_SECONDS):
                            inst.reset()
                    probe = _hvd.allreduce(jnp.arange(8.0) + 1.0,
                                           op=_hvd.Sum, name="probe")
                    flat = np.asarray(probe).reshape(-1)
                    world = int(round(float(flat[0])))
                    if abs(float(flat[1]) - 2.0 * world) > 1e-6:
                        state.p_ok = False
                    if state.lastw and world != state.lastw:
                        state.trans += 1
                    state.lastw = world
                    state.params = {
                        k: v + np.float32(1.0)
                        for k, v in state.params.items()}
                    state.step += 1
                    time.sleep(sleep_s)
                    state.commit()
                return state.step, state.trans, state.p_ok

            step_n, trans, p_ok = train(state)
            if _hvd.rank() == 0:
                srcs = {}
                for k, v in tot(_metrics.CKPT_RESTORE_BYTES).items():
                    src = dict(k).get("source", "?")
                    srcs[src] = srcs.get(src, 0) + int(v)
                rs_sum, rs_count = 0.0, 0
                for s in _metrics._all_stores():
                    for h in _metrics.CKPT_RESTORE_SECONDS.series(
                            s).values():
                        rs_sum += h.sum
                        rs_count += h.count
                box["result"] = {
                    "rank0_bytes": srcs.get("rank0", 0),
                    "peer_bytes": srcs.get("peer", 0),
                    "shards_pulled": int(sum(tot(
                        _metrics.CKPT_PEER_SHARDS_PULLED).values())),
                    "degraded": int(sum(tot(
                        _metrics.CKPT_DEGRADED_RESTORES).values())),
                    "steps": int(step_n),
                    "transitions": int(trans),
                    "numerics_ok": bool(p_ok),
                    "restore_s_sum": round(rs_sum, 3),
                    "restore_count": int(rs_count),
                }
            return 0

        env = dict(base_env)
        env["HVD_CKPT_PEER_RESTORE"] = "1" if peer_on else "0"
        results, ok = elastic_run(
            body, np=world_n, min_np=2, max_np=world_n,
            discovery=FixedHosts({f"h{i}": 1 for i in range(world_n)}),
            timeout=180, extra_env=env)
        if not ok or "result" not in box:
            return None, (results.error_message or "no rank-0 result")
        return box["result"], None

    t0 = time.monotonic()
    lanes = []
    err = None
    for n_floats in sizes:
        row = {"size": n_floats, "tree_bytes": n_floats * 4}
        for key, peer_on in (("peer", True), ("broadcast", False)):
            res, lane_err = lane(n_floats, peer_on)
            if lane_err:
                err = f"{key} lane at size {n_floats}: {lane_err}"
                break
            row[key] = res
        if err:
            break
        row["ratio"] = (
            round(row["peer"]["rank0_bytes"]
                  / row["broadcast"]["rank0_bytes"], 4)
            if row["broadcast"]["rank0_bytes"] else None)
        lanes.append(row)

    degraded_probe = None
    if err is None:
        degraded_probe, probe_err = lane(
            sizes[0], True, inject="ckpt.shard_pull:error")
        if probe_err:
            err = f"degraded probe: {probe_err}"
    elapsed = time.monotonic() - t0

    if err is not None:
        print(json.dumps({
            "metric": "ckpt_recovery_rank0_bytes",
            "value": None,
            "unit": "peer/broadcast rank-0 restore bytes at the "
                    "largest model size",
            "error": err[:500],
        }))
        return

    print(json.dumps({
        "metric": "ckpt_recovery_rank0_bytes",
        "value": lanes[-1]["ratio"],
        "unit": "peer/broadcast rank-0 restore bytes at the largest "
                "model size over the IDENTICAL 4->3->4 churn (<1.0 = "
                "the sharded peer restore serves measurably fewer "
                "bytes through rank 0 than the reference broadcast; "
                "~1/survivors = rank 0 serves only its own shard)",
        "world": world_n,
        "schedule": churn_spec,
        "sizes": sizes,
        "lanes": lanes,
        "degraded_probe": degraded_probe,
        "numerics_ok": bool(
            all(r[k]["numerics_ok"] for r in lanes
                for k in ("peer", "broadcast"))
            and degraded_probe["numerics_ok"]),
        "elapsed_s": round(elapsed, 1),
        "fast_health": {"interval_s": 0.3, "timeout_s": 4.0},
        "baseline": "the same churn with HVD_CKPT_PEER_RESTORE=0: the "
                    "reference rank-0 object broadcast re-syncing every "
                    "rank's full tree through rank 0",
    }))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=256,
                        help="per-chip batch size (256 measures ~1.5x the "
                             "throughput of 128 on v5e)")
    parser.add_argument("--num-iters", type=int, default=20,
                        help="total timed steps, rounded DOWN to a "
                             "multiple of --window (at least one window); "
                             "the JSON's timing.timed_steps reports the "
                             "actual count")
    parser.add_argument("--num-warmup", type=int, default=3,
                        help="untimed warmup steps (minimum 1: the first "
                             "step's loss is the training baseline and "
                             "compile must finish before timing)")
    parser.add_argument("--window", type=int, default=5,
                        help="steps per timed window (one device sync per "
                             "window; the chain serializes the steps)")
    parser.add_argument("--fp32", action="store_true",
                        help="compute in float32 instead of bfloat16")
    parser.add_argument("--remat", action="store_true",
                        help="rematerialize the forward in the backward "
                             "(jax.checkpoint): trades ~30%% more FLOPs "
                             "for activation memory, enabling per-chip "
                             "batches past HBM (e.g. 512 on v5e)")
    parser.add_argument("--dispatch-bench", action="store_true",
                        help="run the eager dispatch-overhead microbench "
                             "(CPU backend, no accelerator probe) instead "
                             "of the ResNet-50 training benchmark")
    parser.add_argument("--dispatch-iters", type=int, default=400,
                        help="timed calls per cache mode in "
                             "--dispatch-bench")
    parser.add_argument("--dispatch-tensors", type=int, default=16,
                        help="tensors per grouped_allreduce in "
                             "--dispatch-bench")
    parser.add_argument("--dispatch-size", type=int, default=1024,
                        help="per-rank elements per tensor in "
                             "--dispatch-bench")
    parser.add_argument("--cycle-bench", action="store_true",
                        help="run the cross-call fusion scheduler "
                             "microbench (CPU backend, no accelerator "
                             "probe): per-tensor allreduce_async loop, "
                             "scheduler on vs HVD_CYCLE_TIME=0")
    parser.add_argument("--cycle-iters", type=int, default=60,
                        help="timed submit+synchronize rounds per mode in "
                             "--cycle-bench")
    parser.add_argument("--cycle-tensors", type=int, default=64,
                        help="async allreduces per round in --cycle-bench")
    parser.add_argument("--cycle-size", type=int, default=4096,
                        help="bytes per tensor in --cycle-bench (default "
                             "4 KiB: the small-gradient regime the fusion "
                             "cycle exists for)")
    parser.add_argument("--pipeline-bench", action="store_true",
                        help="run the pipelined flush executor + chunk "
                             "pipeline microbench (CPU backend, no "
                             "accelerator probe): large-tensor "
                             "allreduce_async stream, "
                             "HVD_MAX_INFLIGHT_FLUSHES=2 + chunking vs "
                             "the synchronous executor")
    parser.add_argument("--pipeline-iters", type=int, default=20,
                        help="timed submit+synchronize rounds per mode in "
                             "--pipeline-bench")
    parser.add_argument("--pipeline-tensors", type=int, default=6,
                        help="async allreduces per round in "
                             "--pipeline-bench")
    parser.add_argument("--pipeline-size", type=int, default=4 * 1024 * 1024,
                        help="bytes per tensor in --pipeline-bench "
                             "(default 4 MiB: the large-tensor regime "
                             "chunk pipelining exists for)")
    parser.add_argument("--pipeline-chunks", type=int, default=4,
                        help="HVD_PIPELINE_CHUNKS for the pipelined mode "
                             "of --pipeline-bench")
    parser.add_argument("--overlap-bench", action="store_true",
                        help="run the flush-overlap microbench (CPU "
                             "backend, no accelerator probe): per-flush "
                             "allreduce_async stream, "
                             "HVD_MAX_INFLIGHT_FLUSHES=2 vs 1, gating "
                             "overlap_ratio > 0")
    parser.add_argument("--overlap-iters", type=int, default=12,
                        help="timed submit+synchronize rounds per mode in "
                             "--overlap-bench")
    parser.add_argument("--overlap-tensors", type=int, default=6,
                        help="async allreduces (= flushes) per round in "
                             "--overlap-bench")
    parser.add_argument("--overlap-size", type=int, default=1024 * 1024,
                        help="bytes per tensor in --overlap-bench "
                             "(default 1 MiB: big enough that a flush's "
                             "collective is still in flight when the next "
                             "flush dispatches)")
    parser.add_argument("--overlap-slots", type=int, default=2,
                        help="HVD_MAX_INFLIGHT_FLUSHES for the pipelined "
                             "mode of --overlap-bench")
    parser.add_argument("--step-bench", action="store_true",
                        help="run the end-to-end eager DP step-time "
                             "benchmark (CPU backend, no accelerator "
                             "probe): models/ ResNet-50 + TransformerLM, "
                             "bucketed backward (HVD_BUCKET_BYTES) vs "
                             "whole-tree allreduce")
    parser.add_argument("--step-iters", type=int, default=10,
                        help="timed steps per mode/model in --step-bench")
    parser.add_argument("--step-batch", type=int, default=2,
                        help="per-chip batch size in --step-bench")
    parser.add_argument("--step-image-size", type=int, default=16,
                        help="ResNet input resolution in --step-bench "
                             "(small: the bench isolates sync overlap, "
                             "not conv throughput)")
    parser.add_argument("--step-seq-len", type=int, default=64,
                        help="transformer sequence length in --step-bench")
    parser.add_argument("--step-bucket-bytes", type=int,
                        default=4 * 1024 * 1024,
                        help="HVD_BUCKET_BYTES for the bucketed mode of "
                             "--step-bench (default 4 MiB so the small "
                             "bench models split into several buckets; "
                             "production default is 64 MiB)")
    parser.add_argument("--capture-bench", action="store_true",
                        help="run the step capture-and-replay benchmark "
                             "(CPU backend, no accelerator probe): eager "
                             "DP TransformerLM step, HVD_STEP_CAPTURE on "
                             "(whole-step replay program) vs off (per-"
                             "flush dispatch), plus a forced-divergence "
                             "fallback check")
    parser.add_argument("--capture-iters", type=int, default=8,
                        help="timed steps per mode pass in --capture-bench")
    parser.add_argument("--capture-batch", type=int, default=1,
                        help="per-chip batch size in --capture-bench")
    parser.add_argument("--capture-seq-len", type=int, default=8,
                        help="sequence length in --capture-bench")
    parser.add_argument("--capture-vocab", type=int, default=1024,
                        help="vocab size in --capture-bench (small: the "
                             "bench isolates dispatch overhead, not "
                             "collective bandwidth)")
    parser.add_argument("--capture-layers", type=int, default=8,
                        help="transformer layers in --capture-bench "
                             "(deep-narrow: many small gradient leaves, "
                             "the per-parameter dispatch regime)")
    parser.add_argument("--capture-dmodel", type=int, default=64,
                        help="model width in --capture-bench")
    parser.add_argument("--capture-bucket-bytes", type=int, default=8192,
                        help="HVD_BUCKET_BYTES in --capture-bench (tiny: "
                             "~per-parameter dispatch, the reference's "
                             "per-layer hook stream; the divergence "
                             "phase quadruples it)")
    parser.add_argument("--metrics-bench", action="store_true",
                        help="run the metrics-registry overhead "
                             "microbench (CPU backend, no accelerator "
                             "probe): the --cycle-bench async stream with "
                             "the registry force-enabled vs disabled in "
                             "interleaved A/B chunks (docs/metrics.md "
                             "overhead contract; ci.sh gates <= 3%%)")
    parser.add_argument("--metrics-iters", type=int, default=60,
                        help="total timed rounds per mode in "
                             "--metrics-bench")
    parser.add_argument("--metrics-tensors", type=int, default=64,
                        help="async allreduces per round in "
                             "--metrics-bench")
    parser.add_argument("--metrics-size", type=int, default=4096,
                        help="bytes per tensor in --metrics-bench (small: "
                             "maximizes per-dispatch overhead visibility)")
    parser.add_argument("--conformance-bench", action="store_true",
                        help="run the conformance-recorder overhead "
                             "microbench (CPU backend, no accelerator "
                             "probe): the --metrics-bench async stream "
                             "with the recorder force-enabled vs disabled "
                             "in ABBA-interleaved chunks "
                             "(docs/conformance.md cost contract; ci.sh "
                             "gates <= 3%%)")
    parser.add_argument("--conformance-iters", type=int, default=60,
                        help="total timed rounds per mode in "
                             "--conformance-bench")
    parser.add_argument("--conformance-tensors", type=int, default=64,
                        help="async allreduces per round in "
                             "--conformance-bench")
    parser.add_argument("--conformance-size", type=int, default=4096,
                        help="bytes per tensor in --conformance-bench "
                             "(small: maximizes per-dispatch overhead "
                             "visibility)")
    parser.add_argument("--protocol-bench", action="store_true",
                        help="protocol-scalability sweep: negotiation "
                             "round latency + per-rank KV ops/step + "
                             "response-cache hit rate vs world, flat vs "
                             "hierarchy+cache (BENCH_r13; "
                             "docs/negotiation.md)")
    parser.add_argument("--protocol-worlds", default="4,16,64",
                        help="comma-separated loopback world sizes to "
                             "sweep (each in a fresh subprocess)")
    parser.add_argument("--protocol-child", type=int, default=0,
                        help="(internal) run ONE world of the sweep in "
                             "this process; XLA devices must already be "
                             "seeded by the parent")
    parser.add_argument("--protocol-cache", default="0",
                        help="(internal) HVD_RESPONSE_CACHE for the child")
    parser.add_argument("--protocol-hier", default="auto",
                        help="(internal) HVD_HIER_NEGOTIATION for the "
                             "child")
    parser.add_argument("--protocol-steps", type=int, default=8,
                        help="steady-state steps measured per world")
    parser.add_argument("--protocol-warmup", type=int, default=3,
                        help="warm-up steps before the measured window "
                             "(negotiate + confirm the response cache)")
    parser.add_argument("--protocol-tensors", type=int, default=4,
                        help="named negotiated allreduces per step")
    parser.add_argument("--protocol-flat-max", type=int, default=16,
                        help="largest world the FLAT (uncached) lane "
                             "runs at — its rounds grow superlinearly "
                             "on the CPU emulation; larger worlds run "
                             "the cached lane only (skip is recorded)")
    parser.add_argument("--protocol-capture-parity", action="store_true",
                        help="(internal) also run capture-on/off parity "
                             "steps in the child world")
    parser.add_argument("--elastic-bench", action="store_true",
                        help="elastic churn under load at a loopback "
                             "world (docs/elastic.md; BENCH_r14): a "
                             "seeded HVD_FAULT_SPEC schedule removes, "
                             "adds, preempts and crashes workers "
                             "mid-training and the recovery-time / "
                             "steps-lost / warm-vs-cold SLOs come off "
                             "the step log and hvd_elastic_* registry")
    parser.add_argument("--elastic-world", type=int, default=4,
                        help="starting loopback world size for "
                             "--elastic-bench")
    parser.add_argument("--elastic-steps", type=int, default=80,
                        help="committed training steps in --elastic-bench")
    parser.add_argument("--elastic-step-sleep", type=float, default=0.02,
                        help="seconds of compute stand-in per step in "
                             "--elastic-bench")
    parser.add_argument("--elastic-tensors", type=int, default=6,
                        help="stable-named allreduces per step in "
                             "--elastic-bench (negotiation traffic the "
                             "warm/cold window actually measures)")
    parser.add_argument("--elastic-window", type=int, default=6,
                        help="steps of the post-re-form window the "
                             "warm/cold step-time ratio averages over")
    parser.add_argument("--elastic-e1", type=int, default=6,
                        help="round-1 commit of each phase's first "
                             "event (cold preempt / abrupt remove)")
    parser.add_argument("--elastic-e2", type=int, default=8,
                        help="commits INSIDE each later round before "
                             "its event fires (at_round-keyed, so "
                             "re-form latency cannot skew the schedule)")
    parser.add_argument("--elastic-abrupt-steps", type=int, default=40,
                        help="committed steps in the abrupt-loss phase "
                             "of --elastic-bench")
    parser.add_argument("--elastic-spec", default=None,
                        help="HVD_FAULT_SPEC override for the CHURN "
                             "phase of --elastic-bench (replaces the "
                             "scheduled graceful default; the abrupt "
                             "phase keeps its own schedule)")
    parser.add_argument("--autoscale-bench", action="store_true",
                        help="closed-loop elastic autoscaling at a "
                             "loopback world (docs/elastic.md "
                             "'Autoscaler'; BENCH_r15): an un-scripted "
                             "SLO breach triggers a policy scale-up, "
                             "sustained idle a zero-loss scale-down, a "
                             "fault-injected slow rank is evicted and "
                             "named, and adversarial flapping produces "
                             "no oscillation")
    parser.add_argument("--ckpt-recovery-bench", action="store_true",
                        help="checkpoint state-plane recovery-SLO lane "
                             "(docs/checkpoint.md; BENCH_r18): the "
                             "identical 4->3->4 churn per model size "
                             "with peer-restore on vs the rank-0 "
                             "broadcast baseline, gated on the "
                             "deterministic hvd_ckpt_restore_bytes "
                             "counters, plus an injected "
                             "ckpt.shard_pull probe that must take the "
                             "typed degraded path")
    parser.add_argument("--ckpt-recovery-world", type=int, default=4,
                        help="starting loopback world size for "
                             "--ckpt-recovery-bench")
    parser.add_argument("--ckpt-recovery-steps", type=int, default=16,
                        help="committed steps per lane in "
                             "--ckpt-recovery-bench (the lane runs on "
                             "until both churn transitions were "
                             "observed, capped at 4x)")
    parser.add_argument("--ckpt-recovery-step-sleep", type=float,
                        default=0.02,
                        help="seconds of compute stand-in per step in "
                             "--ckpt-recovery-bench")
    parser.add_argument("--ckpt-recovery-sizes",
                        default="8192,65536,262144",
                        help="comma-separated float32 param counts (the "
                             "model-size sweep of --ckpt-recovery-bench"
                             "; default 32 KB / 256 KB / 1 MB trees)")
    parser.add_argument("--serve-bench", action="store_true",
                        help="run the multi-tenant inference-serving QoS "
                             "benchmark (CPU backend, no accelerator "
                             "probe): high-priority transformer serve "
                             "tenant vs a saturating bulk tenant, "
                             "HVD_QOS on vs off (docs/qos.md)")
    parser.add_argument("--serve-requests", type=int, default=25,
                        help="serve requests per measurement phase in "
                             "--serve-bench (4 phases QoS on, 2 off)")
    parser.add_argument("--serve-vocab", type=int, default=512,
                        help="transformer vocab in --serve-bench")
    parser.add_argument("--serve-dmodel", type=int, default=128,
                        help="transformer width in --serve-bench (sized "
                             "so a request's grad sync is ~1.6 MB — a "
                             "real per-request sync, not a microbench "
                             "ping)")
    parser.add_argument("--serve-batch", type=int, default=8,
                        help="activation rows allgathered per request in "
                             "--serve-bench")
    parser.add_argument("--serve-bulk-tensors", type=int, default=8,
                        help="tensors per bulk burst in --serve-bench")
    parser.add_argument("--serve-bulk-size", type=int, default=8 * 1024,
                        help="bytes per bulk tensor in --serve-bench "
                             "(small: bounded head-of-line blocking per "
                             "drained batch)")
    parser.add_argument("--serve-bulk-depth", type=int, default=8,
                        help="outstanding bulk bursts before the flood "
                             "thread reaps one in --serve-bench")
    parser.add_argument("--serve-bulk-pace", type=float, default=0.001,
                        help="seconds between bulk bursts in "
                             "--serve-bench (paced continuous-batching "
                             "arrivals; 0 = busy loop)")
    parser.add_argument("--serve-quota", type=int, default=256 * 1024,
                        help="bulk tenant pending-bytes shed quota in "
                             "--serve-bench (below depth x burst bytes "
                             "so a deep backlog sheds while the flood "
                             "continues)")
    parser.add_argument("--max-wait", type=float, default=600.0,
                        help="max seconds to wait for the accelerator "
                             "backend to answer a clean-exit probe before "
                             "giving up with an error artifact (0 disables "
                             "the wait; kept under typical driver kill "
                             "budgets so the artifact always lands)")
    args = parser.parse_args()

    if args.dispatch_bench:
        # host-side microbench: CPU mesh, no accelerator probe needed
        return run_dispatch_bench(args)
    if args.cycle_bench:
        return run_cycle_bench(args)
    if args.pipeline_bench:
        return run_pipeline_bench(args)
    if args.overlap_bench:
        return run_overlap_bench(args)
    if args.step_bench:
        return run_step_bench(args)
    if args.capture_bench:
        return run_capture_bench(args)
    if args.metrics_bench:
        return run_metrics_bench(args)
    if args.conformance_bench:
        return run_conformance_bench(args)
    if args.protocol_child:
        return run_protocol_child(args)
    if args.protocol_bench:
        return run_protocol_bench(args)
    if args.serve_bench:
        return run_serve_bench(args)
    if args.elastic_bench:
        return run_elastic_bench(args)
    if args.autoscale_bench:
        return run_autoscale_bench(args)
    if args.ckpt_recovery_bench:
        return run_ckpt_recovery_bench(args)

    if args.max_wait > 0 and not wait_for_backend(args.max_wait):
        # Claiming the backend ourselves now would either fail identically
        # or hang unboundedly (losing the artifact to a driver kill, the
        # round-4 failure mode); surface a parseable error artifact instead.
        raise RuntimeError(
            f"accelerator backend did not answer a clean-exit probe within "
            f"--max-wait={args.max_wait:.0f}s; refusing to claim it")
    hvd.init()
    n = hvd.size()
    axis = hvd.axis_name()
    mesh = hvd.mesh()

    model = ResNet50(num_classes=1000,
                     dtype=jnp.float32 if args.fp32 else jnp.bfloat16,
                     axis_name=axis)
    rng = jax.random.PRNGKey(0)
    images_host = np.random.default_rng(0).standard_normal(
        (n * args.batch_size, 224, 224, 3), dtype=np.float32)
    labels_host = np.random.default_rng(1).integers(
        0, 1000, size=(n * args.batch_size,))

    variables = model.init(rng, jnp.zeros((1, 224, 224, 3), jnp.float32),
                           train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]

    # Reference benchmark uses plain SGD lr=0.01; gradient sync through the
    # framework's DistributedOptimizer (allreduce average over the mesh).
    tx = hvd.DistributedOptimizer(optax.sgd(0.01, momentum=0.9))
    opt_state = tx.init(params)

    def train_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            apply = lambda p, x: model.apply(
                {"params": p, "batch_stats": batch_stats}, x, train=True,
                mutable=["batch_stats"])
            if args.remat:
                apply = jax.checkpoint(apply)
            logits, mutated = apply(p, images)
            one_hot = jax.nn.one_hot(labels, 1000)
            loss = -jnp.mean(jnp.sum(one_hot * jax.nn.log_softmax(logits), -1))
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_stats, new_opt, loss

    sharded_step = jax.jit(
        jax.shard_map(
            train_step, mesh=mesh,
            in_specs=(P(), P(), P(), P(axis), P(axis)),
            out_specs=(P(), P(), P(), P()),
            check_vma=False),
        # donate state buffers: the update writes in place instead of
        # copying params+momentum+stats every step (r3 VERDICT weak #2)
        donate_argnums=(0, 1, 2))

    data_sharding = NamedSharding(mesh, P(axis))
    images = jax.device_put(images_host, data_sharding)
    labels = jax.device_put(labels_host, data_sharding)
    params = jax.device_put(params, NamedSharding(mesh, P()))
    batch_stats = jax.device_put(batch_stats, NamedSharding(mesh, P()))
    opt_state = jax.device_put(opt_state, NamedSharding(mesh, P()))

    # Per-device program FLOPs from the compiler itself; falls back to the
    # analytic ResNet-50 count when cost_analysis isn't available. The
    # compiled executable is reused for the run so the program compiles once.
    flops_per_step_per_chip = None
    try:
        compiled = sharded_step.lower(
            params, batch_stats, opt_state, images, labels).compile()
        sharded_step = compiled
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if ca and ca.get("flops"):
            flops_per_step_per_chip = float(ca["flops"])
    except Exception:
        pass
    flops_source = "xla_cost_analysis"
    if not flops_per_step_per_chip:
        flops_per_step_per_chip = (
            ANALYTIC_RESNET50_TRAIN_FLOPS_PER_IMAGE * args.batch_size)
        flops_source = "analytic"
    if args.remat and flops_source == "xla_cost_analysis":
        # MFU convention counts MODEL flops only; the compiled program's
        # count includes the rematerialized forward, which would inflate
        # utilization by the recompute fraction. Keep the executed count
        # as a diagnostic, score MFU from the analytic model count.
        flops_executed = flops_per_step_per_chip
        flops_per_step_per_chip = (
            ANALYTIC_RESNET50_TRAIN_FLOPS_PER_IMAGE * args.batch_size)
        flops_source = "analytic_model_flops_remat_excluded"
    elif args.remat:
        # analytic fallback under remat: we have no executed count at all
        # (the analytic number is MODEL flops); don't mislabel it
        flops_executed = None
    else:
        flops_executed = flops_per_step_per_chip

    first_loss = None
    for _ in range(max(1, args.num_warmup)):
        params, batch_stats, opt_state, loss = sharded_step(
            params, batch_stats, opt_state, images, labels)
        if first_loss is None:
            first_loss = float(loss)  # step-1 loss: the training baseline
    jax.block_until_ready(loss)  # warmup fully complete before timing

    # Timed windows of chained steps: the data dependency (step i+1
    # consumes step i's params/stats/opt_state) serializes the steps on
    # device, so window_time/window = true steady-state step time; the
    # single D2H sync per window keeps the host round trip out of the
    # measurement (see module docstring).
    window = max(1, args.window)
    n_windows = max(1, args.num_iters // window)
    window_means = []
    last_loss = first_loss
    for _ in range(n_windows):
        start = time.perf_counter()
        for _ in range(window):
            params, batch_stats, opt_state, loss = sharded_step(
                params, batch_stats, opt_state, images, labels)
        last_loss = float(loss)  # D2H: the whole chained window finished
        window_means.append((time.perf_counter() - start) / window)

    times = np.asarray(window_means)
    mean_t = float(times.mean())
    img_per_sec_per_chip = args.batch_size / mean_t
    losses = [first_loss, last_loss]

    peak = chip_peak_flops(jax.devices()[0])
    mfu = None
    if peak:
        mfu = round(flops_per_step_per_chip / mean_t / peak, 4)

    print(json.dumps({
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": round(img_per_sec_per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec_per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 3),
        "baseline": BASELINE_DESC,
        "mfu": mfu,
        "flops_per_step_per_chip": flops_per_step_per_chip,
        "flops_executed_per_step_per_chip": flops_executed,
        "flops_source": flops_source,
        "remat": bool(args.remat),
        "chip_peak_bf16_flops": peak,
        "device_kind": jax.devices()[0].device_kind,
        "n_chips": n,
        "batch_size_per_chip": args.batch_size,
        "step_time_ms": {
            "mean": round(mean_t * 1e3, 3),
            "p50": round(float(np.percentile(times, 50)) * 1e3, 3),
            "min": round(float(times.min()) * 1e3, 3),
            "max": round(float(times.max()) * 1e3, 3),
        },
        "timing": {"method": "chained_windows", "window": window,
                   "n_windows": n_windows,
                   "timed_steps": window * n_windows},
        "pipeline_overlap": _pipeline_summary(),
        "loss_first": round(losses[0], 4),
        "loss_last": round(losses[-1], 4),
        "loss_decreased": bool(losses[-1] < losses[0]),
    }))


def _error_artifact(message: str) -> None:
    print(json.dumps({
        "metric": "resnet50_synthetic_images_per_sec_per_chip",
        "value": None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "error": message[:500],
    }), flush=True)


def _on_sigterm(signum, frame):
    # A supervising driver's kill budget must not erase the evidence:
    # emit the parseable error artifact before dying (SIGKILL is
    # unsurvivable, but drivers normally TERM first).
    _error_artifact(f"terminated by signal {signum} while running/waiting")
    sys.exit(1)


if __name__ == "__main__":
    import signal
    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        main()
    except Exception as e:  # noqa: BLE001 — the artifact must always parse
        # Even a dead backend yields a parseable artifact that says exactly
        # what failed (round 4's rc=1 with empty stdout lost the evidence).
        import traceback
        traceback.print_exc()
        _error_artifact(f"{type(e).__name__}: {e}")
        sys.exit(1)  # the artifact parses, but the run did fail
