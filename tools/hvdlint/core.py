"""hvdlint core: project model, pragma handling, call-graph machinery.

The passes (``tools/hvdlint/passes/``) are AST analyses over a
:class:`Project` — the ``horovod_tpu`` package plus the repo docs. This
module owns everything they share:

* :class:`SourceFile` — parsed module + the inline pragma index
  (``# hvdlint: disable=<pass>[,<pass>]`` suppresses findings anchored on
  that line — or on the next line when the pragma sits on a comment-only
  line; ``# hvdlint: <marker>`` attaches a named marker, e.g.
  ``timer-boundary``, that passes can query).
* :class:`Project` — the file set, path helpers, and the cross-module
  function index (:class:`FuncInfo`) with import-aware call resolution:
  bare names resolve within the module, ``self.method`` within the
  enclosing class, ``alias.func`` through the module's (or function's)
  relative imports. Unresolvable calls (methods on runtime objects,
  stdlib) resolve to ``None`` — the analyses are deliberately
  conservative about what they claim to know.
* :func:`dotted_name` / :func:`parent_map` — small AST helpers.

Everything is stdlib-only (``ast``): the suite must run in CI before any
heavyweight import.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path

PRAGMA_RE = re.compile(r"#\s*hvdlint:\s*([A-Za-z0-9=,_*-]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, anchored at ``path:line``."""

    pass_name: str
    path: str  # project-root-relative
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_name}] {self.message}"


class SourceFile:
    def __init__(self, root: Path, rel: str):
        self.rel = rel
        self.path = root / rel
        self.text = self.path.read_text()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=str(self.path))
        # line -> set of pass names (or "*") suppressed on that line
        self.suppressions: dict[int, set[str]] = {}
        # marker name -> set of line numbers carrying it
        self.markers: dict[str, set[int]] = {}
        self._index_pragmas()

    def _index_pragmas(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = PRAGMA_RE.search(line)
            if not m:
                continue
            body = m.group(1)
            targets = [i]
            if line.strip().startswith("#"):
                targets.append(i + 1)  # comment-only pragma covers the
                # next line too
            if body.startswith("disable="):
                names = {p.strip() for p in body[len("disable="):].split(",")
                         if p.strip()}
                for t in targets:
                    self.suppressions.setdefault(t, set()).update(names)
            else:
                for t in targets:
                    self.markers.setdefault(body.strip(), set()).update([t])

    def suppressed(self, pass_name: str, line: int) -> bool:
        names = self.suppressions.get(line)
        return bool(names) and (pass_name in names or "*" in names)

    def has_marker(self, marker: str, line: int) -> bool:
        """Marker on ``line`` or within the two preceding lines (so a
        marker comment above a ``def`` also covers it)."""
        lines = self.markers.get(marker)
        if not lines:
            return False
        return any(ln in lines for ln in range(line - 2, line + 1))


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def parent_map(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


@dataclasses.dataclass
class FuncInfo:
    """One function/method in the project index."""

    file: "SourceFile"
    node: ast.FunctionDef
    qualname: str  # e.g. "FusionScheduler._loop" or "flush_all"
    class_name: str | None

    @property
    def key(self) -> tuple[str, str]:
        return (self.file.rel, self.qualname)


class Project:
    """The analyzed tree: package sources + docs, with a function index
    and import-aware call resolution."""

    def __init__(self, root, package_rel: str = "horovod_tpu",
                 knobs_doc_rel: str = "docs/knobs.md"):
        self.root = Path(root)
        self.package_rel = package_rel.rstrip("/")
        self.knobs_doc_rel = knobs_doc_rel
        self.files: list[SourceFile] = []
        self.by_rel: dict[str, SourceFile] = {}
        pkg = self.root / self.package_rel
        for path in sorted(pkg.rglob("*.py")):
            rel = path.relative_to(self.root).as_posix()
            if "__pycache__" in rel:
                continue
            sf = SourceFile(self.root, rel)
            self.files.append(sf)
            self.by_rel[rel] = sf
        self._funcs: dict[tuple[str, str], FuncInfo] = {}
        self._by_name: dict[str, dict[str, list[FuncInfo]]] = {}
        self._index_functions()
        self._imports: dict[str, dict[str, str]] = {
            f.rel: self._module_imports(f) for f in self.files}

    # -- file helpers ------------------------------------------------------

    def package_file(self, tail: str) -> SourceFile | None:
        return self.by_rel.get(f"{self.package_rel}/{tail}")

    def ops_files(self) -> list[SourceFile]:
        prefix = f"{self.package_rel}/ops/"
        return [f for f in self.files if f.rel.startswith(prefix)]

    def knobs_doc_path(self) -> Path:
        return self.root / self.knobs_doc_rel

    # -- function index ----------------------------------------------------

    def _index_functions(self) -> None:
        for sf in self.files:
            for node in sf.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add_func(sf, node, node.name, None)
                elif isinstance(node, ast.ClassDef):
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            self._add_func(sf, sub,
                                           f"{node.name}.{sub.name}",
                                           node.name)

    def _add_func(self, sf, node, qualname, class_name) -> None:
        info = FuncInfo(sf, node, qualname, class_name)
        self._funcs[info.key] = info
        self._by_name.setdefault(sf.rel, {}).setdefault(
            node.name, []).append(info)

    def func(self, rel: str, qualname: str) -> FuncInfo | None:
        return self._funcs.get((rel, qualname))

    def functions(self):
        return self._funcs.values()

    # -- import resolution -------------------------------------------------

    def _resolve_relative(self, rel: str, level: int, module: str | None,
                          leaf: str) -> str | None:
        """Map ``from <dots><module> import <leaf>`` in file ``rel`` to a
        project-relative module path, or None for out-of-project."""
        parts = rel.split("/")[:-1]  # package dirs of the importing file
        if level > 0:
            if level - 1 > len(parts):
                return None
            base = parts[:len(parts) - (level - 1)]
        else:
            base = []
        target = base + (module.split(".") if module else []) + [leaf]
        cand = "/".join(target) + ".py"
        if cand in self.by_rel:
            return cand
        cand = "/".join(target) + "/__init__.py"
        return cand if cand in self.by_rel else None

    def _collect_imports(self, rel: str, body) -> dict[str, str]:
        aliases: dict[str, str] = {}
        for node in body:
            if isinstance(node, ast.ImportFrom):
                for n in node.names:
                    target = self._resolve_relative(
                        rel, node.level, node.module, n.name)
                    if target is not None:
                        aliases[n.asname or n.name] = target
        return aliases

    def _module_imports(self, sf: SourceFile) -> dict[str, str]:
        return self._collect_imports(sf.rel, sf.tree.body)

    def func_imports(self, info: FuncInfo) -> dict[str, str]:
        """Module-level imports overlaid with the function's own
        (function-level imports are the project idiom for cycle-prone
        modules, e.g. ``from . import collectives as _coll``)."""
        aliases = dict(self._imports[info.file.rel])
        for node in ast.walk(info.node):
            if isinstance(node, ast.ImportFrom):
                for n in node.names:
                    target = self._resolve_relative(
                        info.file.rel, node.level, node.module, n.name)
                    if target is not None:
                        aliases[n.asname or n.name] = target
        return aliases

    # -- call resolution ---------------------------------------------------

    def resolve_call(self, info: FuncInfo, call: ast.Call,
                     aliases: dict[str, str] | None = None
                     ) -> FuncInfo | None:
        """Resolve a call inside ``info`` to a project function:
        ``name()`` -> same module; ``self.m()`` -> method of the enclosing
        class (else the module's only class defining ``m``);
        ``alias.f()`` -> imported module's ``f``. None when unknown."""
        if aliases is None:
            aliases = self.func_imports(info)
        func = call.func
        if isinstance(func, ast.Name):
            cands = self._by_name.get(info.file.rel, {}).get(func.id, [])
            for c in cands:
                if c.class_name is None:
                    return c
            return None
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        if isinstance(base, ast.Name) and base.id in ("self", "cls"):
            if info.class_name is not None:
                hit = self.func(info.file.rel,
                                f"{info.class_name}.{func.attr}")
                if hit is not None:
                    return hit
            cands = [c for c in self._by_name.get(info.file.rel, {})
                     .get(func.attr, []) if c.class_name is not None]
            return cands[0] if len(cands) == 1 else None
        if isinstance(base, ast.Name) and base.id in aliases:
            target = aliases[base.id]
            cands = self._by_name.get(target, {}).get(func.attr, [])
            for c in cands:
                if c.class_name is None:
                    return c
        return None
