"""CLI: ``python -m tools.hvdlint <package-dir> [--root DIR]...
[--pass NAME]... [--json] [--list]``.

Exit status: 0 = clean, 1 = findings, 2 = usage error. The package
argument is the path to the analyzed package relative to the repo root
(normally ``horovod_tpu``); docs are resolved as ``docs/knobs.md``
next to it. ``--root DIR`` adds further package roots to the same run
(repeatable) — ``python -m tools.hvdlint horovod_tpu --root tools``
lints the analysis tools with the suite that lints the runtime;
registry round-trip checks that need runtime files (``utils/envs.py``,
``metrics.py``, ``conformance.py``) skip themselves for roots that
lack them. ``--json`` replaces the line-per-finding output with one
JSON document — ``{file, line, pass, message}`` records plus per-pass
wall-time — for structured consumers (the ci.sh annotation step).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import PASSES, Project, run_all


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvdlint",
        description="project-invariant static analysis for horovod_tpu "
                    "(docs/static_analysis.md)")
    parser.add_argument("package", nargs="?", default="horovod_tpu",
                        help="package directory to analyze "
                             "(default: horovod_tpu)")
    parser.add_argument("--root", dest="roots", action="append",
                        metavar="DIR",
                        help="additional package root to analyze in the "
                             "same run (repeatable), e.g. --root tools")
    parser.add_argument("--pass", dest="passes", action="append",
                        metavar="NAME",
                        help="run only this pass (repeatable); "
                             "default: all")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON report (findings + per-pass "
                             "timing) instead of text lines")
    parser.add_argument("--list", action="store_true",
                        help="list available passes and exit")
    args = parser.parse_args(argv)

    if args.list:
        for name, fn in PASSES.items():
            first = (fn.__module__ and
                     sys.modules[fn.__module__].__doc__ or "").strip()
            print(f"{name}: {first.splitlines()[0] if first else ''}")
        return 0

    packages = [args.package] + list(args.roots or [])
    findings = []
    timings: dict[str, float] = {}
    n_files = 0
    for package in packages:
        pkg = Path(package)
        root = pkg.parent if pkg.parent != Path("") else Path(".")
        if not (root / pkg.name).is_dir():
            print(f"hvdlint: package directory {package!r} not found",
                  file=sys.stderr)
            return 2
        project = Project(root, package_rel=pkg.name)
        try:
            findings.extend(run_all(project, args.passes,
                                    timings=timings))
        except KeyError as e:
            print(f"hvdlint: {e.args[0]}", file=sys.stderr)
            return 2
        n_files += len(project.files)
    ran_names = args.passes if args.passes else list(PASSES)
    if args.json:
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.pass_name] = counts.get(f.pass_name, 0) + 1
        print(json.dumps({
            "tool": "hvdlint",
            "package": " ".join(packages),
            "files": n_files,
            "clean": not findings,
            "findings": [{"file": f.path, "line": f.line,
                          "pass": f.pass_name, "message": f.message}
                         for f in findings],
            "passes": [{"name": name,
                        "seconds": round(timings.get(name, 0.0), 4),
                        "findings": counts.get(name, 0)}
                       for name in ran_names],
        }, indent=2))
        return 1 if findings else 0
    for f in findings:
        print(f.format())
    if findings:
        print(f"hvdlint: {len(findings)} finding(s) across {n_files} "
              "file(s)", file=sys.stderr)
        return 1
    print(f"hvdlint: clean ({n_files} files; passes: "
          f"{', '.join(ran_names)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
