"""hvdlint: project-invariant static analysis for the horovod_tpu runtime.

Nine AST passes, each encoding a concurrency/determinism invariant that
a PR introduced and a future regression would break silently (a hang or
a cross-rank divergence, not a test failure):

===============  ============================================================
pass             invariant (provenance)
===============  ============================================================
issue-lock       compiled eager collectives enqueue under the program-issue
                 lock (PR 3's reproduced XLA rendezvous deadlock)
lock-order       the static ``with``-nesting graph across modules is acyclic
                 (the documented one-way ``_mu -> _exec_cv`` convention)
timer-purity     nothing reachable from the cycle timer reads wall clocks,
                 randomness, negotiates, or iterates sets into batch order
                 (PR 2-3's rank-deterministic flush composition contract)
knob-registry    every HVD_* knob flows through utils/envs.py and
                 round-trips with docs/knobs.md + the autotune tunables
                 (PR 1's override-epoch invalidation)
donation         a donated buffer is never referenced after the donating
                 call (PR 1's aliasing rules; CPU tests cannot catch this)
silent-except    broad ``except: pass`` handlers and hand-rolled
                 ``time.sleep`` retry loops route failures around the
                 failure domain (PR 5's retry/watchdog machinery)
rank-divergence  collective submissions (``*_async`` / ``flush_entry`` /
                 ``negotiate_many_submit``) never sit under rank-local
                 control flow — rank comparisons, wall-clock tests, set
                 iteration (the mismatched-collective hang class)
metrics-registry telemetry flows through the unified metrics registry
                 (``horovod_tpu/metrics.py``): no ad-hoc module-level
                 counters/dicts, instrument catalog centralized there,
                 and the catalog round-trips with docs/metrics.md
trace-coverage   every conformance decision point registered in
                 ``conformance.SITES`` contains its ``record(...)``
                 call, no ``record()`` sits outside the registry, and
                 the registry round-trips with docs/conformance.md
===============  ============================================================

Run ``python -m tools.hvdlint horovod_tpu`` from the repo root (add
``--root tools`` to lint the analysis tools themselves); findings
print as ``file:line: [pass] message`` and a nonzero exit fails CI
(``--json`` emits the same findings as structured records plus per-pass
timing). Suppress a vetted exception inline with
``# hvdlint: disable=<pass>``. Full catalog: docs/static_analysis.md.
The dynamic counterparts are the ``HVD_DEBUG_INVARIANTS=1`` runtime
checker (``horovod_tpu/utils/invariants.py``) and the
``HVD_SCHED_CHECK=1`` schedule-exploration checker (``tools/hvdsched``,
docs/schedule_checker.md).
"""

from __future__ import annotations

import time

from .core import Finding, Project
from .passes import PASSES

__all__ = ["Finding", "PASSES", "Project", "run_all"]


def run_all(project: Project, only: list[str] | None = None,
            timings: dict[str, float] | None = None) -> list[Finding]:
    """Run the suite (or the ``only`` subset) and return deduplicated
    findings in (path, line) order. When ``timings`` is a dict, each
    pass's wall seconds are accumulated into it (the ``--json`` report
    and the CI annotation step surface them)."""
    names = list(PASSES) if not only else only
    out: list[Finding] = []
    seen: set[Finding] = set()
    for name in names:
        if name not in PASSES:
            raise KeyError(f"unknown hvdlint pass {name!r}; "
                           f"available: {', '.join(PASSES)}")
        t0 = time.perf_counter()
        for f in PASSES[name](project):
            if f not in seen:
                seen.add(f)
                out.append(f)
        if timings is not None:
            timings[name] = (timings.get(name, 0.0)
                             + time.perf_counter() - t0)
    out.sort(key=lambda f: (f.path, f.line, f.pass_name))
    return out
