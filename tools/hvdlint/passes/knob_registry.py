"""knob-registry pass: every ``HVD_*`` knob flows through ``utils/envs.py``
and round-trips with ``docs/knobs.md``.

Invariant (PR 1, ``utils/envs.py``): runtime knob overrides (the
autotuner) sit *under* the environment, and the override **epoch** is what
flushes derived state — the dispatch-plan cache keys fusion layouts off
knob values and compares epochs instead of re-reading knobs per call. A
direct ``os.environ`` read therefore doesn't just bypass the
HVD_/HOROVOD_ prefix fallback: it reads a knob the override epoch knows
nothing about, so tuned values and epoch-driven invalidation silently
never apply to it. This pass enforces:

1. **no direct reads**: ``os.environ.get``/``[]``/``setdefault`` and
   ``os.getenv`` with an ``HVD_``/``HOROVOD_`` key are illegal outside
   ``utils/envs.py`` (writes — seeding worker environments — are the
   launcher contract and stay legal);
2. **no literal knob names**: ``envs.get*(...)`` must take a registry
   constant (``envs.FUSION_THRESHOLD``), not a string literal — literals
   are invisible to the inventory and typo-prone;
3. **doc round-trip**: the registry inventory (module-level constants in
   ``utils/envs.py``) and the ``HVD_*`` names in ``docs/knobs.md`` must
   match exactly in both directions;
4. **autotune tunables**: every ``Tunable(...)`` knob argument in
   ``autotune.py`` must be a registry constant.
"""

from __future__ import annotations

import ast
import re

from ..core import Finding, Project, dotted_name

NAME = "knob-registry"

_PREFIXES = ("HVD_", "HOROVOD_")
_GETTERS = ("get", "get_bool", "get_int", "get_float", "require", "set_env")
_DOC_TOKEN = re.compile(r"HVD_([A-Z][A-Z0-9_]*)")


def _literal_env_key(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value.startswith(_PREFIXES):
            return node.value
    return None


def _check_direct_reads(project: Project, findings: list[Finding]) -> None:
    envs_rel = f"{project.package_rel}/utils/envs.py"
    for sf in project.files:
        if sf.rel == envs_rel:
            continue
        for node in ast.walk(sf.tree):
            key, line = None, None
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name in ("os.environ.get", "os.getenv",
                            "os.environ.setdefault") and node.args:
                    key = _literal_env_key(node.args[0])
                    line = node.lineno
            elif (isinstance(node, ast.Subscript)
                  and isinstance(node.ctx, ast.Load)
                  and dotted_name(node.value) == "os.environ"):
                key = _literal_env_key(node.slice)
                line = node.lineno
            if key is None or sf.suppressed(NAME, line):
                continue
            findings.append(Finding(
                NAME, sf.rel, line,
                f"direct os.environ read of {key!r} bypasses the "
                "utils/envs.py registry: the HOROVOD_ fallback, runtime "
                "overrides, and the override-epoch invalidation (which "
                "flushes the dispatch cache) never apply to it — use "
                f"envs.{key.split('_', 1)[1]} through envs.get*/require"))


def _inventory(project: Project) -> dict[str, int]:
    """Registry inventory: knob name -> envs.py line, from module-level
    ``NAME = \"KNOB\"`` constants."""
    envs_sf = project.package_file("utils/envs.py")
    inv: dict[str, int] = {}
    if envs_sf is None:
        return inv
    for node in envs_sf.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not (isinstance(target, ast.Name) and target.id.isupper()
                and not target.id.startswith("_")
                and not target.id.startswith("DEFAULT_")):
            continue
        if (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and re.fullmatch(r"[A-Z][A-Z0-9_]*", node.value.value)):
            inv[node.value.value] = node.lineno
    return inv


def _module_literals(sf) -> dict[str, str]:
    """Module-level ``NAME = \"literal\"`` bindings (indirection that
    would otherwise hide a knob name from the inventory check)."""
    out: dict[str, str] = {}
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            out[node.targets[0].id] = node.value.value
    return out


def _check_getter_args(project: Project, inventory: dict,
                       findings: list[Finding]) -> None:
    envs_rel = f"{project.package_rel}/utils/envs.py"
    for sf in project.files:
        if sf.rel == envs_rel:
            continue
        literals = _module_literals(sf)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr in _GETTERS
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "envs"):
                continue
            arg = node.args[0]
            if sf.suppressed(NAME, node.lineno):
                continue
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                findings.append(Finding(
                    NAME, sf.rel, node.lineno,
                    f"envs.{func.attr}({arg.value!r}): knob names must be "
                    "registry constants (envs.<NAME>), not string "
                    "literals — literals are invisible to the knob "
                    "inventory and the docs round-trip"))
            elif (isinstance(arg, ast.Name) and arg.id in literals
                  and literals[arg.id] not in inventory):
                findings.append(Finding(
                    NAME, sf.rel, node.lineno,
                    f"envs.{func.attr}({arg.id}): resolves to "
                    f"{literals[arg.id]!r}, which is not registered in "
                    "utils/envs.py — add the constant there so the "
                    "inventory and docs/knobs.md stay in lockstep"))


def _check_doc_roundtrip(project: Project, inventory: dict,
                         findings: list[Finding]) -> None:
    doc_path = project.knobs_doc_path()
    if not doc_path.exists():
        findings.append(Finding(
            NAME, project.knobs_doc_rel, 1,
            "docs/knobs.md is missing — the knob inventory must be "
            "documented"))
        return
    doc_names: dict[str, int] = {}
    for i, line in enumerate(doc_path.read_text().splitlines(), start=1):
        for m in _DOC_TOKEN.finditer(line):
            doc_names.setdefault(m.group(1), i)
    envs_rel = f"{project.package_rel}/utils/envs.py"
    for knob, line in sorted(inventory.items()):
        if knob not in doc_names:
            findings.append(Finding(
                NAME, envs_rel, line,
                f"knob HVD_{knob} is registered in utils/envs.py but "
                f"undocumented in {project.knobs_doc_rel}"))
    for knob, line in sorted(doc_names.items()):
        if knob not in inventory:
            findings.append(Finding(
                NAME, project.knobs_doc_rel, line,
                f"{project.knobs_doc_rel} documents HVD_{knob}, which is "
                "not in the utils/envs.py registry (stale entry, or the "
                "constant is missing)"))


def _check_tunables(project: Project, findings: list[Finding]) -> None:
    sf = project.package_file("autotune.py")
    if sf is None:
        return
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "Tunable" and node.args):
            continue
        arg = node.args[0]
        ok = (isinstance(arg, ast.Attribute)
              and isinstance(arg.value, ast.Name)
              and arg.value.id == "envs")
        if not ok and not sf.suppressed(NAME, node.lineno):
            findings.append(Finding(
                NAME, sf.rel, node.lineno,
                "Tunable(...) knob must be an envs.<NAME> registry "
                "constant — the tuner's overrides are keyed by registry "
                "name, and env-pinning (is_env_fixed) only sees "
                "registered knobs"))


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    inventory = _inventory(project)
    _check_direct_reads(project, findings)
    _check_getter_args(project, inventory, findings)
    # The doc round-trip only makes sense against the runtime's registry
    # — a root without utils/envs.py (linting tools/) has no inventory
    # to diff the docs against.
    if project.package_file("utils/envs.py") is not None:
        _check_doc_roundtrip(project, inventory, findings)
    _check_tunables(project, findings)
    return findings
