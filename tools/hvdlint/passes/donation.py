"""donation pass: a donated buffer must not be referenced after the
donating call.

Invariant (PR 1/PR 3, docs/dispatch_cache.md + docs/pipeline.md): the
dispatch plans donate wire buffers into their compiled programs
(``donate_argnums``) so the collective reuses their HBM; ping-pong
chunk plans additionally donate scratch sets. On backends with real
donation, reading a donated array after the call returns garbage (JAX
raises only under ``jax_debug_nans``-style checks, and the CPU backend
silently ignores donation — so a TPU-only corruption can pass every CPU
test). This pass flags any local name passed in a donated argument
position and then read later in the same function without rebinding.

What counts as a donating callable:

* a direct ``jax.jit(..., donate_argnums=...)`` result — including one
  wrapped in ``issue_serialized(...)`` — bound to a local name or called
  immediately (donated positions parsed from a literal tuple; a dynamic
  expression conservatively donates every position);
* results of the project's donating-program constructors, tracked
  through tuple unpacking: ``_plan_fused_programs`` (wire stage donates
  all args), ``_plan_step_programs`` (the step capture-and-replay twin:
  its wire stage donates the whole step's fused buffers), and
  ``_plan_chunked_programs`` (fuse stage donates arg 0 under
  ping-pong; the per-piece programs donate arg 0), and the
  ``donate=``-parameterized cached constructors
  (``_eager_grouped_allreduce_fn`` / ``_eager_grouped_broadcast_fn`` /
  ``_eager_hier_grouped_allreduce_fn`` / ``_piece_allreduce_fn``, plus
  the GSPMD cached-step compiler ``_gspmd_step_program`` — params and
  opt-state handed to a donated cached-step position belong to the
  step).

Bindings flow into nested functions (the plan ``execute`` closures are
where the calls actually happen). The analysis is line-ordered (control
flow is ignored), so a vetted re-use in a loop can be suppressed with
``# hvdlint: disable=donation``.
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, dotted_name

NAME = "donation"

ALL = "ALL"

_WRAPPERS = ("issue_serialized", "_issue_serialized", "functools.lru_cache")

# constructor name -> donation spec of its result(s):
#   a spec is ALL, a frozenset of positions, None (never donates), or
#   "donate-kwarg" (positions derive from the call's donate= argument);
#   a tuple of specs describes tuple-unpacked results; ("list", spec)
#   marks a list of callables each with `spec`.
CONSTRUCTORS = {
    "_plan_fused_programs": (None, ALL),
    # step capture (ops/step_capture.py): (fuse_fn, wire_fn) where the
    # wire stage takes every record's fused buffers donated
    "_plan_step_programs": (None, ALL),
    "_plan_chunked_programs": (frozenset({0}), ("list", frozenset({0})),
                               None, None),
    "_eager_grouped_allreduce_fn": "donate-kwarg",
    "_eager_grouped_broadcast_fn": "donate-kwarg",
    "_eager_hier_grouped_allreduce_fn": "donate-kwarg",
    "_piece_allreduce_fn": "donate-kwarg",
    # GSPMD cached-step compiler (ops/gspmd_cache.py): the result is the
    # compiled step executable; its donate= positions are the derived
    # params/opt-state mask (dynamic at every call site -> ALL)
    "_gspmd_step_program": "donate-kwarg",
}


def _unwrap(call: ast.Call) -> ast.Call:
    """Peel issue_serialized(...) wrappers off a constructor expression."""
    while True:
        name = dotted_name(call.func)
        if (name is not None and name.split(".")[-1] in
                [w.split(".")[-1] for w in _WRAPPERS]
                and len(call.args) == 1
                and isinstance(call.args[0], ast.Call)):
            call = call.args[0]
            continue
        return call


def _jit_donated_positions(call: ast.Call):
    """Donated positions of a ``jax.jit(...)`` call, or None when it does
    not donate."""
    if dotted_name(call.func) not in ("jax.jit", "jit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        val = kw.value
        if isinstance(val, ast.Tuple) and all(
                isinstance(e, ast.Constant) for e in val.elts):
            pos = frozenset(e.value for e in val.elts)
            return pos or None
        if isinstance(val, ast.Constant):
            return frozenset({val.value}) if val.value != () else None
        return ALL  # dynamic mask: assume every position may donate
    return None


def _donate_kwarg_positions(call: ast.Call):
    """Donation spec from a constructor's ``donate=`` argument."""
    for kw in call.keywords:
        if kw.arg != "donate":
            continue
        val = kw.value
        if isinstance(val, ast.Constant):
            if val.value in (False, None, ()):
                return None
            return frozenset({0})  # donate=True: single-buffer programs
        if isinstance(val, ast.Tuple) and not val.elts:
            return None  # donate=() — explicit no-donation
        if isinstance(val, ast.Tuple) and all(
                isinstance(e, ast.Constant) for e in val.elts):
            return frozenset(e.value for e in val.elts)
        return ALL
    return None


def _spec_of_value(expr: ast.AST):
    """Donation spec for the value of an assignment, or None."""
    if not isinstance(expr, ast.Call):
        return None
    call = _unwrap(expr)
    jit_pos = _jit_donated_positions(call)
    if jit_pos is not None:
        return jit_pos
    name = dotted_name(call.func)
    if name is not None:
        spec = CONSTRUCTORS.get(name.split(".")[-1])
        if spec == "donate-kwarg":
            return _donate_kwarg_positions(call)
        if spec is not None:
            return spec
    return None


def _consumed_args(call: ast.Call, spec) -> list[tuple[str, int]]:
    """(name, lineno) of local names passed in donated positions."""
    out = []
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            # positions >= i are covered by the star; conservatively
            # consumed whenever any donated position can land there
            if spec is ALL or (isinstance(spec, frozenset)
                               and any(p >= i for p in spec)):
                if isinstance(arg.value, ast.Name):
                    out.append((arg.value.id, call.lineno))
            continue
        if spec is ALL or (isinstance(spec, frozenset) and i in spec):
            if isinstance(arg, ast.Name):
                out.append((arg.id, call.lineno))
    return out


def _walk_local(fn: ast.FunctionDef):
    """Walk ``fn``'s body excluding nested function subtrees — those are
    analyzed separately (with the inherited binding env) by
    ``_recurse_nested``; visiting them here too would double-report
    findings and mix loads across sibling closures."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _binding_lines(fn: ast.FunctionDef) -> dict[str, list[int]]:
    """name -> lines where the name is (re)bound."""
    lines: dict[str, list[int]] = {}

    def bind(target: ast.AST, lineno: int) -> None:
        if isinstance(target, ast.Name):
            lines.setdefault(target.id, []).append(lineno)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                bind(e, lineno)
        elif isinstance(target, ast.Starred):
            bind(target.value, lineno)

    for node in _walk_local(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                bind(t, node.lineno)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            bind(node.target, node.lineno)
        elif isinstance(node, ast.For):
            bind(node.target, node.lineno)
        elif isinstance(node, ast.With):
            for item in node.items:
                if item.optional_vars is not None:
                    bind(item.optional_vars, node.lineno)
    return lines


def _analyze_function(sf, fn: ast.FunctionDef, inherited: dict,
                      findings: list[Finding]) -> None:
    env = dict(inherited)

    # 1st sweep: collect donating bindings (tuple unpacking included)
    for node in _walk_local(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            spec = _spec_of_value(node.value)
            target = node.targets[0]
            if spec is None:
                # rebinding a name clears any stale donating spec
                if isinstance(target, ast.Name):
                    env.pop(target.id, None)
                continue
            if isinstance(target, ast.Name):
                env[target.id] = spec
            elif (isinstance(target, ast.Tuple)
                  and isinstance(spec, tuple)):
                for elt, sub in zip(target.elts, spec):
                    if not isinstance(elt, ast.Name) or sub is None:
                        continue
                    if isinstance(sub, tuple) and sub[0] == "list":
                        env[elt.id] = sub  # a list of donating callables
                    else:
                        env[elt.id] = sub
        elif isinstance(node, ast.For):
            # `for piece, f in zip(xs, piece_fns):` — loop names drawn
            # from a list-of-donating-callables donate like its elements
            env.update(_loop_bindings(node, env))

    # 2nd sweep: find donated names read after the donating call
    bindings = _binding_lines(fn)
    loads: dict[str, list[int]] = {}
    for node in _walk_local(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            loads.setdefault(node.id, []).append(node.lineno)

    for node in _walk_local(fn):
        if not isinstance(node, ast.Call):
            continue
        spec = None
        if isinstance(node.func, ast.Name):
            spec = env.get(node.func.id)
            if isinstance(spec, tuple) and spec and spec[0] == "list":
                spec = None  # the list itself is not callable
        else:
            direct = _spec_of_value(node.func) if isinstance(
                node.func, ast.Call) else None
            spec = direct
        if spec is None:
            continue
        for name, call_line in _consumed_args(node, spec):
            # a rebind on the call line itself is `x = f(x)` — the
            # assignment lands after the donation, so later reads see
            # the fresh binding
            rebinds = [ln for ln in bindings.get(name, ())
                       if ln >= call_line]
            horizon = min(rebinds) if rebinds else None
            for load_line in loads.get(name, ()):
                if load_line <= call_line:
                    continue
                if horizon is not None and load_line >= horizon:
                    continue
                if sf.suppressed(NAME, load_line):
                    continue
                findings.append(Finding(
                    NAME, sf.rel, load_line,
                    f"{name!r} was donated at line {call_line} "
                    "(its buffer may be reused by the compiled program) "
                    "but is referenced afterwards — reading a donated "
                    "array is undefined on backends with real donation"))
                break  # one finding per consumed name is enough

    # nested functions inherit the enclosing donating bindings (plan
    # execute closures call programs constructed in the builder)
    for node in ast.iter_child_nodes(fn):
        _recurse_nested(sf, node, env, findings)


def _loop_bindings(node: ast.For, env: dict) -> dict:
    out: dict = {}
    it = node.iter
    sources: list[ast.AST] = []
    if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
            and it.func.id == "zip"):
        sources = list(it.args)
    else:
        sources = [it]
    targets = (list(node.target.elts)
               if isinstance(node.target, ast.Tuple) else [node.target])
    if len(targets) != len(sources):
        return out
    for tgt, src in zip(targets, sources):
        if not (isinstance(tgt, ast.Name) and isinstance(src, ast.Name)):
            continue
        spec = env.get(src.id)
        if isinstance(spec, tuple) and spec and spec[0] == "list":
            out[tgt.id] = spec[1]
    return out


def _recurse_nested(sf, node: ast.AST, env: dict,
                    findings: list[Finding]) -> None:
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        _analyze_function(sf, node, env, findings)
        return
    for child in ast.iter_child_nodes(node):
        _recurse_nested(sf, child, env, findings)


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.ops_files():
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _analyze_function(sf, node, {}, findings)
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        _analyze_function(sf, sub, {}, findings)
    return findings
