"""timer-purity pass: code reachable from the cycle timer thread must be
rank-deterministic.

Invariant (PRs 2-3, docs/fusion_cycle.md): flush *composition* — what ends
up in each dispatched program — must be identical on every rank, derived
from submission order and submission-time negotiation names only. The
cycle timer (``FusionScheduler._loop``, pacing ``HVD_CYCLE_TIME`` /
``HVD_PENDING_CYCLE_TIME``) fires on wall-clock jitter that differs per
process, so everything it can reach must be composition-pure:

* no ``negotiate`` / ``negotiate_many*`` calls (negotiation order from a
  jittery timer would desynchronize the KV rounds across processes —
  the timer must never drain svc-backed queues);
* no wall-clock reads (``time.time`` / ``time.time_ns`` /
  ``datetime.now``) — ``time.monotonic`` / ``time.sleep`` are exempt:
  they pace *when* a single-controller flush fires, which is free to
  jitter, never *what* is composed;
* no ``random`` (stdlib or numpy) draws;
* no iteration over Python ``set`` values (unordered iteration feeding
  batch order is rank-nondeterministic; sets are fine as membership
  guards — ``isdisjoint`` / ``in`` — just not as ``for`` sources).

Traversal starts at the timer callback (``FusionScheduler._loop``, plus
any ``def`` carrying a ``# hvdlint: timer-root`` marker) and follows
resolvable project calls. A ``# hvdlint: timer-boundary`` marker on a
``def`` stops traversal there — used for entry points that are
dynamically unreachable from the timer for svc queues (the ``_loop``
skip) or trivially rank-consistent (single-controller dispatch); each
in-tree marker documents its justification. A statically-reachable but
dynamically-guarded banned call is suppressed at the call line with
``# hvdlint: disable=timer-purity``.
"""

from __future__ import annotations

import ast

from ..core import Finding, FuncInfo, Project, dotted_name

NAME = "timer-purity"

ROOT_MARKER = "timer-root"
BOUNDARY_MARKER = "timer-boundary"

DEFAULT_ROOTS = (("ops/fusion_cycle.py", "FusionScheduler._loop"),)

_WALLCLOCK = {"time.time", "time.time_ns", "datetime.now",
              "datetime.datetime.now", "datetime.utcnow",
              "datetime.datetime.utcnow"}


def _banned_call(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name is None:
        return None
    parts = name.split(".")
    last = parts[-1]
    if last.startswith("negotiate"):
        return (f"'{name}': negotiation from timer-reachable code — flush "
                "composition would depend on per-process timer jitter")
    if name in _WALLCLOCK:
        return (f"'{name}': wall-clock read in timer-reachable code (use "
                "time.monotonic for pacing; composition must not read "
                "clocks)")
    if "random" in parts[:-1] or parts[0] == "random":
        return (f"'{name}': randomness in timer-reachable code is "
                "rank-nondeterministic")
    return None


def _set_typed_names(fn: ast.FunctionDef) -> set[str]:
    """Local names bound to an obvious set value anywhere in ``fn``."""
    names: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_set_expr(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def _is_set_expr(expr: ast.AST) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    return (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Name)
            and expr.func.id in ("set", "frozenset"))


def _iter_sources(fn: ast.FunctionDef):
    """(iter-expr, lineno) of every for-loop and comprehension source."""
    for node in ast.walk(fn):
        if isinstance(node, ast.For):
            yield node.iter, node.lineno
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                yield gen.iter, getattr(gen.iter, "lineno", node.lineno)


def _roots(project: Project) -> list[FuncInfo]:
    roots: list[FuncInfo] = []
    for tail, qual in DEFAULT_ROOTS:
        info = project.func(f"{project.package_rel}/{tail}", qual)
        if info is not None:
            roots.append(info)
    for info in project.functions():
        if info.file.has_marker(ROOT_MARKER, info.node.lineno):
            roots.append(info)
    return roots


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    visited: set[tuple] = set()
    queue = list(_roots(project))
    root_keys = {i.key for i in queue}
    while queue:
        info = queue.pop()
        if info.key in visited:
            continue
        visited.add(info.key)
        if (info.key not in root_keys
                and info.file.has_marker(BOUNDARY_MARKER, info.node.lineno)):
            continue
        sf = info.file
        aliases = project.func_imports(info)
        set_names = _set_typed_names(info.node)
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call):
                why = _banned_call(node)
                if why is not None and not sf.suppressed(NAME, node.lineno):
                    findings.append(Finding(
                        NAME, sf.rel, node.lineno,
                        f"timer-reachable (via {info.qualname}): {why}"))
                callee = project.resolve_call(info, node, aliases)
                if callee is not None:
                    queue.append(callee)
        for src, lineno in _iter_sources(info.node):
            if (_is_set_expr(src)
                    or (isinstance(src, ast.Name) and src.id in set_names)):
                if not sf.suppressed(NAME, lineno):
                    findings.append(Finding(
                        NAME, sf.rel, lineno,
                        f"timer-reachable (via {info.qualname}): iteration "
                        "over an unordered set — batch order derived from "
                        "set order is rank-nondeterministic (sort first, "
                        "or keep a list)"))
    return findings
