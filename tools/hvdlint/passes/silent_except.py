"""silent-except pass: failures must be surfaced, retried, or vetted.

Invariant (PR 5, docs/robustness.md): the failure domain only works if
failures actually *reach* it. Two lexical patterns defeat that silently:

1. **Swallowed broad exceptions** — an ``except``/``except Exception``/
   ``except BaseException`` handler whose body is exactly ``pass``
   discards errors the retry/watchdog/poison machinery should have seen
   (the PUT-observer bug this PR fixed hid a dead elastic protocol as a
   hang). Narrow typed handlers (``except queue.Empty: pass``,
   ``except OSError: pass``) are deliberate control flow and stay legal;
   a *broad* silent handler needs either a real body (log it) or a
   ``# hvdlint: disable=silent-except`` pragma documenting why nothing
   can be done.
2. **Hand-rolled sleep loops** — ``time.sleep`` inside a ``while``/
   ``for`` loop outside ``utils/retry.py`` is a fixed-cadence retry/poll
   loop that bypasses the unified backoff policy (``HVD_RETRY_*``
   knobs, deterministic jitter, deadline accounting, retry counters in
   ``hvd.health_stats()``). Route it through ``retry.call`` /
   ``retry.poll_intervals``, or pragma a vetted exception (e.g. the
   SIGKILL escalation probe in ``runner/safe_exec.py``, which has no
   server to back off from).
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, dotted_name, parent_map

NAME = "silent-except"

_BROAD = ("Exception", "BaseException")
_RETRY_HOME = "utils/retry.py"


def _is_broad(type_node: ast.AST | None) -> bool:
    if type_node is None:
        return True  # bare except
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    return False


def _check_silent_handlers(sf, findings: list[Finding]) -> None:
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        body_is_pass = (len(node.body) == 1
                        and isinstance(node.body[0], ast.Pass))
        if not body_is_pass or not _is_broad(node.type):
            continue
        if sf.suppressed(NAME, node.lineno) \
                or sf.suppressed(NAME, node.body[0].lineno):
            continue
        what = ("bare except" if node.type is None
                else f"except {ast.unparse(node.type)}")
        findings.append(Finding(
            NAME, sf.rel, node.lineno,
            f"{what}: pass — a broad silent handler discards failures "
            "the failure domain should see (retry ladder, watchdog "
            "poison, health_stats counters). Log it, narrow the type, "
            "or pragma a vetted best-effort site"))


def _check_sleep_loops(sf, findings: list[Finding]) -> None:
    parents = parent_map(sf.tree)
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call)
                and dotted_name(node.func) == "time.sleep"):
            continue
        # In a loop? Walk ancestors up to the enclosing function/module:
        # a sleep in a nested def is that function's own business.
        cur = parents.get(node)
        in_loop = False
        while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                      ast.ClassDef, ast.Module)):
            if isinstance(cur, (ast.While, ast.For)):
                in_loop = True
                break
            cur = parents.get(cur)
        if not in_loop or sf.suppressed(NAME, node.lineno):
            continue
        findings.append(Finding(
            NAME, sf.rel, node.lineno,
            "time.sleep inside a loop: a hand-rolled retry/poll loop "
            "bypasses the unified backoff policy — use "
            "utils/retry.py (retry.call / retry.poll_intervals) so "
            "HVD_RETRY_* knobs, jitter, deadlines, and the "
            "health_stats retry counters apply"))


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    retry_rel = f"{project.package_rel}/{_RETRY_HOME}"
    for sf in project.files:
        _check_silent_handlers(sf, findings)
        if sf.rel != retry_rel:
            _check_sleep_loops(sf, findings)
    return findings
