"""issue-lock pass: compiled-collective programs must enqueue under the
process-wide program-issue lock.

Invariant (PR 3, ``ops/program_issue.py``): two threads interleaving the
per-device enqueues of two multi-device collective programs deadlock the
backend's collective rendezvous — reproduced on the XLA CPU backend. The
fix is that every *eager compiled program constructor* in the ops layer
wraps its ``jax.jit(...)`` in ``issue_serialized`` so concurrent callers
enqueue atomically. This pass makes the wrapper a machine-checked rule:

* every ``jax.jit(...)`` call in ``horovod_tpu/ops/`` must appear inside
  an ``issue_serialized(...)`` / ``_issue_serialized(...)`` call;
* an eagerly-invoked ``jax.shard_map(...)(x)`` (compiled multi-device
  program executed without jit, hence without the lock) is flagged too.

``ops/program_issue.py`` itself is exempt (it defines the wrapper).
Traced-mode code outside ``ops/`` composes into the *user's* jit and
never dispatches eagerly, so the rule is scoped to the eager dispatch
layer. Suppress a deliberate exception with
``# hvdlint: disable=issue-lock``.
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, dotted_name, parent_map

NAME = "issue-lock"

WRAPPERS = ("issue_serialized", "_issue_serialized")


def _is_jax_jit(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name in ("jax.jit", "jit")


def _is_shard_map(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name in ("jax.shard_map", "shard_map",
                    "jax.experimental.shard_map.shard_map")


def _wrapped(call: ast.Call, parents) -> bool:
    node = parents.get(call)
    while node is not None:
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is not None and name.split(".")[-1] in WRAPPERS:
                return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # don't escape the defining scope: a wrapper call in an
            # enclosing function does not cover a jit built inside a
            # nested one
            return False
        node = parents.get(node)
    return False


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.ops_files():
        if sf.rel.endswith("/program_issue.py"):
            continue
        parents = parent_map(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if _is_jax_jit(node) and not _wrapped(node, parents):
                if not sf.suppressed(NAME, node.lineno):
                    findings.append(Finding(
                        NAME, sf.rel, node.lineno,
                        "jax.jit(...) outside issue_serialized(...): "
                        "compiled eager programs must enqueue under the "
                        "program-issue lock (ops/program_issue.py; "
                        "concurrent per-device enqueues deadlock the "
                        "collective rendezvous)"))
            elif (isinstance(node.func, ast.Call)
                  and _is_shard_map(node.func)
                  and not _wrapped(node, parents)):
                if not sf.suppressed(NAME, node.lineno):
                    findings.append(Finding(
                        NAME, sf.rel, node.lineno,
                        "eager jax.shard_map(...)(...) invocation without "
                        "jit + issue_serialized: multi-device programs "
                        "must dispatch under the program-issue lock"))
    return findings
