"""rank-divergence pass: collective submissions must not sit under
rank-local control flow.

Invariant (the reference coordinator's first rule, PAPER.md; PR-2's
determinism contract): every process must submit the SAME sequence of
collectives. A collective enqueue/flush reachable only under a
condition whose value differs per rank — ``rank()`` / ``local_rank()``
/ ``cross_rank()`` comparisons, wall-clock reads, or iteration order of
an unordered ``set`` — is the classic mismatched-collective hang: rank
0 calls ``allreduce_async`` inside ``if rank() == 0:`` and every other
rank waits forever for a negotiation that will never complete (the
stall inspector names it after 60 s; the job is already dead).

*Checked:* call sites of the submission surface — any ``*_async`` call,
``flush_entry``, or ``negotiate_many_submit`` — lexically inside the
body/orelse of an ``if``/``while``/ternary whose test is **rank-local**
(contains a rank-family or wall-clock call, a **dynamic queue/tenant
runtime-state** read — ``fusion_stats()`` / ``qos_stats()`` /
``dispatch_cache_stats()`` / ``health_stats()`` / ``metrics_dump()``,
whose values track per-rank completion timing, so a collective
conditioned on them is the same mismatched-collective hang class as a
rank-conditioned one — a **mesh-axis-index query on a data axis**
(``jax.lax.axis_index`` with a data-axis literal/constant, or a mesh
coordinate lookup; the composed-mesh layer of ISSUE 17 makes "my
coordinate in the gradient-sync group" as reachable as ``rank()``, and
it diverges identically), or a local name assigned from one), or inside
a ``for`` over an obvious ``set`` value (unordered iteration diverges
submission *order* across ranks even when the call count matches).
Static QoS *configuration* reads (``qos.get_class`` /
``set_qos`` weights, priorities, quotas) stay legal: they are pure
config, identical on every rank by the set_qos contract.

Rank-symmetric conditionals are fine and common (``root_rank``
dispatch where every rank takes the same branch is NOT flagged — the
test must reference a rank-local value). A vetted divergence —
e.g. a site guarded by an out-of-band agreement — carries
``# hvdlint: disable=rank-divergence`` with a justification, like every
other pragma.
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, dotted_name, parent_map

NAME = "rank-divergence"

_RANK_CALLS = {"rank", "local_rank", "cross_rank"}
_WALLCLOCK = {"time.time", "time.time_ns", "time.monotonic",
              "time.perf_counter", "datetime.now", "datetime.datetime.now",
              "datetime.utcnow", "datetime.datetime.utcnow"}
# clocks the concurrency core reads through the invariants seam
# (utils/invariants.monotonic and its _inv/primitives aliases): matched
# by last segment, since the package never spells time.monotonic raw
_WALLCLOCK_LAST = {"monotonic", "perf_counter"}
# dynamic queue/tenant runtime state (ISSUE 12): these read per-rank
# scheduler/engine progress — queue depths, shed counts, in-flight
# bytes, cache hit rates — which track completion timing and therefore
# differ across ranks. A collective submission conditioned on them is
# the mismatched-collective hang class; static QoS config (weights,
# priorities, quotas via qos.get_class/set_qos) is NOT in this set.
# ISSUE 15 adds the autoscale surfaces: `policy_stats()` is
# driver-authoritative controller state a rank can only observe at some
# arbitrary point of a membership transition, and `straggler_stats()` /
# `straggler_blames()` are this-rank observations of peer lag — all
# three differ across ranks (and across reads) exactly like a queue
# depth, so branching a collective on them is the same hang class.
_RUNTIME_STATE_LAST = {"fusion_stats", "qos_stats",
                       "dispatch_cache_stats", "health_stats",
                       "metrics_dump", "straggler_stats",
                       "straggler_blames", "policy_stats"}
# autoscale decision state read as a bare attribute (ISSUE 15,
# elastic/policy.py): `policy.last_decision` / `policy.decisions` are
# the controller's mutable decision log — rank-divergent for the same
# reason as the call surfaces above (caught below like `.is_leader`).
_POLICY_STATE_ATTRS = {"last_decision", "decisions"}
# leader-role predicates (ISSUE 13, negotiation/layout.py): "am I a
# leader" differs per rank exactly like rank() — a collective submission
# conditioned on it is the same mismatched-collective hang. The static
# layout's rank-SYMMETRIC shape queries (n_groups, leaders(),
# members_of(g), leader_of(g) with a literal group) stay legal: every
# rank computes the same value from the same (world, G).
_LEADER_CALLS = {"is_leader", "is_group_leader", "leads"}
# composed-mesh data-axis coordinates (ISSUE 17, parallel/mesh.py): a
# mesh-axis-index query on a DATA axis — ``jax.lax.axis_index("dcn")``,
# or spelled through the canonical axis constants — is this rank's
# coordinate within the gradient-sync group, rank-local exactly like
# ``rank()``. Model-axis queries (a schedule's own
# ``axis_index(cfg.seq_axis)`` positioning math, transformer.py /
# parallel/{sequence,moe,pipeline}.py) are legal traced compute and are
# NOT matched: only string-literal data-axis names and the canonical
# data-axis constants taint. Mesh *coordinate lookups* (resolving a
# device's coordinates in the composed mesh) taint regardless of axis —
# the answer is per-device by construction.
_DATA_AXIS_LITERALS = {"hvd", "dcn", "ici_dp", "hvd_dcn", "hvd_ici"}
_DATA_AXIS_CONSTS = {"AXIS_NAME", "DCN_AXIS", "ICI_DP_AXIS", "ICI_AXIS",
                     "DATA_AXES"}
_MESH_COORD_CALLS = {"coords_of", "device_coords", "mesh_coords"}
_SUBMIT_NAMES = {"flush_entry", "negotiate_many_submit"}


def _is_data_axis_expr(expr: ast.AST | None) -> bool:
    if expr is None:
        return False
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value in _DATA_AXIS_LITERALS
    if isinstance(expr, ast.Subscript):  # DATA_AXES[0] etc.
        base = dotted_name(expr.value)
        return base is not None and base.split(".")[-1] == "DATA_AXES"
    name = dotted_name(expr)
    return name is not None and name.split(".")[-1] in _DATA_AXIS_CONSTS


def _taint_call(node: ast.AST) -> str | None:
    """The offending source when ``node`` is a rank-local call."""
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if name is None:
        return None
    last = name.split(".")[-1]
    if last in _RANK_CALLS:
        return f"{name}()"
    if name in _WALLCLOCK or last in _WALLCLOCK_LAST:
        return f"{name}() (wall clock)"
    if last in _RUNTIME_STATE_LAST:
        return f"{name}() (dynamic queue/tenant runtime state)"
    if last in _LEADER_CALLS:
        return (f"{name}() (leader-role state: leadership is rank-local; "
                "only the static group layout's shape is symmetric)")
    if last == "axis_index" and _is_data_axis_expr(
            node.args[0] if node.args else None):
        return (f"{name}() on a data axis (this rank's coordinate in the "
                "gradient-sync group — rank-local like rank(); model-axis "
                "queries are schedule math and stay legal)")
    if last in _MESH_COORD_CALLS:
        return f"{name}() (mesh coordinate lookup: per-device by construction)"
    return None


def _expr_taint(expr: ast.AST, tainted: dict[str, str]) -> str | None:
    for node in ast.walk(expr):
        why = _taint_call(node)
        if why is not None:
            return why
        if isinstance(node, ast.Attribute) and node.attr == "is_leader":
            # bare `.is_leader` attribute read (a cached role flag);
            # the call form `layout.is_leader(r)` is caught by
            # _taint_call first (ast.walk visits the Call before its
            # func attribute)
            return f"{node.attr} (leader-role state)"
        if (isinstance(node, ast.Attribute)
                and node.attr in _POLICY_STATE_ATTRS):
            return (f"{node.attr} (autoscale policy decision state: "
                    "decisions are driver-authoritative — a rank must "
                    "never branch a collective on them)")
        if isinstance(node, ast.Name) and node.id in tainted:
            return tainted[node.id]
    return None


def _tainted_names(fn: ast.AST) -> dict[str, str]:
    """Local names (transitively) assigned from rank-local values,
    mapped to the original source for the message."""
    tainted: dict[str, str] = {}
    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            value = None
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                value, targets = node.value, [node.target]
            if value is None:
                continue
            why = _expr_taint(value, tainted)
            if why is None:
                continue
            for t in targets:
                if isinstance(t, ast.Name) and t.id not in tainted:
                    tainted[t.id] = f"{t.id} (from {why})"
                    changed = True
    return tainted


def _submission_call(node: ast.AST) -> str | None:
    if not isinstance(node, ast.Call):
        return None
    name = dotted_name(node.func)
    if name is None:
        return None
    last = name.split(".")[-1]
    if last.endswith("_async") or last in _SUBMIT_NAMES:
        return name
    return None


def _is_set_expr(expr: ast.AST) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    return (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Name)
            and expr.func.id in ("set", "frozenset"))


def _set_typed_names(fn: ast.AST) -> set[str]:
    names: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_set_expr(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return names


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for sf in project.files:
        parents = parent_map(sf.tree)
        funcs = [n for n in ast.walk(sf.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                 and not isinstance(parents.get(n),
                                    (ast.FunctionDef, ast.AsyncFunctionDef))]
        for fn in funcs:
            tainted = _tainted_names(fn)
            set_names = _set_typed_names(fn)
            for node in ast.walk(fn):
                call_name = _submission_call(node)
                if call_name is None or sf.suppressed(NAME, node.lineno):
                    continue
                cur = node
                while cur is not fn:
                    parent = parents.get(cur)
                    if parent is None:
                        break
                    why = _guard_taint(parent, cur, tainted, set_names)
                    if why is not None:
                        findings.append(Finding(
                            NAME, sf.rel, node.lineno,
                            f"collective submission '{call_name}' under "
                            f"rank-local control flow ({why}): every rank "
                            "must submit the identical collective "
                            "sequence — a rank-conditioned enqueue/flush "
                            "hangs the peers (mismatched collectives). "
                            "Hoist the call, or pragma a vetted "
                            "exception"))
                        break
                    cur = parent
    return findings


def _guard_taint(parent: ast.AST, child: ast.AST, tainted: dict,
                 set_names: set[str]) -> str | None:
    """Why ``child``'s position under ``parent`` is rank-divergent."""
    if isinstance(parent, (ast.If, ast.While)):
        if child in parent.body or child in parent.orelse:
            return _expr_taint(parent.test, tainted)
    elif isinstance(parent, ast.IfExp):
        if child is parent.body or child is parent.orelse:
            return _expr_taint(parent.test, tainted)
    elif isinstance(parent, ast.For):
        if child in parent.body:
            src = parent.iter
            if (_is_set_expr(src)
                    or (isinstance(src, ast.Name) and src.id in set_names)):
                return "iteration over an unordered set"
    return None
