"""trace-coverage pass: the conformance decision-point registry, the
``conformance.record(...)`` call sites, and ``docs/conformance.md``
agree exactly.

Invariant (``horovod_tpu/conformance.py``): the lockstep conformance
instrument is only as good as its coverage — a decision point that is
registered in :data:`SITES` but never records is a silent blind spot
(``tools/hvdtrace`` would report a diverging world clean), and a
``record()`` call outside the registry produces events the offline
differ cannot classify (stream/class fall back to permissive
defaults). The knob-registry pass's pattern, applied to trace
coverage:

1. **registry -> site**: every site key in ``SITES``
   (``"<file>::<qualname>"``) must name a real function in the
   package, and that function body must contain a
   ``conformance.record(...)`` call whose first argument is the site's
   own key as a string literal;
2. **site -> registry**: every resolved ``conformance.record(...)``
   call in the package (outside ``conformance.py`` itself) must pass a
   string-literal site key that is registered AND matches the file +
   enclosing function it actually sits in — a copy-pasted key from
   another site mislabels every event it emits;
3. **doc round-trip**: the site keys in ``SITES`` and the
   ``file::qualname`` tokens in ``docs/conformance.md`` must match
   exactly in both directions.

When the analyzed package has no ``conformance.py`` (linting
``tools/`` itself), the pass is a no-op — the registry lives with the
runtime.
"""

from __future__ import annotations

import ast
import re

from ..core import Finding, FuncInfo, Project

NAME = "trace-coverage"

_DOC_REL = "docs/conformance.md"
_SITE_TOKEN = re.compile(
    r"\b[A-Za-z0-9_][A-Za-z0-9_/]*\.py::[A-Za-z_][A-Za-z0-9_.]*")
# The recorder's own epoch-move events carry this site; it is internal
# (emitted from inside Recorder.note, not a hooked decision point) but
# documented, so the doc round-trip must accept it.
_INTERNAL_SITES = {"conformance.py::Recorder.note"}


def _sites_literal(conf_sf) -> dict[str, int]:
    """``SITES`` keys -> declaration line, from the module-level dict
    literal in conformance.py."""
    out: dict[str, int] = {}
    for node in conf_sf.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "SITES"
                and isinstance(node.value, ast.Dict)):
            continue
        for key in node.value.keys:
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                out[key.value] = key.lineno
    return out


def _record_calls(project: Project, info: FuncInfo, conf_rel: str):
    """Yield ``conformance.record(...)`` / ``record(...)`` calls inside
    ``info`` whose callee resolves (through the module's import aliases)
    to the conformance module."""
    aliases = project.func_imports(info)
    for node in ast.walk(info.node):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if (isinstance(func, ast.Attribute) and func.attr == "record"
                and isinstance(func.value, ast.Name)
                and aliases.get(func.value.id) == conf_rel):
            yield node
        elif (isinstance(func, ast.Name)
              and aliases.get(func.id) == conf_rel):
            # `from .. import conformance` then `conformance(...)` can't
            # happen; this arm catches `from ..conformance import record`
            if func.id == "record":
                yield node


def _site_of(project: Project, info: FuncInfo) -> str:
    rel = info.file.rel
    prefix = f"{project.package_rel}/"
    if rel.startswith(prefix):
        rel = rel[len(prefix):]
    return f"{rel}::{info.qualname}"


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    conf_rel = f"{project.package_rel}/conformance.py"
    conf_sf = project.by_rel.get(conf_rel)
    if conf_sf is None:
        return findings  # linting a tree without the runtime registry
    sites = _sites_literal(conf_sf)
    if not sites:
        findings.append(Finding(
            NAME, conf_rel, 1,
            "conformance.py defines no SITES literal — the decision-"
            "point registry must be a module-level dict of string keys"))
        return findings

    # index: registered site -> the literal keys actually recorded there
    recorded_at: dict[str, set[str]] = {}
    for info in project.functions():
        if info.file.rel == conf_rel:
            continue  # the recorder's own internals are not hooked sites
        here = _site_of(project, info)
        for call in _record_calls(project, info, conf_rel):
            if info.file.suppressed(NAME, call.lineno):
                continue
            arg = call.args[0] if call.args else None
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                findings.append(Finding(
                    NAME, info.file.rel, call.lineno,
                    "conformance.record(...) site key must be a string "
                    "literal — computed keys are invisible to this "
                    "coverage check and to the docs round-trip"))
                continue
            key = arg.value
            if key not in sites:
                findings.append(Finding(
                    NAME, info.file.rel, call.lineno,
                    f"conformance.record({key!r}): site is not "
                    "registered in conformance.SITES — unregistered "
                    "events fall back to a permissive stream/class the "
                    "offline differ cannot validate"))
            elif key != here:
                findings.append(Finding(
                    NAME, info.file.rel, call.lineno,
                    f"conformance.record({key!r}) called from {here!r}: "
                    "the site key must name the file + function it sits "
                    "in, or every event it emits is mislabeled"))
            recorded_at.setdefault(here, set()).add(key)

    # every registered site resolves to a real function that records
    for site, line in sorted(sites.items()):
        rel, _, qualname = site.partition("::")
        info = project.func(f"{project.package_rel}/{rel}", qualname)
        if info is None:
            if not conf_sf.suppressed(NAME, line):
                findings.append(Finding(
                    NAME, conf_rel, line,
                    f"SITES registers {site!r} but no such function "
                    "exists in the package (renamed or removed "
                    "decision point — update the registry)"))
            continue
        if site not in recorded_at.get(site, set()):
            if not conf_sf.suppressed(NAME, line):
                findings.append(Finding(
                    NAME, conf_rel, line,
                    f"SITES registers {site!r} but the function contains "
                    "no conformance.record(...) call with that key — an "
                    "unhooked decision point is a blind spot hvdtrace "
                    "reports as clean"))

    # doc round-trip, both directions
    doc_path = project.root / _DOC_REL
    if not doc_path.exists():
        findings.append(Finding(
            NAME, _DOC_REL, 1,
            "docs/conformance.md is missing — the decision-point "
            "registry must be documented"))
        return findings
    doc_sites: dict[str, int] = {}
    for i, line_text in enumerate(doc_path.read_text().splitlines(),
                                  start=1):
        for m in _SITE_TOKEN.finditer(line_text):
            doc_sites.setdefault(m.group(0), i)
    for site, line in sorted(sites.items()):
        if site not in doc_sites:
            findings.append(Finding(
                NAME, conf_rel, line,
                f"site {site} is registered in conformance.SITES but "
                f"undocumented in {_DOC_REL}"))
    for site, line in sorted(doc_sites.items()):
        if site in sites or site in _INTERNAL_SITES:
            continue
        findings.append(Finding(
            NAME, _DOC_REL, line,
            f"{_DOC_REL} documents site {site}, which is not in "
            "conformance.SITES (stale entry, or the registration is "
            "missing)"))
    return findings
