"""lock-order pass: the static ``with <lock>`` nesting graph must be
acyclic.

The runtime holds 10+ locks across ``fusion_cycle`` (queue mutex +
executor condition), ``dispatch_cache``, ``autotune``, ``process_sets``,
``engine_service``, ``timeline``, and ``elastic/``. A consistent global
acquisition order is what makes that safe; the order exists only by
convention (e.g. ``fusion_cycle``'s documented one-way ``_mu ->
_exec_cv`` nesting). This pass extracts the acquisition-order graph
statically and fails on any cycle:

* a ``with A:`` lexically containing ``with B:`` adds edge ``A -> B``;
* a call made while holding ``A`` to a project function that (transitively,
  through resolvable calls) acquires ``B`` adds ``A -> B`` as well —
  cross-module nesting is where conventions rot first.

Lock identity is ``module::Class.attr`` (or ``module::name`` for
module-level locks); ``with`` context expressions whose final attribute
looks lock-like (``lock`` / ``mu`` / ``mutex`` / ``cv`` / ``cond``) are
treated as locks — the same naming convention
``horovod_tpu.utils.invariants.make_lock`` enforces at runtime. The
runtime twin of this pass is the ``HVD_DEBUG_INVARIANTS=1`` lock-order
witness, which checks the *dynamic* acquisition order with stacks.

The transitive-call edge is an over-approximation (a callee may acquire
only on an unreached branch); suppress a vetted false positive with
``# hvdlint: disable=lock-order`` on the inner ``with`` or call line.
"""

from __future__ import annotations

import ast

from ..core import Finding, FuncInfo, Project, dotted_name

NAME = "lock-order"

_LOCKISH = ("lock", "mutex", "mu", "cv", "cond")


def _is_lockish(last_segment: str) -> bool:
    seg = last_segment.lower()
    return any(tok in seg for tok in _LOCKISH)


def _lock_id(project: Project, info: FuncInfo, expr: ast.AST,
             aliases: dict[str, str]) -> str | None:
    """Identity of a ``with`` context expression when it looks like a
    lock; None otherwise (calls — e.g. ``with timeline.op_range(...)`` —
    are never locks here)."""
    name = dotted_name(expr)
    if name is None:
        return None
    parts = name.split(".")
    if not _is_lockish(parts[-1]):
        return None
    if parts[0] in ("self", "cls"):
        owner = info.class_name or "?"
        return f"{info.file.rel}::{owner}.{'.'.join(parts[1:])}"
    if parts[0] in aliases and len(parts) > 1:
        return f"{aliases[parts[0]]}::{'.'.join(parts[1:])}"
    return f"{info.file.rel}::{name}"


class _FuncFacts:
    """Per-function lock facts: every lock acquired directly, and the
    (held-lock -> nested-lock / held-lock -> callee) observations."""

    def __init__(self):
        self.direct: set[str] = set()  # locks acquired anywhere in the fn
        # (held lock, lock, file, line) for lexically nested withs
        self.nested: list[tuple[str, str, str, int]] = []
        # (held lock, callee FuncInfo key, file, line)
        self.calls_under: list[tuple[str, tuple, str, int]] = []
        self.callees: set[tuple] = set()  # all resolvable callees


def _collect(project: Project, info: FuncInfo) -> _FuncFacts:
    facts = _FuncFacts()
    aliases = project.func_imports(info)
    sf = info.file

    def visit(node: ast.AST, held: tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def's body runs when called, not under the locks
            # lexically surrounding the def — analyze it with no held
            # locks but fold its facts into this function (closures are
            # not in the module-level function index)
            for sub in node.body:
                visit(sub, ())
            return
        if isinstance(node, ast.With):
            inner = held
            for item in node.items:
                visit(item.context_expr, held)
                lid = _lock_id(project, info, item.context_expr, aliases)
                if lid is None:
                    continue
                facts.direct.add(lid)
                if not sf.suppressed(NAME, node.lineno):
                    for h in held:
                        if h != lid:
                            facts.nested.append((h, lid, sf.rel,
                                                 node.lineno))
                inner = inner + (lid,)
            for sub in node.body:
                visit(sub, inner)
            return
        if isinstance(node, ast.Call):
            callee = project.resolve_call(info, node, aliases)
            if callee is not None:
                facts.callees.add(callee.key)
                if held and not sf.suppressed(NAME, node.lineno):
                    for h in held:
                        facts.calls_under.append(
                            (h, callee.key, sf.rel, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in info.node.body:
        visit(stmt, ())
    return facts


def _acquire_closure(facts_by_key, key, memo, visiting) -> set[str]:
    if key in memo:
        return memo[key]
    if key in visiting:
        return set()  # call-graph cycle: closed over by the caller
    visiting.add(key)
    facts = facts_by_key.get(key)
    acquired = set(facts.direct) if facts else set()
    if facts:
        for callee in facts.callees:
            acquired |= _acquire_closure(facts_by_key, callee, memo,
                                         visiting)
    visiting.discard(key)
    memo[key] = acquired
    return acquired


def run(project: Project) -> list[Finding]:
    facts_by_key = {}
    for info in project.functions():
        facts_by_key[info.key] = _collect(project, info)

    # edge (a, b) -> first (file, line, kind) observation
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}
    memo: dict = {}
    for key, facts in facts_by_key.items():
        for h, lid, rel, line in facts.nested:
            edges.setdefault((h, lid), (rel, line, "nested with"))
        for h, callee, rel, line in facts.calls_under:
            for lid in _acquire_closure(facts_by_key, callee, memo, set()):
                if lid != h:
                    edges.setdefault(
                        (h, lid),
                        (rel, line, f"call into {callee[1]} ({callee[0]})"))

    # cycle detection over the lock digraph
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())

    findings: list[Finding] = []
    seen_cycles: set[frozenset] = set()

    def dfs(node, stack, on_stack, visited):
        visited.add(node)
        on_stack.add(node)
        stack.append(node)
        for nxt in sorted(graph.get(node, ())):
            if nxt in on_stack:
                cycle = stack[stack.index(nxt):] + [nxt]
                key = frozenset(cycle)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    findings.append(_cycle_finding(cycle, edges))
            elif nxt not in visited:
                dfs(nxt, stack, on_stack, visited)
        stack.pop()
        on_stack.discard(node)

    visited: set[str] = set()
    for node in sorted(graph):
        if node not in visited:
            dfs(node, [], set(), visited)
    return findings


def _cycle_finding(cycle: list[str], edges) -> Finding:
    hops = []
    anchor = ("", 0)
    for a, b in zip(cycle, cycle[1:]):
        rel, line, kind = edges[(a, b)]
        if not anchor[0]:
            anchor = (rel, line)
        hops.append(f"{a} -> {b} [{kind} at {rel}:{line}]")
    return Finding(
        NAME, anchor[0], anchor[1],
        "lock acquisition-order cycle (deadlock risk): "
        + "; ".join(hops))
