"""hvdlint pass registry (see docs/static_analysis.md for the catalog)."""

from __future__ import annotations

from . import (
    donation,
    issue_lock,
    knob_registry,
    lock_order,
    metrics_registry,
    rank_divergence,
    silent_except,
    timer_purity,
    trace_coverage,
)

# name -> run(project) -> list[Finding]; keep the catalog order stable so
# output and docs line up.
PASSES = {
    issue_lock.NAME: issue_lock.run,
    lock_order.NAME: lock_order.run,
    timer_purity.NAME: timer_purity.run,
    knob_registry.NAME: knob_registry.run,
    donation.NAME: donation.run,
    silent_except.NAME: silent_except.run,
    rank_divergence.NAME: rank_divergence.run,
    metrics_registry.NAME: metrics_registry.run,
    trace_coverage.NAME: trace_coverage.run,
}
