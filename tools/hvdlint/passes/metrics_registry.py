"""metrics-registry pass: telemetry flows through ``metrics.py`` and the
instrument catalog round-trips with ``docs/metrics.md``.

Invariant (PR 11, ``horovod_tpu/metrics.py``): the unified metrics
registry is the ONE telemetry namespace — instruments are declared in
``metrics.py`` at module level with literal names, recorded from
anywhere, and exposed through ``/metrics`` / ``hvd.metrics_dump()``. An
ad-hoc module-level counter is invisible to every exposition surface,
to the per-rank loopback stores (it silently aggregates across ranks),
and to the HVD_METRICS overhead gate. The knob-registry pass's pattern,
applied to telemetry:

1. **no ad-hoc module counters**: a module-level integer mutated with
   ``global NAME`` + ``NAME += ...`` inside a function is an unregistered
   counter (epochs/sequence state that genuinely isn't telemetry carries
   a pragma);
2. **no ad-hoc dict telemetry**: a module-level dict literal whose
   entries are incremented inside a function (``D[k] += n`` or
   ``D[k] = D.get(k, ...) + ...``) is an unregistered labeled counter;
3. **catalog centralization**: instrument constructors
   (``metrics.counter/gauge/histogram``) are only legal in
   ``metrics.py`` — a declaration elsewhere is invisible to the
   docs round-trip;
4. **doc round-trip**: the literal instrument names declared in
   ``metrics.py`` and the ``hvd_*`` names in ``docs/metrics.md`` must
   match exactly in both directions (histogram series suffixes
   ``_bucket``/``_sum``/``_count`` are derived, not instruments).
"""

from __future__ import annotations

import ast
import re

from ..core import Finding, Project, dotted_name

NAME = "metrics-registry"

_CONSTRUCTORS = ("counter", "gauge", "histogram")
_DOC_REL = "docs/metrics.md"
_DOC_TOKEN = re.compile(r"\bhvd_[a-z][a-z0-9_]*\b")
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _module_int_names(sf) -> set[str]:
    """Module-level names bound to an integer literal (the ad-hoc
    counter shape: ``_hits = 0``)."""
    out: set[str] = set()
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and type(node.value.value) is int):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _module_dict_names(sf) -> set[str]:
    """Module-level names bound to a dict literal / ``dict(...)`` call."""
    out: set[str] = set()
    for node in sf.tree.body:
        value = node.value if isinstance(node, ast.Assign) else None
        if value is None:
            continue
        is_dict = isinstance(value, (ast.Dict, ast.DictComp)) or (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Name)
            and value.func.id == "dict")
        if is_dict:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _check_adhoc_counters(project: Project, metrics_rel: str,
                          findings: list[Finding]) -> None:
    for sf in project.files:
        if sf.rel == metrics_rel:
            continue
        int_names = _module_int_names(sf)
        dict_names = _module_dict_names(sf)
        if not int_names and not dict_names:
            continue
        # collect names declared global anywhere in this module's
        # functions — module-level ints only count as counters when a
        # function rebinding them via `global` increments them
        global_names: set[str] = set()
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Global):
                global_names.update(node.names)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.AugAssign) or not isinstance(
                    node.op, (ast.Add, ast.Sub)):
                continue
            if sf.suppressed(NAME, node.lineno):
                continue
            target = node.target
            if (isinstance(target, ast.Name)
                    and target.id in int_names
                    and target.id in global_names):
                findings.append(Finding(
                    NAME, sf.rel, node.lineno,
                    f"module-level counter {target.id!r} mutated as "
                    "telemetry outside metrics.py: invisible to "
                    "/metrics, metrics_dump(), and the per-rank "
                    "loopback stores — register a Counter in "
                    "horovod_tpu/metrics.py (pragma non-telemetry "
                    "state: epochs, sequence numbers)"))
            elif (isinstance(target, ast.Subscript)
                  and isinstance(target.value, ast.Name)
                  and target.value.id in dict_names):
                findings.append(Finding(
                    NAME, sf.rel, node.lineno,
                    f"module-level dict {target.value.id!r} incremented "
                    "as telemetry outside metrics.py: this is an "
                    "unregistered labeled counter — register one in "
                    "horovod_tpu/metrics.py (pragma non-telemetry "
                    "state)"))
        # D[k] = D.get(k, ...) + ... — the setdefault-free increment
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Subscript)
                    and isinstance(node.targets[0].value, ast.Name)
                    and node.targets[0].value.id in dict_names
                    and isinstance(node.value, ast.BinOp)
                    and isinstance(node.value.op, ast.Add)):
                continue
            if sf.suppressed(NAME, node.lineno):
                continue
            dname = node.targets[0].value.id
            reads_get = any(
                isinstance(sub, ast.Call)
                and dotted_name(sub.func) == f"{dname}.get"
                for sub in ast.walk(node.value))
            if reads_get:
                findings.append(Finding(
                    NAME, sf.rel, node.lineno,
                    f"module-level dict {dname!r} incremented as "
                    "telemetry outside metrics.py (D[k] = D.get(k) + n) "
                    "— register a labeled Counter in "
                    "horovod_tpu/metrics.py"))


def _instrument_call(node: ast.AST) -> tuple[str, str] | None:
    """``(name, kind)`` of an instrument-constructor call — attribute
    style (``metrics.counter(...)``) or bare name after a
    ``from ... import counter`` (``counter(...)``) — else None. ``kind``
    is the constructor name (counter/gauge/histogram)."""
    if not (isinstance(node, ast.Call) and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith("hvd_")):
        return None
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in _CONSTRUCTORS:
        return node.args[0].value, func.attr
    if isinstance(func, ast.Name) and func.id in _CONSTRUCTORS:
        return node.args[0].value, func.id
    return None


def _check_constructor_sites(project: Project, metrics_rel: str,
                             findings: list[Finding]) -> None:
    for sf in project.files:
        if sf.rel == metrics_rel:
            continue
        for node in ast.walk(sf.tree):
            hit = _instrument_call(node)
            if hit is None or sf.suppressed(NAME, node.lineno):
                continue
            findings.append(Finding(
                NAME, sf.rel, node.lineno,
                f"instrument {hit[0]!r} declared outside "
                "horovod_tpu/metrics.py: the catalog is centralized "
                "there so docs/metrics.md and the exposition "
                "completeness gate see every instrument"))


def _inventory(project: Project, metrics_rel: str
               ) -> dict[str, tuple[int, str]]:
    """Instrument name -> (declaration line, kind), from
    literal-first-arg constructor calls in metrics.py (module level or
    not — the catalog convention is module level, but the round-trip
    should see every registration)."""
    sf = project.by_rel.get(metrics_rel)
    inv: dict[str, tuple[int, str]] = {}
    if sf is None:
        return inv
    for node in ast.walk(sf.tree):
        hit = _instrument_call(node)
        if hit is not None:
            inv.setdefault(hit[0], (node.lineno, hit[1]))
    return inv


def _check_doc_roundtrip(project: Project, metrics_rel: str,
                         inventory: dict[str, int],
                         findings: list[Finding]) -> None:
    doc_path = project.root / _DOC_REL
    if not doc_path.exists():
        findings.append(Finding(
            NAME, _DOC_REL, 1,
            "docs/metrics.md is missing — the instrument catalog must "
            "be documented"))
        return
    doc_names: dict[str, int] = {}
    for i, line in enumerate(doc_path.read_text().splitlines(), start=1):
        for m in _DOC_TOKEN.finditer(line):
            doc_names.setdefault(m.group(0), i)
    for name, (line, _kind) in sorted(inventory.items()):
        if name not in doc_names:
            findings.append(Finding(
                NAME, metrics_rel, line,
                f"instrument {name} is registered in metrics.py but "
                f"undocumented in {_DOC_REL}"))
    # _bucket/_sum/_count are derived series of HISTOGRAMS only — the
    # same token hanging off a counter/gauge name is a stale doc entry
    hist_derived = {f"{n}{s}" for n, (_l, kind) in inventory.items()
                    if kind == "histogram" for s in _HIST_SUFFIXES}
    for name, line in sorted(doc_names.items()):
        if name in inventory or name in hist_derived:
            continue
        findings.append(Finding(
            NAME, _DOC_REL, line,
            f"{_DOC_REL} documents {name}, which is not registered in "
            "horovod_tpu/metrics.py (stale entry, or the registration "
            "is missing)"))


def run(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    metrics_rel = f"{project.package_rel}/metrics.py"
    _check_adhoc_counters(project, metrics_rel, findings)
    _check_constructor_sites(project, metrics_rel, findings)
    # The doc round-trip only makes sense against the runtime's catalog
    # — a root without metrics.py (linting tools/) has nothing to diff
    # the docs against.
    if project.by_rel.get(metrics_rel) is not None:
        _check_doc_roundtrip(project, metrics_rel,
                             _inventory(project, metrics_rel), findings)
    return findings
