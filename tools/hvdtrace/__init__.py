"""hvdtrace: cross-rank conformance trace differ + protocol FSM validator.

The offline half of the lockstep conformance instrument
(``horovod_tpu/conformance.py`` is the runtime half): given the per-rank
trace files a conformance-enabled world dumped at shutdown/abort (or via
``hvd.conformance_dump()``), this tool

1. **groups** traces into comparable worlds by the rendezvous
   coordinates every trace header carries — ``(world, round, size,
   generation)`` — so one directory of dumps from an elastic run with
   many re-formed rounds diffs each round against itself;
2. **cross-diffs** every lockstep stream against the lowest-rank
   reference: the digest fast path compares final chain values (equal
   chains + equal event counts prove the whole stream byte-identical),
   and on mismatch a **binary search** over the cumulative per-event
   chain values localizes the FIRST divergent event — the chain at
   index *i* equals iff every event up to *i* matched, so prefix
   equality is monotone and bisectable;
3. **validates** each rank's trace against the protocol FSM — capture
   phase legality (seal only while recording, replay completion only
   from replay, no explicit transition into the implicit ``replayed``
   state), response-cache warm-handshake ordering (a non-empty confirm
   needs a prior non-empty restore), service lifecycle (events need a
   preceding ``svc_start``; no join after a coordinated abort), no
   locally-served batches after this rank joined, and knob-override
   epoch chaining/monotonicity.

Divergence reports quote both ranks' full payloads from the bounded
ring (when the event is still inside the ring window), the decision
site, and each side's knob-override epoch at the divergence point —
the localization the 600 s exchange-deadline hang never gives you.

Stdlib-only, like tools/hvdlint: the differ must run in CI (and on a
workstation over scp'd trace files) without importing the runtime.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path

__all__ = [
    "load_traces", "group_traces", "diff_group", "validate_fsm",
    "format_finding", "run_check",
]

TRACE_SCHEMA = 1
LOCKSTEP = "lockstep"
LOCAL = "local"

# events rows are [seq, stream, cls, site, kind, crc]; ring rows are
# [seq, site, kind, repr(payload)]
_E_SEQ, _E_STREAM, _E_CLS, _E_SITE, _E_KIND, _E_CRC = range(6)

_EPOCH_STREAM = "epoch"


# ---------------------------------------------------------------------------
# loading + grouping
# ---------------------------------------------------------------------------


def load_traces(paths) -> tuple[list[dict], list[str]]:
    """Load trace documents from files and/or directories (directories
    expand to their ``hvdtrace-*.json``). Returns ``(docs, errors)`` —
    an unreadable or wrong-schema file is an error string, not a crash:
    a partial dump from an aborted world must not mask the diff of the
    ranks that did dump."""
    docs: list[dict] = []
    errors: list[str] = []
    files: list[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(sorted(path.glob("hvdtrace-*.json")))
        else:
            files.append(path)
    for path in files:
        try:
            doc = json.loads(path.read_text())
        except (OSError, ValueError) as e:
            errors.append(f"{path}: unreadable trace ({e})")
            continue
        if not isinstance(doc, dict) or "events" not in doc:
            errors.append(f"{path}: not a conformance trace document")
            continue
        if doc.get("schema") != TRACE_SCHEMA:
            errors.append(f"{path}: unsupported trace schema "
                          f"{doc.get('schema')!r} (expected {TRACE_SCHEMA})")
            continue
        doc["_path"] = str(path)
        docs.append(doc)
    return docs, errors


def group_key(doc: dict) -> tuple:
    return (doc.get("world", ""), doc.get("round", ""),
            doc.get("size", -1), doc.get("generation", 0))


def group_traces(docs: list[dict]) -> dict[tuple, dict[str, dict]]:
    """``(world, round, size, generation) -> {label: doc}``. A rank that
    dumped more than once in an incarnation (on-demand dump + shutdown
    dump) keeps the longest trace — the others are prefixes of it."""
    groups: dict[tuple, dict[str, dict]] = {}
    for doc in docs:
        key = group_key(doc)
        label = doc.get("label") or f"rank{doc.get('rank', '?')}"
        held = groups.setdefault(key, {}).get(label)
        if held is None or doc.get("n_events", 0) > held.get("n_events", 0):
            groups[key][label] = doc
    return groups


# ---------------------------------------------------------------------------
# cross-rank diff
# ---------------------------------------------------------------------------


def _stream_events(doc: dict, stream: str) -> list[list]:
    return [e for e in doc.get("events", [])
            if e[_E_STREAM] == stream and e[_E_CLS] == LOCKSTEP]


def _ring_payload(doc: dict, seq: int) -> str | None:
    for row in doc.get("ring", []):
        if row[0] == seq:
            return row[3]
    return None


def _epoch_at(doc: dict, seq: int):
    """The knob-override epoch in force when event ``seq`` was recorded:
    the payload ``(old, new)`` of the last epoch event before it (new),
    or None when no epoch move was ever observed (epoch 0 throughout)."""
    last = None
    for e in doc.get("events", []):
        if e[_E_SEQ] >= seq:
            break
        if e[_E_STREAM] == _EPOCH_STREAM:
            last = e
    if last is None:
        return None
    payload = _ring_payload(doc, last[_E_SEQ])
    if payload is None:
        return f"crc:{last[_E_CRC]}"
    try:
        return ast.literal_eval(payload)[1]
    except (ValueError, SyntaxError, IndexError, TypeError):
        return payload


def _first_divergent_index(a_ev: list[list], b_ev: list[list]) -> int:
    """Smallest stream index where the chains disagree. The recorded crc
    at index i is the cumulative chain AFTER event i, so "prefix
    [0..i] identical" is a monotone predicate — binary search it."""
    n = min(len(a_ev), len(b_ev))
    lo, hi = 0, n  # invariant: prefix [0..lo) equal, first diff < hi
    while lo < hi:
        mid = (lo + hi) // 2
        if a_ev[mid][_E_CRC] == b_ev[mid][_E_CRC]:
            lo = mid + 1
        else:
            hi = mid
    return lo  # == n when the shared prefix fully matches (length skew)


def _event_view(doc: dict, ev: list | None) -> dict | None:
    if ev is None:
        return None
    return {
        "seq": ev[_E_SEQ],
        "site": ev[_E_SITE],
        "kind": ev[_E_KIND],
        "crc": ev[_E_CRC],
        "payload": _ring_payload(doc, ev[_E_SEQ]),
        "epoch": _epoch_at(doc, ev[_E_SEQ]),
    }


def diff_group(key: tuple, by_label: dict[str, dict]) -> list[dict]:
    """Cross-diff one comparable world: every rank's lockstep streams
    against the lowest-rank reference. Returns finding dicts."""
    world, rnd, size, generation = key
    base = {"world": world, "round": rnd, "size": size,
            "generation": generation}
    findings: list[dict] = []
    docs = sorted(by_label.values(), key=lambda d: (d.get("rank", 1 << 30),
                                                    d.get("label", "")))
    if isinstance(size, int) and size > 0 and len(docs) < size:
        have = [d.get("label") for d in docs]
        findings.append({**base, "type": "missing-ranks",
                         "have": have,
                         "missing": size - len(docs)})
    if len(docs) < 2:
        return findings
    ref = docs[0]
    streams: list[str] = []
    for d in docs:
        for s in d.get("chains", {}):
            if s not in streams:
                streams.append(s)
    for other in docs[1:]:
        for stream in streams:
            a_ev = _stream_events(ref, stream)
            b_ev = _stream_events(other, stream)
            # digest fast path: equal final chains + equal counts prove
            # the whole stream identical without touching the events
            if (len(a_ev) == len(b_ev)
                    and ref.get("chains", {}).get(stream, 0)
                    == other.get("chains", {}).get(stream, 0)):
                continue
            i = _first_divergent_index(a_ev, b_ev)
            a = a_ev[i] if i < len(a_ev) else None
            b = b_ev[i] if i < len(b_ev) else None
            if a is None and b is None:
                # counts matched but final chains differed — impossible
                # unless a trace was hand-edited; report it as-is
                pass
            findings.append({
                **base, "type": "divergence", "stream": stream,
                "index": i,
                "rank_a": ref.get("label"), "rank_b": other.get("label"),
                "a": _event_view(ref, a), "b": _event_view(other, b),
            })
    return findings


# ---------------------------------------------------------------------------
# per-rank protocol FSM
# ---------------------------------------------------------------------------


def _parse_payload(row):
    try:
        return ast.literal_eval(row[3])
    except (ValueError, SyntaxError):
        return None


def validate_fsm(doc: dict) -> list[dict]:
    """Validate one rank's trace against the protocol FSM. Payload-level
    rules read the bounded ring; when the ring no longer covers the
    trace head (``HVD_CONFORMANCE_RING`` smaller than the event count),
    "must be preceded by" rules are suppressed for the unseen prefix
    rather than reported as violations."""
    findings: list[dict] = []
    base = {"world": doc.get("world", ""), "round": doc.get("round", ""),
            "generation": doc.get("generation", 0),
            "rank": doc.get("label") or f"rank{doc.get('rank', '?')}"}

    def flag(rule: str, row, detail: str) -> None:
        findings.append({**base, "type": "fsm", "rule": rule,
                         "seq": row[0], "site": row[1], "kind": row[2],
                         "payload": row[3], "detail": detail})

    ring = list(doc.get("ring", []))
    truncated = bool(ring) and ring[0][0] > 0

    capture_state: str | None = None
    warm_ok: dict = {}          # pset -> a non-empty restore is pending
    started: set = set()        # psets with an observed svc_start
    aborted: set = set()        # psets under a coordinated abort
    joined: set = set()         # psets this rank joined
    prev_epoch = None

    for row in ring:
        _seq, site, kind, _payload = row
        payload = _parse_payload(row)

        if site.startswith("ops/step_capture.py::"):
            if kind == "phase" and isinstance(payload, (list, tuple)) \
                    and len(payload) == 2:
                frm, to = payload
                if to == "replayed":
                    flag("capture-phase", row,
                         "explicit transition into 'replayed' — that "
                         "state is only entered implicitly when a "
                         "sealed step's replay completes")
                if capture_state is not None and frm != capture_state:
                    flag("capture-phase", row,
                         f"phase claims from={frm!r} but the previous "
                         f"event left the state at {capture_state!r}")
                capture_state = to
            elif kind == "seal":
                if capture_state is not None and capture_state != "record":
                    flag("capture-seal", row,
                         f"seal while state={capture_state!r} — a step "
                         "can only seal from 'record'")
            elif kind == "replayed":
                if capture_state is not None and capture_state != "replay":
                    flag("capture-replay", row,
                         f"replay completion while state="
                         f"{capture_state!r} — only legal from 'replay'")
                capture_state = "replayed"

        elif site.startswith("negotiation/response_cache.py::"):
            pset = payload[0] if isinstance(payload, (list, tuple)) \
                and payload else None
            n = payload[1] if isinstance(payload, (list, tuple)) \
                and len(payload) > 1 else None
            if kind == "warm_restore":
                if isinstance(n, int) and n > 0:
                    warm_ok[pset] = True
            elif kind == "warm_confirm":
                if isinstance(n, int) and n > 0 \
                        and not warm_ok.get(pset) and not truncated:
                    flag("warm-order", row,
                         "non-empty warm confirm without a preceding "
                         "non-empty warm restore for this process set")
                warm_ok[pset] = False
            elif kind == "warm_drop":
                warm_ok[pset] = False
            elif kind == "served":
                if pset in joined:
                    flag("served-after-join", row,
                         "batch served from the response cache after "
                         "this rank joined — the join latch must end "
                         "local serving (docs/negotiation.md 'Joins')")

        elif site.startswith("engine_service.py::"):
            pset = payload[0] if isinstance(payload, (list, tuple)) \
                and payload else None
            if kind == "svc_start":
                started.add(pset)
                aborted.discard(pset)
                joined.discard(pset)
            else:
                if pset not in started and not truncated:
                    flag("service-lifecycle", row,
                         f"{kind} for process set {pset!r} without a "
                         "preceding svc_start")
                if kind == "svc_abort":
                    aborted.add(pset)
                elif kind == "join":
                    if pset in aborted:
                        flag("service-lifecycle", row,
                             "join after a coordinated abort — an "
                             "aborted service only stops")
                    joined.add(pset)

        elif kind == "epoch":
            if isinstance(payload, (list, tuple)) and len(payload) == 2:
                old, new = payload
                if prev_epoch is not None and old != prev_epoch:
                    flag("epoch-chain", row,
                         f"epoch move claims old={old!r} but the "
                         f"previous move ended at {prev_epoch!r}")
                if isinstance(old, int) and isinstance(new, int) \
                        and new <= old:
                    flag("epoch-chain", row,
                         f"non-monotone epoch move {old} -> {new}")
                prev_epoch = new
    return findings


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------


def _fmt_side(label: str, view: dict | None) -> str:
    if view is None:
        return f"    {label}: (no further events in this stream)"
    payload = view["payload"]
    quoted = payload if payload is not None else \
        f"(aged out of ring; chain crc={view['crc']})"
    return (f"    {label}: seq={view['seq']} {view['site']} "
            f"{view['kind']} payload={quoted}")


def format_finding(f: dict) -> str:
    where = f"world={f.get('world')!r} round={f.get('round')!r}"
    if f["type"] == "divergence":
        lines = [f"DIVERGENCE {where} stream={f['stream']} "
                 f"index={f['index']}: {f['rank_a']} vs {f['rank_b']}",
                 _fmt_side(f["rank_a"], f["a"]),
                 _fmt_side(f["rank_b"], f["b"])]
        ea = (f["a"] or {}).get("epoch")
        eb = (f["b"] or {}).get("epoch")
        if ea is not None or eb is not None:
            lines.append(f"    override epochs: {f['rank_a']}={ea} "
                         f"{f['rank_b']}={eb}")
        return "\n".join(lines)
    if f["type"] == "fsm":
        return (f"FSM {where} {f['rank']}: [{f['rule']}] seq={f['seq']} "
                f"{f['site']} {f['kind']} payload={f['payload']} — "
                f"{f['detail']}")
    if f["type"] == "missing-ranks":
        return (f"INCOMPLETE {where} size={f.get('size')}: "
                f"{f['missing']} rank trace(s) missing "
                f"(have {', '.join(f['have'])}) — a rank that never "
                "dumped usually died before shutdown; check its log")
    return repr(f)


def run_check(paths, fsm: bool = True) -> tuple[list[dict], list[str],
                                                dict]:
    """Load, group, diff, and FSM-validate. Returns
    ``(findings, errors, summary)``."""
    docs, errors = load_traces(paths)
    groups = group_traces(docs)
    findings: list[dict] = []
    for key in sorted(groups, key=repr):
        findings.extend(diff_group(key, groups[key]))
    if fsm:
        for doc in docs:
            findings.extend(validate_fsm(doc))
    summary = {
        "traces": len(docs),
        "groups": [
            {"world": k[0], "round": k[1], "size": k[2],
             "generation": k[3], "ranks": sorted(groups[k])}
            for k in sorted(groups, key=repr)],
        "divergences": sum(1 for f in findings
                           if f["type"] == "divergence"),
        "fsm_violations": sum(1 for f in findings if f["type"] == "fsm"),
        "incomplete_groups": sum(1 for f in findings
                                 if f["type"] == "missing-ranks"),
    }
    return findings, errors, summary
