"""CLI: ``python -m tools.hvdtrace <trace-file-or-dir>... [--json]
[--no-fsm]``.

Exit status: 0 = all comparable worlds diff clean and every trace
passes the protocol FSM, 1 = divergences or FSM violations found,
2 = usage error / no loadable traces. ``--json`` replaces the text
report with one JSON document (findings + per-group summary) for
structured consumers (the ci.sh annotation step).

Typical flows::

    # a conformance-enabled run dumped per-rank traces at shutdown
    HVD_CONFORMANCE=1 HVD_CONFORMANCE_DIR=/tmp/traces python train.py
    python -m tools.hvdtrace /tmp/traces

    # a hung world: SIGTERM the job (the abort path dumps), then
    python -m tools.hvdtrace /tmp/traces --json
"""

from __future__ import annotations

import argparse
import json
import sys

from . import format_finding, run_check


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvdtrace",
        description="cross-rank lockstep conformance trace differ + "
                    "protocol FSM validator (docs/conformance.md)")
    parser.add_argument("paths", nargs="*",
                        help="trace files and/or directories holding "
                             "hvdtrace-*.json dumps")
    parser.add_argument("--dir", dest="dirs", action="append",
                        metavar="DIR",
                        help="directory of trace dumps (repeatable; "
                             "same as a positional directory)")
    parser.add_argument("--json", action="store_true",
                        help="emit one JSON report instead of text")
    parser.add_argument("--no-fsm", action="store_true",
                        help="skip the per-rank protocol FSM validation "
                             "(cross-rank diff only)")
    args = parser.parse_args(argv)

    paths = list(args.paths) + list(args.dirs or [])
    if not paths:
        parser.print_usage(sys.stderr)
        print("hvdtrace: no trace files or directories given",
              file=sys.stderr)
        return 2
    findings, errors, summary = run_check(paths, fsm=not args.no_fsm)
    if summary["traces"] == 0:
        for e in errors:
            print(f"hvdtrace: {e}", file=sys.stderr)
        print("hvdtrace: no loadable conformance traces "
              f"under {', '.join(paths)}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "tool": "hvdtrace",
            "clean": not findings,
            "summary": summary,
            "findings": findings,
            "errors": errors,
        }, indent=2))
        return 1 if findings else 0

    for e in errors:
        print(f"hvdtrace: warning: {e}", file=sys.stderr)
    for f in findings:
        print(format_finding(f))
    groups = summary["groups"]
    if findings:
        print(f"hvdtrace: {summary['divergences']} divergence(s), "
              f"{summary['fsm_violations']} FSM violation(s) across "
              f"{summary['traces']} trace(s) in {len(groups)} world(s)",
              file=sys.stderr)
        return 1
    print(f"hvdtrace: clean ({summary['traces']} traces, "
          f"{len(groups)} comparable world(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
