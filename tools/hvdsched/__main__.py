"""CLI: ``HVD_SCHED_CHECK=1 python -m tools.hvdsched [options]``.

Default: explore every clean-matrix model (``models.MATRIX``) with the
schedule budget split across them; any finding prints its full report
plus the ``(seed, trace)`` replay line and exits 1. ``--demos`` runs
the known-bad fixtures instead and exits 1 unless exploration FINDS
every planted bug (detector sanity). ``--replay FILE`` re-runs one
recorded schedule byte-for-byte from a JSON ``{model, seed, trace}``.

Exit status: 0 = gate passed, 1 = findings (or a demo not found),
2 = usage error. ``--json`` replaces the per-model text lines with one
JSON document carrying each model's exploration accounting (schedules
run, branch points, pruned/swept counts, findings) plus the budget
split — structured consumers (the ci.sh starvation gate) check that
the ceil-divided per-model budget left no model under-explored.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _ensure_env() -> None:
    """The cooperative seam must be active BEFORE any horovod_tpu
    module creates primitives; the CLI owns its process, so it sets
    the knob unconditionally (an exported HVD_SCHED_CHECK=0 would
    otherwise silently run the models on real threads: the unguarded
    demos then deadlock for real, and the matrix gate prints a
    meaningless 'clean') and refreshes the cached flag."""
    import os
    os.environ["HVD_SCHED_CHECK"] = "1"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # models deliberately simulate failures (poison records, aborts);
    # their ERROR logs are expected output, not gate noise. CLI-layer
    # seeding BEFORE the runtime imports — the registry isn't up yet.
    os.environ.setdefault("HVD_LOG_LEVEL", "fatal")  # hvdlint: disable=knob-registry
    from horovod_tpu.utils import invariants
    invariants.refresh()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="hvdsched",
        description="deterministic schedule-exploration checker for the "
                    "horovod_tpu concurrency core "
                    "(docs/schedule_checker.md)")
    parser.add_argument("--model", action="append", metavar="NAME",
                        help="explore only this model (repeatable); "
                             "default: the clean matrix")
    parser.add_argument("--schedules", type=int, default=None,
                        help="total schedule budget (default: "
                             "HVD_SCHED_SCHEDULES or 200)")
    parser.add_argument("--seed", type=int, default=None,
                        help="base PRNG seed (default: HVD_SCHED_SEED "
                             "or 0)")
    parser.add_argument("--max-steps", type=int, default=20000,
                        help="livelock bound per schedule")
    parser.add_argument("--demos", action="store_true",
                        help="run the known-bad fixtures; fail unless "
                             "every planted bug is FOUND")
    parser.add_argument("--replay", metavar="FILE",
                        help="replay one schedule from a JSON file "
                             "{model, seed, trace}")
    parser.add_argument("--json", dest="as_json", action="store_true",
                        help="emit one JSON report (per-model explored-"
                             "schedule accounting + budget split) "
                             "instead of text lines")
    parser.add_argument("--list", action="store_true",
                        help="list models and exit")
    args = parser.parse_args(argv)

    _ensure_env()
    from horovod_tpu.utils import envs

    from . import SchedFailure, explore, run_model
    from . import models as _models

    if args.list:
        for name in _models.MATRIX:
            print(f"{name} [matrix]")
        for name in _models.DEMOS:
            print(f"{name} [demo]")
        return 0

    seed = (args.seed if args.seed is not None
            else envs.get_int(envs.SCHED_SEED, 0))
    budget = (args.schedules if args.schedules is not None
              else envs.get_int(envs.SCHED_SCHEDULES, 200))

    if args.replay:
        with open(args.replay) as f:
            rec = json.load(f)
        fn = _models.MODELS.get(rec["model"])
        if fn is None:
            print(f"hvdsched: unknown model {rec['model']!r}",
                  file=sys.stderr)
            return 2
        try:
            run_model(fn, seed=int(rec["seed"]), trace=rec["trace"],
                      max_steps=args.max_steps)
        except SchedFailure as fail:
            print(f"replay of {rec['model']!r} reproduced: {fail}")
            return 1
        print(f"replay of {rec['model']!r}: clean "
              "(the recorded schedule no longer fails)")
        return 0

    pool = _models.DEMOS if args.demos else _models.MATRIX
    if args.model:
        unknown = [m for m in args.model if m not in _models.MODELS]
        if unknown:
            print(f"hvdsched: unknown model(s) {unknown}; --list shows "
                  "the catalog", file=sys.stderr)
            return 2
        pool = {m: _models.MODELS[m] for m in args.model}

    # ceil-divide: a --schedules budget is a floor for the run, so the
    # per-model split must round up, never shave the total under it
    per_model = max(-(-budget // max(len(pool), 1)), 1)
    failed = False
    records: list[dict] = []
    for name, fn in pool.items():
        t0 = time.perf_counter()
        result = explore(fn, schedules=per_model, seed=seed,
                         max_steps=args.max_steps)
        dt = time.perf_counter() - t0
        records.append({
            "model": name,
            "demo": bool(args.demos),
            "seconds": round(dt, 3),
            "runs": result.runs,
            "branch_points": result.branch_points,
            "pruned": result.pruned,
            "swept": result.swept,
            "findings": len(result.findings),
            "found": not result.ok,
        })
        if args.demos:
            found = not result.ok
            if not args.as_json:
                print(f"{name}: planted bug "
                      f"{'FOUND' if found else 'NOT FOUND'} — "
                      f"{result.summary()} [{dt:.1f}s]")
            if found:
                f0 = result.findings[0]
                if not args.as_json:
                    print(f"  kind={f0.kind} seed={f0.seed} "
                          f"trace={f0.trace!r}")
            else:
                failed = True
                if args.as_json:
                    print(f"hvdsched: demo {name!r} NOT FOUND",
                          file=sys.stderr)
        else:
            if not args.as_json:
                print(f"{name}: {result.summary()} [{dt:.1f}s]")
            for f0 in result.findings:
                failed = True
                # replay coordinates survive --json runs on stderr: a
                # structured consumer (the ci.sh gate) must never eat
                # the (seed, trace) a human needs to reproduce
                out = sys.stderr if args.as_json else sys.stdout
                print(f"--- {name} finding "
                      f"(replay: --model {name} + seed/trace below)",
                      file=out)
                print(str(f0), file=out)
    if args.as_json:
        print(json.dumps({
            "tool": "hvdsched",
            "demos": bool(args.demos),
            "budget": budget,
            "per_model": per_model,
            "models": len(pool),
            "clean": not failed,
            "results": records,
        }, indent=2))
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
