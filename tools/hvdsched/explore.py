"""hvdsched schedule exploration: seed sweeps + DPOR-lite branching.

Two complementary strategies over a *model* (a zero-argument callable
that builds fresh state and exercises the concurrency core; see
``models.py``):

1. **Seed sweep** — run the model under N distinct PRNG seeds. Cheap,
   unbiased, and the strategy that finds "wide" races (many schedules
   hit them).

2. **Targeted preemption branching (DPOR-lite)** — from each clean
   run's recorded decision points, re-run with the schedule *forced* to
   diverge at one point: replay the decision prefix byte-for-byte, pick
   a different runnable task there, then continue randomly from a seed
   derived from (base seed, step, alternative). Branch points are
   pruned with a dependence heuristic in the spirit of dynamic
   partial-order reduction:

   * an alternative whose pending operation touches a **different
     primitive** than the chosen task's operation commutes with it —
     flipping the order yields an equivalent schedule, so the branch is
     skipped (counted in ``pruned``);
   * conflicting branch points are **ranked** by whether the primitive
     participates in the run's recorded acquisition-order edge graph
     (the same held->acquired edges the ``HVD_DEBUG_INVARIANTS``
     lock-order witness records): nested locks are where ordering bugs
     live, so they are explored first; leaf primitives come after.

Every failing schedule carries ``(seed, trace)``; feed them back to
:func:`run_model` (or ``python -m tools.hvdsched --replay``) for a
byte-for-byte reproduction.
"""

from __future__ import annotations

import zlib
from collections import deque

from .runtime import Runtime, SchedFailure

_DEFAULT_MAX_STEPS = 20000


def run_model(fn, *, seed: int = 0, trace=None,
              max_steps: int = _DEFAULT_MAX_STEPS):
    """One controlled run of ``fn``. Returns a ``Result`` on a clean
    run; raises :class:`SchedFailure` (deadlock / lost-wakeup /
    livelock / replay divergence) or the model's own exception."""
    return Runtime(seed=seed, trace=trace, max_steps=max_steps).run(fn)


class ExploreResult:
    """Outcome of :func:`explore`: the findings (empty = clean) and the
    exploration accounting."""

    __slots__ = ("findings", "runs", "branch_points", "pruned", "swept")

    def __init__(self):
        self.findings: list[SchedFailure] = []
        self.runs = 0
        self.branch_points = 0
        self.pruned = 0
        self.swept = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def summary(self) -> str:
        state = ("clean" if self.ok
                 else f"{len(self.findings)} finding(s)")
        return (f"{state} over {self.runs} schedule(s) "
                f"({self.swept} seed-swept, {self.branch_points} branched, "
                f"{self.pruned} pruned as equivalent)")


def _derived_seed(seed: int, step: int, alt: int) -> int:
    return zlib.crc32(f"{seed}:{step}:{alt}".encode()) & 0x7FFFFFFF


def _branch_prefixes(result, seed: int, tried: set, stats: ExploreResult,
                     min_step: int = 0):
    """(priority, prefix, derived seed) candidates from one clean run's
    decision points — one per conflicting alternative choice."""
    out = []
    for point in result.points:
        step = point["step"]
        if step < min_step:
            continue
        chosen = point["chosen"]
        chosen_op = point["ops"].get(chosen)
        chosen_res = chosen_op[1] if chosen_op else None
        for alt in point["runnable"]:
            if alt == chosen:
                continue
            alt_op = point["ops"].get(alt)
            alt_res = alt_op[1] if alt_op else None
            prefix = tuple(result.trace[:step]) + (alt,)
            if prefix in tried:
                continue
            if (chosen_res is None or alt_res is None
                    or chosen_res != alt_res):
                # independent ops commute: an equivalent schedule
                stats.pruned += 1
                tried.add(prefix)
                continue
            tried.add(prefix)
            in_edges = any(chosen_res in e for e in result.edges)
            out.append((0 if in_edges else 1, list(prefix),
                        _derived_seed(seed, step, alt)))
    out.sort(key=lambda item: item[0])
    return out


def explore(fn, *, schedules: int = 200, seed: int = 0,
            max_steps: int = _DEFAULT_MAX_STEPS,
            stop_on_first: bool = True) -> ExploreResult:
    """Sweep ``schedules`` total runs of ``fn``: half fresh seeds, half
    targeted preemption branches off clean runs (the branch frontier is
    drained first when it has work). Returns an :class:`ExploreResult`;
    model contract assertions surface as replayable ``model-assertion``
    findings (the runtime wraps them with ``(seed, trace)``), while
    other model-body exceptions propagate (they are bugs in the model,
    not schedule findings)."""
    stats = ExploreResult()
    tried: set = set()
    frontier: deque = deque()
    next_fresh = 0

    def attempt(s, trace=None, branched=False):
        stats.runs += 1
        if branched:
            stats.branch_points += 1
        else:
            stats.swept += 1
        try:
            return run_model(fn, seed=s, trace=trace, max_steps=max_steps)
        except SchedFailure as f:
            if f.kind == "replay-divergence" and branched:
                # the forced prefix pushed the model somewhere the
                # recorded run never went (e.g. a task finished
                # earlier); not a bug, just an infeasible branch
                return None
            stats.findings.append(f)
            return None

    while stats.runs < schedules:
        if frontier:
            _prio, prefix, dseed = frontier.popleft()
            res = attempt(dseed, trace=prefix, branched=True)
            # branch only past the forced divergence: the shared prefix
            # was already harvested by the run it came from
            min_step = len(prefix)
        else:
            res = attempt(seed + next_fresh)
            next_fresh += 1
            min_step = 0
        if stats.findings and stop_on_first:
            break
        if res is not None:
            for item in _branch_prefixes(res, res.seed, tried, stats,
                                         min_step=min_step):
                frontier.append(item)
    return stats
