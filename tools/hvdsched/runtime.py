"""hvdsched runtime: the cooperative serializing scheduler.

The model checker's core mechanism (docs/schedule_checker.md): every
thread participating in a model run is a *managed task* — a real OS
thread that only executes while the controller has scheduled it. At
every interleaving point (lock acquire/release, condition wait/notify,
event wait/set/clear, sleep, thread spawn/join) the running task parks
on its own semaphore and hands control back to the controller, which
picks the next runnable task from a **seeded PRNG** (or from a replay
trace). Exactly one task runs at a time, so a run is fully determined
by ``(model, seed, trace)`` — any failing schedule replays
byte-for-byte.

Time is **virtual**: ``sleep`` and timed waits record a wake deadline on
the virtual clock, and the clock only advances when no task is runnable
(to the earliest deadline). A model therefore never waits wall-clock
time, and timer-paced code (the fusion cycle loop, watchdog beats,
retry backoff) runs deterministically.

Built-in failure detectors, all of which raise :class:`SchedFailure`
carrying ``(seed, trace)`` and a full report (decision trace + every
blocked task's stack):

* **deadlock** — every live task is blocked with no virtual-clock
  deadline and the lock wait graph contains a cycle;
* **lost-wakeup** — same stuck condition, but the wait graph is acyclic
  and at least one task waits on a condition/event that no live task
  can ever signal;
* **livelock** — the schedule exceeds ``max_steps`` decisions without
  completing;
* **replay divergence** — a supplied trace names a task that is not
  runnable at that step (the model changed under the trace).

The runtime also records the **acquisition-order edge graph** (held
lock -> acquired lock) that the explorer uses to rank preemption
points — the dynamic twin of ``utils/invariants.py``'s lock-order
witness edges.
"""

from __future__ import annotations

import random
import sys
import threading
import time
import traceback

RUNNABLE = "runnable"
BLOCKED = "blocked"
DONE = "done"

_DEFAULT_MAX_STEPS = 20000


class SchedError(RuntimeError):
    """Misuse of the runtime itself (not a model finding)."""


class SchedExit(BaseException):
    """Raised inside managed threads during teardown to unwind them.
    A ``BaseException`` so ``except Exception`` handlers in the code
    under test cannot absorb the unwind."""


class SchedFailure(AssertionError):
    """A schedule-level finding (deadlock / lost-wakeup / livelock /
    replay divergence / model exception). Carries everything needed to
    replay the exact schedule: ``seed`` and ``trace`` (the decision
    list), plus a human-readable ``report``."""

    def __init__(self, kind: str, message: str, *, seed: int,
                 trace: list[int], report: str = ""):
        self.kind = kind
        self.seed = seed
        self.trace = list(trace)
        self.report = report
        super().__init__(
            f"[{kind}] {message}\n"
            f"replay: seed={seed} trace={self.trace!r}\n{report}")


class _Task:
    __slots__ = ("tid", "name", "thread", "gate", "state", "daemon",
                 "wait_kind", "wait_resource", "wake_at", "timed_out",
                 "op", "error", "joiners", "held")

    def __init__(self, tid: int, name: str, daemon: bool):
        self.tid = tid
        self.name = name
        self.thread: threading.Thread | None = None
        self.gate = threading.Semaphore(0)
        self.state = RUNNABLE
        self.daemon = daemon
        self.wait_kind: str | None = None
        self.wait_resource = None
        self.wake_at: float | None = None
        self.timed_out = False
        self.op: tuple | None = ("start", self.name if name else "")
        self.error: BaseException | None = None
        self.joiners: list["_Task"] = []
        self.held: list = []  # cooperative locks currently held

    def __repr__(self):
        return f"<task {self.tid}:{self.name} {self.state}>"


class Result:
    """A completed (clean) run: the decision trace, the per-decision
    snapshots the explorer branches from, and the acquisition-order
    edges observed."""

    __slots__ = ("seed", "trace", "points", "edges", "steps", "clock")

    def __init__(self, seed, trace, points, edges, steps, clock):
        self.seed = seed
        self.trace = trace
        self.points = points
        self.edges = edges
        self.steps = steps
        self.clock = clock


_active: "Runtime | None" = None


def active() -> "Runtime | None":
    return _active


def current():
    """``(runtime, task)`` when the calling thread is a managed task of
    the active runtime (and the run is not tearing down), else None."""
    rt = _active
    if rt is None or rt._finishing:
        return None
    task = rt._by_ident.get(threading.get_ident())
    if task is None:
        return None
    return rt, task


def check_exit() -> None:
    """Unwind managed threads during teardown: any blocking primitive
    entered by a managed thread of a finishing runtime raises
    :class:`SchedExit` instead of really blocking."""
    rt = _active
    if (rt is not None and rt._finishing
            and threading.get_ident() in rt._by_ident):
        raise SchedExit


class Runtime:
    """One controlled model run. Use :meth:`run`; the calling thread
    becomes the controller, ``fn`` runs as the non-daemon ``main``
    task."""

    def __init__(self, seed: int = 0, trace=None,
                 max_steps: int = _DEFAULT_MAX_STEPS, name: str = "model"):
        self.seed = int(seed)
        self.name = name
        self.rng = random.Random(self.seed)
        self.replay = list(trace) if trace else None
        self.max_steps = int(max_steps)
        self.clock = 0.0
        self.decisions: list[int] = []
        self.points: list[dict] = []
        self.edges: set[tuple[str, str]] = set()
        self.failure: SchedFailure | None = None
        self.tasks: dict[int, _Task] = {}
        self._by_ident: dict[int, _Task] = {}
        self._ctrl = threading.Semaphore(0)
        self._next_tid = 0
        self._finishing = False
        self._error: BaseException | None = None

    # -- task lifecycle ----------------------------------------------------

    def spawn(self, target, *, name: str, daemon: bool = True,
              args=(), kwargs=None) -> threading.Thread:
        kwargs = kwargs or {}
        tid = self._next_tid
        self._next_tid += 1
        task = _Task(tid, name or f"task-{tid}", daemon)
        th = threading.Thread(
            target=self._wrapper, args=(task, target, args, kwargs),
            name=task.name, daemon=True)
        task.thread = th
        self.tasks[tid] = task
        th.start()
        spawner = self._by_ident.get(threading.get_ident())
        if spawner is not None and not self._finishing:
            self._yield(spawner, ("spawn", task.name))
        return th

    def _wrapper(self, task: _Task, target, args, kwargs) -> None:
        self._by_ident[threading.get_ident()] = task
        task.gate.acquire()  # wait to be scheduled the first time
        if not self._finishing:
            try:
                target(*args, **kwargs)
            except SchedExit:
                pass
            except BaseException as e:  # surfaced by the controller
                task.error = e
        self._finish_task(task)

    def _finish_task(self, task: _Task) -> None:
        task.state = DONE
        if (task.held and not self._finishing and task.error is None):
            # a thread exiting while holding a lock is a permanent
            # deadlock in real threading (locks are never auto-released
            # by a dying owner) — report it rather than mask it with
            # the unwind-path force-release below
            task.error = SchedFailure(
                "lock-leak",
                f"task {task.name!r} exited holding "
                f"{[l.name for l in task.held]!r}: a real thread's exit "
                "never releases its locks, so any waiter blocks forever",
                seed=self.seed, trace=self.decisions,
                report=self._describe(
                    [t for t in self._ordered() if t.state != DONE]))
        for lock in list(task.held):  # a dying task must not wedge others
            try:
                lock._owner = None
                lock._count = 0
                for w in lock._waiters:
                    w.state = RUNNABLE
                lock._waiters.clear()
            except Exception:  # hvdlint: disable=silent-except
                pass  # best-effort unwedge of a simulated lock's guts
        task.held.clear()
        for j in task.joiners:
            if j.state == BLOCKED and j.wait_kind == "join":
                j.state = RUNNABLE
        task.joiners.clear()
        self._ctrl.release()

    # -- park / yield / block ----------------------------------------------

    def _park(self, task: _Task) -> None:
        self._ctrl.release()
        task.gate.acquire()
        if self._finishing:
            raise SchedExit

    def _yield(self, task: _Task, op: tuple) -> None:
        """A schedule point: the task stays runnable but hands control
        back so the scheduler may run someone else first."""
        task.op = op
        self._park(task)

    def _block(self, task: _Task, kind: str, resource,
               wake_at: float | None, op: tuple | None = None) -> bool:
        """Park blocked on ``resource``; returns False when woken by the
        virtual-clock deadline instead of a signal."""
        task.state = BLOCKED
        task.wait_kind = kind
        task.wait_resource = resource
        task.wake_at = wake_at
        task.op = op or (kind, _rname(resource))
        self._park(task)
        task.wait_kind = None
        task.wait_resource = None
        task.wake_at = None
        timed_out, task.timed_out = task.timed_out, False
        return not timed_out

    # -- cooperative primitive operations ----------------------------------

    def lock_acquire(self, lock, task: _Task, blocking: bool = True,
                     timeout: float = -1) -> bool:
        self._yield(task, ("acquire", lock.name))
        deadline = None
        if blocking and timeout is not None and timeout >= 0:
            deadline = self.clock + timeout
        while True:
            if lock._owner is None:
                lock._owner = task
                lock._count = 1
                for h in task.held:
                    if h is not lock:
                        self.edges.add((h.name, lock.name))
                task.held.append(lock)
                return True
            if lock._owner is task and lock._reentrant:
                lock._count += 1
                return True
            if not blocking:
                return False
            lock._waiters.append(task)
            if not self._block(task, "lock", lock, deadline):
                return False  # virtual-clock timeout
            # woken by a release (or the owner dying): re-contend

    def lock_release(self, lock, task: _Task) -> None:
        if lock._owner is not task:
            raise SchedError(
                f"release of {lock.name!r} by {task.name!r}, owned by "
                f"{getattr(lock._owner, 'name', None)!r}")
        lock._count -= 1
        if lock._count == 0:
            lock._owner = None
            task.held.remove(lock)
            for w in lock._waiters:
                w.state = RUNNABLE
            lock._waiters.clear()
        self._yield(task, ("release", lock.name))

    def cv_wait(self, cv, task: _Task, timeout: float | None = None) -> bool:
        lock = cv._coop_lock
        if lock._owner is not task:
            raise SchedError(f"cv {cv.name!r}: wait() without the lock")
        saved = lock._count
        lock._count = 0
        lock._owner = None
        task.held.remove(lock)
        for w in lock._waiters:
            w.state = RUNNABLE
        lock._waiters.clear()
        cv._waiters.append(task)
        deadline = None if timeout is None else self.clock + timeout
        signaled = self._block(task, "cv", cv, deadline,
                               op=("cv-wait", cv.name))
        self.lock_acquire(lock, task)
        lock._count = saved
        return signaled

    def cv_notify(self, cv, task: _Task, n: int) -> None:
        if cv._coop_lock._owner is not task:
            raise SchedError(f"cv {cv.name!r}: notify() without the lock")
        woken, cv._waiters[:n] = cv._waiters[:n], []
        for w in woken:
            w.state = RUNNABLE  # each re-acquires the lock when scheduled
        self._yield(task, ("notify", cv.name))

    def event_wait(self, ev, task: _Task,
                   timeout: float | None = None) -> bool:
        self._yield(task, ("event-wait", ev.name))
        if ev._flag:
            return True
        deadline = None if timeout is None else self.clock + timeout
        ev._waiters.append(task)
        self._block(task, "event", ev, deadline)
        return ev._flag

    def event_set(self, ev, task: _Task) -> None:
        ev._flag = True
        for w in ev._waiters:
            w.state = RUNNABLE
        ev._waiters.clear()
        self._yield(task, ("set", ev.name))

    def event_clear(self, ev, task: _Task) -> None:
        ev._flag = False
        self._yield(task, ("clear", ev.name))

    def sleep(self, task: _Task, seconds: float) -> None:
        self._block(task, "sleep", None, self.clock + max(seconds, 0.0),
                    op=("sleep", f"{seconds:g}"))

    def join(self, thread: threading.Thread, task: _Task,
             timeout: float | None = None) -> None:
        target = next((t for t in self.tasks.values()
                       if t.thread is thread), None)
        if target is None:
            raise SchedError("join of a thread the runtime never spawned")
        if target.state == DONE:
            self._yield(task, ("join", target.name))
            return
        target.joiners.append(task)
        deadline = None if timeout is None else self.clock + timeout
        if not self._block(task, "join", target, deadline,
                           op=("join", target.name)):
            if task in target.joiners:
                target.joiners.remove(task)

    # -- the controller ----------------------------------------------------

    def run(self, fn) -> Result:
        """Run ``fn`` as the model's main task under this runtime's
        schedule. Raises the model's own exception, or
        :class:`SchedFailure` on a detector hit; returns a
        :class:`Result` on a clean run."""
        global _active
        if _active is not None:
            raise SchedError("an hvdsched runtime is already active "
                             "(model runs cannot nest)")
        _active = self
        try:
            self.spawn(fn, name="main", daemon=False)
            self._controller_loop()
        finally:
            self._teardown()
            _active = None
        if self._error is not None:
            if (isinstance(self._error, AssertionError)
                    and not isinstance(self._error, SchedFailure)):
                # a model CONTRACT assertion (entry never settled, a
                # waiter hung) is a schedule finding: it must carry the
                # (seed, trace) replay data like every other detector,
                # not escape as a bare AssertionError the explorer and
                # the CI gate cannot reproduce
                raise SchedFailure(
                    "model-assertion", str(self._error),
                    seed=self.seed, trace=self.decisions) from self._error
            raise self._error
        if self.failure is not None:
            raise self.failure
        return Result(self.seed, list(self.decisions), self.points,
                      set(self.edges), len(self.decisions), self.clock)

    def _ordered(self) -> list[_Task]:
        return [self.tasks[k] for k in sorted(self.tasks)]

    def _controller_loop(self) -> None:
        while True:
            tasks = self._ordered()
            errored = next((t for t in tasks if t.error is not None), None)
            if errored is not None:
                self._error = errored.error
                return
            live = [t for t in tasks if t.state != DONE]
            if not any(not t.daemon for t in live):
                return  # model complete; leftover daemons torn down
            runnable = [t for t in live if t.state == RUNNABLE]
            if not runnable:
                timed = [t for t in live if t.wake_at is not None]
                if timed:
                    self.clock = max(self.clock,
                                     min(t.wake_at for t in timed))
                    for t in timed:
                        if t.wake_at is not None and t.wake_at <= self.clock:
                            self._wake_timeout(t)
                    continue
                self._fail_stuck(live)
                return
            if len(self.decisions) >= self.max_steps:
                self.failure = SchedFailure(
                    "livelock",
                    f"schedule exceeded {self.max_steps} decisions "
                    "without completing",
                    seed=self.seed, trace=self.decisions,
                    report=self._describe(live))
                return
            chosen = self._choose(runnable)
            if chosen is None:
                return  # replay divergence recorded
            chosen.gate.release()
            self._ctrl.acquire()

    def _wake_timeout(self, task: _Task) -> None:
        res = task.wait_resource
        waiters = getattr(res, "_waiters", None)
        if waiters is not None and task in waiters:
            waiters.remove(task)
        if task.wait_kind == "join" and res is not None:
            if task in res.joiners:
                res.joiners.remove(task)
        task.timed_out = task.wait_kind != "sleep"
        task.state = RUNNABLE

    def _choose(self, runnable: list[_Task]) -> _Task | None:
        runnable = sorted(runnable, key=lambda t: t.tid)
        k = len(self.decisions)
        if self.replay is not None and k < len(self.replay):
            want = self.replay[k]
            chosen = next((t for t in runnable if t.tid == want), None)
            if chosen is None:
                self.failure = SchedFailure(
                    "replay-divergence",
                    f"trace step {k} schedules task {want}, but runnable "
                    f"tasks are {[t.tid for t in runnable]} — the model "
                    "diverged from the recorded run",
                    seed=self.seed, trace=self.decisions)
                return None
        else:
            chosen = runnable[self.rng.randrange(len(runnable))]
        self.points.append({
            "step": k,
            "runnable": [t.tid for t in runnable],
            "ops": {t.tid: t.op for t in runnable},
            "chosen": chosen.tid,
        })
        self.decisions.append(chosen.tid)
        return chosen

    # -- stuck detection ---------------------------------------------------

    def _fail_stuck(self, live: list[_Task]) -> None:
        cycle = self._lock_cycle(live)
        if cycle:
            kind = "deadlock"
            message = ("all threads blocked; lock wait cycle: "
                       + " -> ".join(cycle))
        elif any(t.wait_kind in ("cv", "event") for t in live):
            kind = "lost-wakeup"
            waiters = [t for t in live if t.wait_kind in ("cv", "event")]
            message = ("all threads blocked; "
                       + ", ".join(f"{t.name} waits on "
                                   f"{t.wait_kind} {_rname(t.wait_resource)!r}"
                                   for t in waiters)
                       + " with no live thread able to signal")
        else:
            kind = "deadlock"
            message = "all threads blocked with a non-empty wait graph"
        self.failure = SchedFailure(kind, message, seed=self.seed,
                                    trace=self.decisions,
                                    report=self._describe(live))

    def _lock_cycle(self, live: list[_Task]) -> list[str] | None:
        """A cycle in task -> lock-owner edges, as lock names."""
        waits = {}
        for t in live:
            if t.wait_kind == "lock" and t.wait_resource is not None:
                owner = t.wait_resource._owner
                if owner is not None:
                    waits[t.tid] = (owner.tid, t.wait_resource.name)
        for start in waits:
            seen, path = {start}, []
            cur = start
            while cur in waits:
                nxt, lname = waits[cur]
                path.append(lname)
                if nxt == start:
                    return path
                if nxt in seen:
                    break
                seen.add(nxt)
                cur = nxt
        return None

    def _describe(self, live: list[_Task]) -> str:
        frames = sys._current_frames()
        lines = [f"decision trace ({len(self.decisions)} steps): "
                 f"{self.decisions!r}",
                 "tasks:"]
        for t in self._ordered():
            held = ",".join(l.name for l in t.held) or "-"
            what = (f"{t.wait_kind} on {_rname(t.wait_resource)!r}"
                    if t.state == BLOCKED else t.state)
            lines.append(f"  [{t.tid}] {t.name}: {what} "
                         f"(held: {held}, daemon: {t.daemon})")
            if t.state == BLOCKED and t.thread is not None:
                frame = frames.get(t.thread.ident)
                if frame is not None:
                    stack = traceback.format_stack(frame)
                    # drop the runtime's own park frames from the tail
                    stack = [s for s in stack
                             if "/hvdsched/runtime.py" not in s]
                    lines.append("".join(stack[-8:]).rstrip())
        return "\n".join(lines)

    # -- teardown ----------------------------------------------------------

    def _teardown(self) -> None:
        self._finishing = True
        for _ in range(500):
            alive = [t for t in self.tasks.values()
                     if t.state != DONE and t.thread is not None
                     and t.thread.is_alive()]
            if not alive:
                break
            for t in alive:
                t.gate.release()
            # simulated-scheduler drain tick, not an I/O retry: the
            # unified backoff policy is part of the system under test
            time.sleep(0.001)  # hvdlint: disable=silent-except
        for t in self.tasks.values():
            if t.thread is not None and t.thread.is_alive():
                t.thread.join(timeout=1.0)


def _rname(res) -> str:
    if res is None:
        return "-"
    return getattr(res, "name", None) or str(res)
