"""hvdsched model library: the concurrency-core race matrix.

Each model is a zero-argument callable that builds **fresh** state
(its own ``FusionScheduler`` / ``HealthWatchdog``), drives a racy
scenario through the real runtime code, asserts the user-visible
contract (every waiter unblocks; every entry ends done with a result
or an error), and tears its threads down. Models run under
:func:`~.explore.run_model` / :func:`~.explore.explore` with
``HVD_SCHED_CHECK=1`` active *before* the model constructs its state,
so every lock/condition/event/thread/sleep routes through the
cooperative scheduler.

Two registries:

* ``MATRIX`` — the in-tree code must survive exploration of these with
  **zero** findings; ``ci.sh`` sweeps them (the schedule-exploration
  gate). They cover scheduler-enqueue x executor-flush x ``abort()`` x
  watchdog-poison x ``flush_all`` quiesce, plus the guarded PR-3/PR-6
  shapes running against the current protections.
* ``DEMOS`` — known-bad fixtures that exploration MUST flag (detector
  sanity, pinned regression traces): a lock inversion, a missed-signal
  lost wakeup, and the PR-3/PR-6 deadlock shapes with their guards
  removed.
"""

from __future__ import annotations

NDEVICES = 2  # virtual devices in the rendezvous models


def _fusion():
    from horovod_tpu.ops import fusion_cycle
    return fusion_cycle


def _inv():
    from horovod_tpu.utils import invariants
    return invariants


def _opaque(fc, name, value=None, fail=None, nbytes=0):
    def run():
        if fail is not None:
            raise fail
        return value if value is not None else name
    return fc._Entry([None], False, nbytes, [name], run=run, label=name)


def _sparse_spec(fc):
    return fc._QueueSpec("sparse", None, None, svc=None)


def _assert_settled(entries) -> None:
    for e in entries:
        if not e.event.wait(30.0):
            raise AssertionError(
                f"entry {e.label!r} never settled (event unset)")
        if e.error is None and e.results is None:
            raise AssertionError(
                f"entry {e.label!r} settled with neither results nor error")


# ---------------------------------------------------------------------------
# the clean race matrix (must explore with zero findings)
# ---------------------------------------------------------------------------

def enqueue_flush_quiesce():
    """Two producers enqueue + threshold-flush against the pipelined
    executor and the cycle timer; a flush_all drains; quiesce must
    leave every entry dispatched."""
    inv, fc = _inv(), _fusion()
    sched = fc.FusionScheduler()
    entries: list = []

    def producer(i):
        spec = _sparse_spec(fc)
        for j in range(2):
            e = _opaque(fc, f"p{i}.{j}", value=(i, j))
            entries.append(e)
            sched.enqueue(("sparse", f"k{i}"), spec, e)
        sched.flush_queue(("sparse", f"k{i}"), "threshold")

    t1 = inv.spawn_thread(producer, name="prod-1", args=(1,))
    t2 = inv.spawn_thread(producer, name="prod-2", args=(2,))
    inv.join_thread(t1)
    inv.join_thread(t2)
    sched.flush_all("barrier")
    _assert_settled(entries)
    for e in entries:
        if e.error is not None:
            raise AssertionError(f"clean flush errored: {e.error!r}")
    sched.stop()


def flush_abort_race():
    """abort() racing producers and the executor: every entry must
    settle (result if its flush won the race, abort error otherwise) —
    no waiter may hang, the exact contract the PR-5 coordinated abort
    promises."""
    inv, fc = _inv(), _fusion()
    sched = fc.FusionScheduler()
    entries: list = []

    def producer(i):
        spec = _sparse_spec(fc)
        for j in range(2):
            e = _opaque(fc, f"a{i}.{j}")
            entries.append(e)
            sched.enqueue(("sparse", f"k{i}"), spec, e)
            if j:
                sched.flush_queue(("sparse", f"k{i}"), "threshold")

    def aborter():
        sched.abort("chaos: simulated service reset")

    ts = [inv.spawn_thread(producer, name="prod-1", args=(1,)),
          inv.spawn_thread(producer, name="prod-2", args=(2,)),
          inv.spawn_thread(aborter, name="aborter")]
    for t in ts:
        inv.join_thread(t)
    sched.flush_all("shutdown")
    _assert_settled(entries)
    sched.stop()


def quiesce_enqueue_race():
    """flush_all quiesce racing a live producer: quiesce must return
    (no self-wait, no lost notify) and everything submitted before the
    final drain must settle."""
    inv, fc = _inv(), _fusion()
    sched = fc.FusionScheduler()
    entries: list = []

    def producer():
        spec = _sparse_spec(fc)
        for j in range(3):
            e = _opaque(fc, f"q.{j}")
            entries.append(e)
            sched.enqueue(("sparse", "kq"), spec, e)

    def drainer():
        sched.flush_all("barrier")

    ts = [inv.spawn_thread(producer, name="producer"),
          inv.spawn_thread(drainer, name="drainer"),
          inv.spawn_thread(drainer, name="drainer-2")]
    for t in ts:
        inv.join_thread(t)
    sched.flush_all("barrier")
    _assert_settled(entries)
    sched.stop()


class _DictKV:
    """Non-blocking in-memory KV for watchdog models."""

    def __init__(self):
        self.d: dict[str, bytes] = {}

    def put(self, key, value):
        self.d[key] = value

    def get(self, key):
        return self.d.get(key)

    def keys(self, prefix):
        return [k for k in sorted(self.d) if k.startswith(prefix)]


def watchdog_poison_abort():
    """Watchdog-poison x executor abort x a blocked waiter: a peer's
    poison record must convert into on_failure -> scheduler abort, and
    a thread waiting on a pending entry must unblock with either a
    result (its flush won) or the abort error — never hang."""
    import horovod_tpu.health as health
    inv, fc = _inv(), _fusion()
    kv = _DictKV()
    sched = fc.FusionScheduler()
    spec = _sparse_spec(fc)
    entry = _opaque(fc, "wd.0")
    sched.enqueue(("sparse", "kw"), spec, entry)
    outcomes: list = []
    decided = inv.make_event("model.watchdog.decided")

    def on_failure(rank, reason):
        outcomes.append(("failed", rank))
        sched.abort(f"peer rank {rank} failed: {reason}")
        decided.set()

    wd = health.HealthWatchdog(kv, 2, 0, "hb", on_failure,
                               interval_s=0.01, timeout_s=0.05)
    wd.start()

    def waiter():
        entry.event.wait(30.0)

    def poisoner():
        kv.put("hb/poison/1", b"simulated peer error")

    ts = [inv.spawn_thread(waiter, name="waiter"),
          inv.spawn_thread(poisoner, name="poisoner")]
    for t in ts:
        inv.join_thread(t)
    # virtual-clock wait: the watchdog tick that sees the poison may be
    # several HVD_HEALTH_INTERVAL periods away
    if not decided.wait(60.0):
        raise AssertionError("watchdog never converted the poison record")
    _assert_settled([entry])
    wd.stop()
    sched.flush_all("shutdown")
    sched.stop()
    if not outcomes:
        raise AssertionError("watchdog decision event without an outcome")


def capture_replay_abort():
    """Step capture lifecycle racing producers, the flush executor, and
    abort(): a record->seal transition while enqueues land concurrently,
    then a replay step whose held entries race an abort() mid-stream.
    Contract: every entry settles (replayed/fallback result if its step
    won the race, abort error otherwise), no boundary or waiter can
    hang. The plan constructor is stubbed (pure Python — no XLA
    programs) so exploration drives the real CaptureState lock/handoff
    structure, not device compute."""
    inv, fc = _inv(), _fusion()
    from horovod_tpu.ops import dispatch_cache, step_capture
    dispatch_cache.reset()
    sched = fc.FusionScheduler()
    cap = sched.capture
    cap.force_enabled = True
    built = [0]

    def stub_build(key, records):
        built[0] += 1

        def run_step(entries_per_record):
            return [[("replayed", e.label) for e in entries
                     for _ in range(e.count)]
                    for entries in entries_per_record]
        return step_capture.StepPlan(key, records, run_step, 0,
                                     len(records))

    cap._build_plan = stub_build
    entries: list = []

    def stream(i, phase):
        spec = _sparse_spec(fc)
        for j in range(2):
            e = _opaque(fc, f"cap{phase}.{i}.{j}", value=(i, j))
            entries.append(e)
            sched.enqueue(("sparse", f"k{i}"), spec, e)
        sched.flush_queue(("sparse", f"k{i}"), "threshold")

    # record step: the recording flushes race the producers' enqueues
    cap.boundary()
    ts = [inv.spawn_thread(stream, name=f"rec-{i}", args=(i, 0))
          for i in (1, 2)]
    for t in ts:
        inv.join_thread(t)
    sched.flush_all("barrier")
    # boundary seals the recording and arms replay; the replay stream
    # then races an abort() — entries settle as replayed results, eager
    # fallbacks, or abort errors depending on the schedule
    cap.boundary()
    if built[0] != 1 or cap._state != "replay":
        raise AssertionError(
            "model precondition broken: the seal must build the stub "
            f"plan and arm replay (built={built[0]}, state={cap._state!r})"
            " — without an armed replay this model explores nothing")
    ts = [inv.spawn_thread(stream, name=f"rep-{i}", args=(i, 1))
          for i in (1, 2)]
    ts.append(inv.spawn_thread(
        lambda: sched.abort("chaos: simulated reset mid-replay"),
        name="aborter"))
    for t in ts:
        inv.join_thread(t)
    cap.boundary(closing=True)
    sched.flush_all("shutdown")
    _assert_settled(entries)
    sched.stop()
    dispatch_cache.reset()


# -- the PR-3 rendezvous shape (guarded = current code's issue lock) --------

def _rendezvous_model(guarded: bool):
    """Two threads each launch one multi-device program by appending a
    per-device participant to every device queue; each device executes
    its queue in FIFO order and a program only completes when EVERY
    device has arrived at it (the collective rendezvous). Interleaved
    launches put the programs in a different order on each device —
    both devices then wait forever for a participant the other will
    never run: the exact XLA CPU deadlock PR 3 reproduced. The guarded
    variant wraps launch in the real ``program_issue.issue_serialized``
    and must survive exploration."""
    inv = _inv()
    from horovod_tpu.ops import program_issue
    cv = inv.make_condition("model.rendezvous.cv")
    queues: list[list[str]] = [[] for _ in range(NDEVICES)]
    arrived: dict[str, int] = {}

    def launch(prog):
        for d in range(NDEVICES):
            with cv:
                queues[d].append(prog)
                cv.notify_all()

    if guarded:
        # the current protection, straight from the tree: if someone
        # removes the program-issue lock, this model deadlocks. The
        # module-level RLock was created at import time (before
        # HVD_SCHED_CHECK could take effect for test-scoped runs), so
        # re-create it through the seam if it is not yet cooperative.
        from . import primitives
        if not isinstance(program_issue._ISSUE_LOCK, primitives.RLock):
            program_issue._ISSUE_LOCK = inv.make_rlock("program_issue.issue")
        launch = program_issue.issue_serialized(launch)

    def device(d):
        for _ in range(2):  # two programs total, one participant each
            with cv:
                while not queues[d]:
                    cv.wait()
                prog = queues[d].pop(0)
                arrived[prog] = arrived.get(prog, 0) + 1
                cv.notify_all()
                while arrived[prog] < NDEVICES:
                    cv.wait()
                cv.notify_all()

    ts = [inv.spawn_thread(device, name=f"device-{d}", args=(d,))
          for d in range(NDEVICES)]
    ts += [inv.spawn_thread(launch, name="launch-A", args=("progA",)),
           inv.spawn_thread(launch, name="launch-B", args=("progB",))]
    for t in ts:
        inv.join_thread(t)


def pr3_issue_lock():
    _rendezvous_model(guarded=True)


def pr3_unguarded():
    _rendezvous_model(guarded=False)


# -- the PR-6 starvation shape (guarded = eager-chain auto-disable) ---------

def _starvation_model(guarded: bool):
    """A shared 2-slot execution pool (the XLA CPU client's per-device
    thread pool), an in-flight 2-chunk collective whose chunks each
    need a pool slot, and two consumer programs that depend on the
    collective's result. Unguarded consumers grab a slot FIRST and then
    block on the result — with both slots held by blocked consumers the
    chunks can never run and the result never materializes (the PR-6
    eager-chain starvation). The guarded variant materializes the
    result before consumers claim slots (``HVD_EAGER_CHAIN`` auto-off
    on CPU) and must survive exploration."""
    inv = _inv()
    pool_cv = inv.make_condition("model.pool.cv")
    free = [2]  # pool slots
    result = inv.make_event("model.collective.result")
    chunks_done = [0]

    def take_slot():
        with pool_cv:
            while free[0] == 0:
                pool_cv.wait()
            free[0] -= 1

    def put_slot():
        with pool_cv:
            free[0] += 1
            pool_cv.notify_all()

    def chunk(_i):
        take_slot()
        chunks_done[0] += 1
        if chunks_done[0] == 2:
            result.set()
        put_slot()

    def consumer(_i):
        if guarded:
            result.wait()  # materialize before claiming compute
            take_slot()
        else:
            take_slot()
            result.wait()  # chained on an in-flight collective
        put_slot()

    ts = [inv.spawn_thread(consumer, name=f"consumer-{i}", args=(i,))
          for i in range(2)]
    ts += [inv.spawn_thread(chunk, name=f"chunk-{i}", args=(i,))
           for i in range(2)]
    for t in ts:
        inv.join_thread(t)


def pr6_chain_guard():
    _starvation_model(guarded=True)


def pr6_unguarded():
    _starvation_model(guarded=False)


class _FakePset:
    """Minimal process-set stand-in for QoS tenancy models: carries the
    two attributes ``qos.tenant_label`` reads (no runtime init needed,
    so the model stays pure-Python under exploration)."""

    is_global = False

    def __init__(self, pid: int):
        self.process_set_id = pid


def qos_admission():
    """Multi-tenant QoS clean matrix (ISSUE 12): two tenants' producers
    enqueue + threshold-flush through the admission gate — skewed
    weights, tenant 8 behind a shed quota — while an ``abort()`` races
    the quota accounting, the window pump, the executor demand pull,
    and ``flush_all``'s gate release. Contract: every entry settles
    with a result, a deterministic :class:`QosAdmissionError` (shed),
    or the abort error — no waiter hangs, no parked batch is lost in
    the gate across the abort."""
    import os

    from horovod_tpu import qos
    from horovod_tpu.exceptions import QosAdmissionError
    inv, fc = _inv(), _fusion()
    prev = {k: os.environ.get(k)
            for k in ("HVD_QOS", "HVD_QOS_WINDOW", "HVD_QOS_QUANTUM")}
    os.environ["HVD_QOS"] = "1"
    os.environ["HVD_QOS_WINDOW"] = "1"
    os.environ["HVD_QOS_QUANTUM"] = "64"
    qos.reset()
    try:
        qos.configure_label("7", priority=1, weight=4.0)
        qos.configure_label("8", weight=1.0, pending_bytes_quota=96,
                            policy="shed")
        sched = fc.FusionScheduler()
        psets = {7: _FakePset(7), 8: _FakePset(8)}
        entries: list = []

        def producer(pid):
            spec = fc._QueueSpec("sparse", psets[pid], None, svc=None)
            for j in range(3):
                e = _opaque(fc, f"t{pid}.{j}", value=(pid, j), nbytes=48)
                entries.append(e)
                sched.enqueue(("sparse", f"k{pid}"), spec, e)
                sched.flush_queue(("sparse", f"k{pid}"), "threshold")

        def aborter():
            sched.abort("chaos: simulated service reset")

        ts = [inv.spawn_thread(producer, name="tenant-7", args=(7,)),
              inv.spawn_thread(producer, name="tenant-8", args=(8,)),
              inv.spawn_thread(aborter, name="aborter")]
        for t in ts:
            inv.join_thread(t)
        sched.flush_all("shutdown")
        _assert_settled(entries)
        for e in entries:
            if e.error is None:
                continue
            if not isinstance(e.error, (QosAdmissionError, RuntimeError)):
                raise AssertionError(
                    f"entry {e.label!r} failed with unexpected "
                    f"{e.error!r}")
        # a shed entry must NEVER carry results (raises, not wrong data)
        for e in entries:
            if isinstance(e.error, QosAdmissionError) and e.results:
                raise AssertionError(
                    f"shed entry {e.label!r} carries results {e.results!r}")
        sched.stop()
    finally:
        qos.reset()
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def loopback_exchange():
    """The loopback world's negotiation-round rendezvous (ISSUE 10): N
    rank tasks race submit/exchange/deliver on the shared
    ``LoopbackHub`` across two rounds while a watchdog-poison task fails
    the world mid-flight. Contract: every participant either receives
    its round's result or the poison error — no deadlock, no lost
    wakeup, no waiter left parked. Some ranks completing a racing round
    while others observe the poison is legal (exactly the real
    coordinated-abort race); a rank recording NOTHING is not."""
    inv = _inv()
    from horovod_tpu.loopback.hub import LoopbackHub
    hub = LoopbackHub("model")
    n = 3
    failed: list = []

    def fail_check():
        return RuntimeError("watchdog: peer dead") if failed else None

    outcomes: list = [[] for _ in range(n)]

    def rank(r):
        for round_id in range(2):
            try:
                out = hub.exchange_compute(
                    ("red", round_id), r, n, r + 1,
                    lambda vals: sum(vals), timeout=30.0,
                    failure_check=fail_check)
                outcomes[r].append(out)
            except RuntimeError as e:
                outcomes[r].append(e)
                return

    ts = [inv.spawn_thread(rank, name=f"rank-{r}", args=(r,))
          for r in range(n)]

    def poisoner():
        failed.append(1)
        hub.fail_all(RuntimeError("watchdog: peer dead"))

    tp = inv.spawn_thread(poisoner, name="watchdog")
    for t in ts:
        inv.join_thread(t)
    inv.join_thread(tp)
    for r in range(n):
        if not outcomes[r]:
            raise AssertionError(f"rank {r} recorded no outcome")
        first = outcomes[r][0]
        if not (isinstance(first, RuntimeError) or first == 6):
            raise AssertionError(f"rank {r} round 0 outcome {first!r}")


def hier_negotiation():
    """ISSUE-13: the two-level negotiation round (member posts to its
    group, the leader aggregates, one cross-leader exchange, the agreed
    response fans back down) rendezvousing over a cooperative KV, raced
    by a LEADER-death task that poisons the world mid-round (the
    watchdog's coordinated abort). World 3, G=2: groups [0,1] and [2],
    leaders 0 and 2 — leader 2 is also a one-member group (the ragged
    G∤world shape). Contract: every rank either returns its round's
    fan-down table or raises the failure; no waiter parks forever, and
    any completing rank's table carries every rank's frame. The waits
    re-check their predicate ATOMICALLY under the condition — the exact
    window the planted ``leader-lost-wakeup-demo`` leaves open."""
    inv = _inv()
    cv = inv.make_condition("hier.cv")
    kv: dict = {}
    failed: list = []
    groups = {0: [0, 1], 1: [2]}
    leader_of = {0: 0, 1: 2}
    gid_of = {0: 0, 1: 0, 2: 1}

    def put(key, val):
        with cv:
            kv[key] = val
            cv.notify_all()

    def wait_for(pred):
        with cv:
            while True:
                got = pred()
                if got is not None:
                    return got
                if failed:
                    raise RuntimeError("watchdog: leader dead")
                cv.wait(5.0)

    outcomes: dict = {}

    def rank(r):
        gid = gid_of[r]
        try:
            put(("m", gid, r), f"frame{r}")
            if leader_of[gid] == r:
                members = groups[gid]
                blob = wait_for(lambda: (
                    {m: kv[("m", gid, m)] for m in members}
                    if all(("m", gid, m) in kv for m in members)
                    else None))
                put(("x", gid), blob)
                table = wait_for(lambda: (
                    {r2: f for g in groups for r2, f in
                     kv.get(("x", g), {}).items()}
                    if all(("x", g) in kv for g in groups) else None))
                put(("r", gid), table)
                outcomes[r] = table
            else:
                outcomes[r] = wait_for(lambda: kv.get(("r", gid)))
        except RuntimeError as e:
            outcomes[r] = e

    def leader_killer():
        with cv:
            failed.append(1)
            cv.notify_all()

    ts = [inv.spawn_thread(rank, name=f"rank-{r}", args=(r,))
          for r in gid_of]
    tk = inv.spawn_thread(leader_killer, name="leader-killer")
    for t in ts:
        inv.join_thread(t)
    inv.join_thread(tk)
    for r in gid_of:
        if r not in outcomes:
            raise AssertionError(f"rank {r} recorded no outcome")
        out = outcomes[r]
        if not isinstance(out, RuntimeError):
            if sorted(out) != [0, 1, 2]:
                raise AssertionError(
                    f"rank {r} fan-down table incomplete: {out!r}")


# ---------------------------------------------------------------------------
# known-bad demos (exploration MUST find these)
# ---------------------------------------------------------------------------


def leader_lost_wakeup_demo():
    """PLANTED leader-lost-wakeup (ISSUE-13): a member checks for its
    group's fan-down response OUTSIDE the condition and only then parks
    — a schedule where the leader publishes and notifies inside that
    window loses the wakeup and the member waits for a notify that
    already happened, exactly the bug class the real hierarchical
    round's atomic check-and-wait (see ``hier_negotiation``) closes.
    Most schedules pass; exploration must FIND the window and the
    finding replays byte-for-byte from (seed, trace)."""
    inv = _inv()
    cv = inv.make_condition("hierdemo.cv")
    kv: dict = {}

    def leader():
        with cv:
            kv["r0"] = {"table": "agreed"}
            cv.notify_all()

    def member():
        if "r0" not in kv:  # BUG: check and wait are not atomic
            with cv:
                cv.wait()

    ts = [inv.spawn_thread(member, name="member"),
          inv.spawn_thread(leader, name="leader")]
    for t in ts:
        inv.join_thread(t)


def loopback_exchange_unguarded():
    """The loopback rendezvous WITHOUT the hub's atomic check-and-wait:
    the waiter tests slot completion OUTSIDE the condition lock, so a
    peer completing the slot in that window notifies nobody and the
    waiter parks forever — the lost-wakeup class
    ``LoopbackHub.exchange_compute`` closes by re-checking under the
    condition. Most schedules pass; exploration must FIND the window,
    and the finding replays byte-for-byte from (seed, trace)."""
    inv = _inv()
    cv = inv.make_condition("lbdemo.cv")
    slot = {"values": {}, "done": False, "result": None}
    n = 2

    def rank(r):
        with cv:
            slot["values"][r] = r + 1
            if len(slot["values"]) == n:
                slot["result"] = sum(slot["values"].values())
                slot["done"] = True
                cv.notify_all()
                return
        # BUG: completion check and wait are not atomic
        if not slot["done"]:
            with cv:
                cv.wait()

    ts = [inv.spawn_thread(rank, name=f"rank-{r}", args=(r,))
          for r in range(n)]
    for t in ts:
        inv.join_thread(t)

def elastic_reform():
    """Elastic re-form clean matrix (ISSUE 14): a worker commit loop, a
    peer-death report recording a registry failure, and the driver's
    resume publishing the next round — all racing a waiter blocked on
    the round advance. Models the driver shape (`_round_lock` +
    `_wait_hosts_cond` + registry-driven resume): every transition is an
    atomic check-and-wait under the round condition, so exploration must
    find no schedule where the blocked waiter misses the round notify or
    two resumes publish the same round twice."""
    inv = _inv()
    round_cv = inv.make_condition("reform.round_cv")
    round_lock = inv.make_lock("reform.round_lock")
    state = {"round": 1, "failures": 0, "published": []}

    def publish_resume():
        # the driver's _activate_workers: round transitions serialize on
        # the round lock; publication and notify are atomic under the cv
        with round_lock:
            with round_cv:
                state["round"] += 1
                state["published"].append(state["round"])
                round_cv.notify_all()

    def commit_waiter():
        # a worker blocked in its reset waiting for the next round: the
        # check and the wait are atomic under the condition (the
        # guarded twin of the stale-plan demo's bug shape)
        with round_cv:
            while state["round"] < 2:
                if not round_cv.wait(30.0):
                    raise AssertionError(
                        "blocked waiter missed the round notify")

    def peer_death_reporter():
        # bootstrap observer path: record failure, then resume NOW
        state["failures"] += 1
        publish_resume()

    def discovery_resume():
        # discovery-thread path: a host change resumes concurrently
        publish_resume()

    ts = [inv.spawn_thread(commit_waiter, name="commit-waiter"),
          inv.spawn_thread(peer_death_reporter, name="peerfail-report"),
          inv.spawn_thread(discovery_resume, name="disco-resume")]
    for t in ts:
        inv.join_thread(t)
    if state["round"] != 3:
        raise AssertionError(f"rounds lost/duplicated: {state}")
    if state["published"] != [2, 3]:
        raise AssertionError(f"non-monotonic publication: {state}")


def autoscale_decision():
    """Autoscale decision clean matrix (ISSUE 15): a policy decision
    racing a watchdog peer-failure report (which re-forms the round)
    and a worker blocked at its commit boundary. Models the guarded
    shape ``elastic/policy.py`` actually ships: the decision is
    ROUND-TAGGED at evaluation and the apply re-validates the tag
    atomically under the round lock — a re-form landing between
    evaluate and apply degrades the decision to a counted hold, never
    a membership mutation against the wrong world. Exploration must
    find no schedule where a stale decision mutates hosts, the blocked
    commit waiter misses the round notify, or the two resumes publish
    a duplicate round."""
    inv = _inv()
    round_cv = inv.make_condition("autoscale.round_cv")
    round_lock = inv.make_lock("autoscale.round_lock")
    state = {"round": 1, "hosts": {"h0", "h1", "h2"},
             "decisions": [], "published": []}

    def policy():
        # evaluate: snapshot the round tag (under the lock, like the
        # driver's rendezvous read), then "think" (a preemption point),
        # then apply with the tag re-validated atomically
        with round_lock:
            tag = state["round"]
            victim = "h2"
        with round_lock:
            if state["round"] != tag or victim not in state["hosts"]:
                state["decisions"].append(("hold", "stale-round", tag))
                return
            state["hosts"].discard(victim)
            state["hosts"].add("auto0")
            state["decisions"].append(("evict", "straggler", tag))

    def peer_death_reporter():
        # watchdog report -> registry failure -> resume publishes the
        # next round; the dead host's replacement inherits its slot
        with round_lock:
            with round_cv:
                state["round"] += 1
                state["published"].append(state["round"])
                state["hosts"] = {"h0", "h1", "h2b"}
                round_cv.notify_all()

    def commit_waiter():
        with round_cv:
            while state["round"] < 2:
                if not round_cv.wait(30.0):
                    raise AssertionError(
                        "commit waiter missed the round notify")

    ts = [inv.spawn_thread(policy, name="policy"),
          inv.spawn_thread(peer_death_reporter, name="peerfail-report"),
          inv.spawn_thread(commit_waiter, name="commit-waiter")]
    for t in ts:
        inv.join_thread(t)
    if state["published"] != [2]:
        raise AssertionError(f"rounds lost/duplicated: {state}")
    (action, reason, tag) = state["decisions"][0]
    if action == "evict":
        # an applied eviction must have run against round 1's world:
        # h2 replaced by auto0, and the re-form then owns the hosts
        if tag != 1:
            raise AssertionError(f"evict applied with a stale tag: {state}")
    else:
        # held: the re-form won the race and membership is untouched
        # by the policy (h2b is the REPORTER's replacement, not ours)
        if reason != "stale-round" or "auto0" in state["hosts"]:
            raise AssertionError(f"stale decision mutated hosts: {state}")


def evict_during_reform_demo():
    """PLANTED stale eviction (ISSUE 15): the policy resolves its
    victim from the decision round's table but applies WITHOUT
    re-validating the round tag — a schedule where the re-form lands
    between evaluate and apply evicts the innocent replacement that
    inherited the dead host's slot (the exact misattribution the
    round-tag check in ``AutoscalePolicy._apply_evict`` closes, and
    the driver-side twin of PR 14's stale peer-failure report). Most
    schedules pass; exploration must FIND the window and the
    model-assertion finding replays byte-for-byte from (seed, trace)."""
    inv = _inv()
    mu = inv.make_lock("evictdemo.mu")
    state = {"round": 1, "hosts": {"h0", "h1", "h2"}}

    def policy():
        with mu:
            tag = state["round"]
            victim = "h2"  # blamed in round 1
        # BUG: no round re-validation at apply — a re-form in this
        # window renames the world and "h2" now labels the replacement
        with mu:
            if state["round"] != tag:
                raise AssertionError(
                    f"stale-round eviction applied: decision round "
                    f"{tag}, world already re-formed to round "
                    f"{state['round']} — evicting the replacement that "
                    f"inherited the slot")
            state["hosts"].discard(victim)

    def reformer():
        with mu:
            state["round"] += 1
            state["hosts"] = {"h0", "h1", "h2"}  # replacement, same label

    ts = [inv.spawn_thread(policy, name="policy"),
          inv.spawn_thread(reformer, name="reformer")]
    for t in ts:
        inv.join_thread(t)


def stale_plan_after_resize_demo():
    """PLANTED stale-plan-after-resize (ISSUE 14): a dispatch-plan cache
    keyed WITHOUT the process-set shape, read outside the resize lock —
    a schedule where the elastic resize lands between the worker's cache
    read and its execute serves a plan compiled for the OLD world size,
    the exact staleness class the shape-keyed shelve/restore in
    ``ops/dispatch_cache.py`` (docs/elastic.md) closes by construction.
    Most schedules pass; exploration must FIND the window and the
    model-assertion finding replays byte-for-byte from (seed, trace)."""
    inv = _inv()
    mu = inv.make_lock("staleplan.mu")
    world = {"size": 4}
    cache: dict = {}

    def worker():
        # BUG: the plan key ignores the world shape and the read is not
        # atomic with the execute — a resize in between serves a plan
        # compiled for the old world
        if "allreduce" not in cache:
            with mu:
                cache["allreduce"] = {"compiled_world": world["size"]}
        plan = cache["allreduce"]
        if plan["compiled_world"] != world["size"]:
            raise AssertionError(
                f"stale plan served: compiled for world "
                f"{plan['compiled_world']}, executing at world "
                f"{world['size']}")

    def resizer():
        # elastic re-form: the world shrinks; the cache SHOULD have been
        # shelved by shape, but this model's key has no shape to match
        with mu:
            world["size"] = 2

    ts = [inv.spawn_thread(worker, name="worker"),
          inv.spawn_thread(resizer, name="resizer")]
    for t in ts:
        inv.join_thread(t)


def deadlock_demo():
    """Classic two-lock inversion: T1 takes a then b, T2 takes b then
    a. Some schedules deadlock; the report must name both locks — the
    same edge the HVD_DEBUG_INVARIANTS lock-order witness records."""
    inv = _inv()
    a = inv.make_lock("demo.a")
    b = inv.make_lock("demo.b")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    ts = [inv.spawn_thread(t1, name="t1"), inv.spawn_thread(t2, name="t2")]
    for t in ts:
        inv.join_thread(t)


def lost_wakeup_demo():
    """Missed-signal bug: the waiter checks the flag OUTSIDE the lock,
    so a schedule where the setter fires between the check and the wait
    leaves the waiter waiting for a notify that already happened. Most
    schedules pass — only exploration finds the window."""
    inv = _inv()
    cv = inv.make_condition("demo.cv")
    flag: list = []

    def setter():
        with cv:
            flag.append(1)
            cv.notify_all()

    def waiter():
        if not flag:  # BUG: check/wait are not atomic
            with cv:
                cv.wait()

    ts = [inv.spawn_thread(waiter, name="waiter"),
          inv.spawn_thread(setter, name="setter")]
    for t in ts:
        inv.join_thread(t)


def qos_inversion_demo():
    """PLANTED priority-inversion deadlock (ISSUE 12): a low-priority
    batch holds the last executor slot while a high-priority submission
    waits behind a quota-blocked enqueue. The BUG is the shape the real
    block-policy quota avoids by construction (``_qos_admit``: an
    atomic check-and-wait on granted-but-unsettled bytes the executor
    settles on its own): here the quota check reads the slot state
    OUTSIDE the condition's atomic check-and-wait, so a schedule where
    the low-priority batch frees the slot between the check and the
    wait loses the notify — the high-priority enqueue parks forever
    while the executor waits for a grant only that producer can make.
    Most schedules pass; exploration must FIND the window and the
    finding replays byte-for-byte from (seed, trace)."""
    inv = _inv()
    cv = inv.make_condition("qosdemo.cv")
    state = {"slot_busy": True, "granted": []}

    def executor():
        with cv:
            state["slot_busy"] = False  # the low-prio batch completes
            cv.notify_all()
            while not state["granted"]:  # serve the next grant
                cv.wait()

    def high_prio_producer():
        # BUG: quota check and wait are not atomic — the inversion
        if state["slot_busy"]:
            with cv:
                cv.wait()
        with cv:
            state["granted"].append("high")
            cv.notify_all()

    ts = [inv.spawn_thread(executor, name="executor"),
          inv.spawn_thread(high_prio_producer, name="producer-high")]
    for t in ts:
        inv.join_thread(t)


def ckpt_snapshot():
    """The checkpoint state plane's snapshot writer (docs/checkpoint.md)
    racing the commit trigger and re-form teardown: commits hand leaf
    slices to the background thread (latest-wins replacement), a
    ``stop()`` lands mid-write, and a late commit races the stop. The
    invariant asserted is the manifest protocol's whole contract: every
    manifest on disk names only complete digest-verified shards, and
    ``latest`` only ever points at a step whose manifest exists — no
    interleaving may expose a torn tree."""
    import json
    import os
    import shutil
    import tempfile

    import numpy as np

    from horovod_tpu import checkpoint as ck
    inv = _inv()
    tmp = tempfile.mkdtemp(prefix="hvdsched-ckpt-")
    plane = ck.StatePlane(tmp, rank=0, world=1, interval=1)

    class _State:  # the State.commit surface the trigger reads
        _commits = 0
        _saved_state: dict = {}

    state = _State()

    def committer():
        for _ in range(3):
            state._commits += 1
            state._saved_state = {"w": np.full(4, state._commits)}
            plane.note_commit(state)

    def teardown():
        plane.stop()  # the re-form path: stop may land mid-write

    ts = [inv.spawn_thread(committer, name="committer"),
          inv.spawn_thread(teardown, name="teardown")]
    for t in ts:
        inv.join_thread(t)
    plane.stop()
    try:
        manifests = {}
        for name in os.listdir(tmp):
            if name.startswith("manifest-") and name.endswith(".json"):
                with open(os.path.join(tmp, name), "rb") as f:
                    man = json.loads(f.read().decode())
                manifests[int(man["step"])] = man
                for meta in man["shards"]:
                    p = os.path.join(
                        ck.step_dir(tmp, man["step"]),
                        f"shard-{meta['lo']}-{meta['hi']}.bin")
                    with open(p, "rb") as f:
                        payload = f.read()
                    if ck.shard_digest(payload) != meta["digest"]:
                        raise AssertionError(
                            f"manifest for step {man['step']} names a "
                            f"corrupt shard [{meta['lo']},{meta['hi']})")
        latest = os.path.join(tmp, "latest")
        if os.path.exists(latest):
            with open(latest, "rb") as f:
                pointed = int(f.read().decode())
            if pointed not in manifests:
                raise AssertionError(
                    f"`latest` points at step {pointed} but no manifest "
                    f"exists for it (have {sorted(manifests)})")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def stale_manifest_restore_demo():
    """PLANTED torn-manifest read: the writer publishes a new snapshot
    by moving the ``latest`` pointer FIRST and writing the manifest
    after — the inverse of the real plane's order (manifest, then
    pointer, each atomic) — while the restore reads the pointer and
    then the manifest without re-checking the generation in between.
    Most schedules pass (the writer finishes before the reader looks);
    exploration must FIND the window where the pointer names a step
    whose manifest has not landed, and the model-assertion finding
    replays byte-for-byte from (seed, trace)."""
    inv = _inv()
    mu = inv.make_lock("ckptdemo.mu")
    store = {"latest": 1, "manifest": {1: {"step": 1}}}

    def writer():
        # BUG: pointer before manifest — the torn window
        with mu:
            store["latest"] = 2
        with mu:
            store["manifest"][2] = {"step": 2}

    def reader():
        with mu:
            step = store["latest"]
        # BUG: no generation re-check between pointer and manifest read
        with mu:
            man = store["manifest"].get(step)
        if man is None or man["step"] != step:
            raise AssertionError(
                f"restore read latest={step} but its manifest is "
                f"{man!r}: the pointer moved before the manifest landed")

    ts = [inv.spawn_thread(writer, name="writer"),
          inv.spawn_thread(reader, name="reader")]
    for t in ts:
        inv.join_thread(t)


MATRIX = {
    "enqueue-flush": enqueue_flush_quiesce,
    "flush-abort": flush_abort_race,
    "quiesce-race": quiesce_enqueue_race,
    "watchdog-abort": watchdog_poison_abort,
    "capture-replay-abort": capture_replay_abort,
    "qos-admission": qos_admission,
    "hier-negotiation": hier_negotiation,
    "loopback-exchange": loopback_exchange,
    "pr3-issue-lock": pr3_issue_lock,
    "pr6-chain-guard": pr6_chain_guard,
    "elastic-reform": elastic_reform,
    "autoscale-decision": autoscale_decision,
    "ckpt-snapshot": ckpt_snapshot,
}

DEMOS = {
    "deadlock-demo": deadlock_demo,
    "lost-wakeup-demo": lost_wakeup_demo,
    "loopback-exchange-unguarded": loopback_exchange_unguarded,
    "leader-lost-wakeup-demo": leader_lost_wakeup_demo,
    "qos-inversion-demo": qos_inversion_demo,
    "pr3-unguarded": pr3_unguarded,
    "pr6-unguarded": pr6_unguarded,
    "stale-plan-after-resize-demo": stale_plan_after_resize_demo,
    "evict-during-reform-demo": evict_during_reform_demo,
    "stale-manifest-restore-demo": stale_manifest_restore_demo,
}

MODELS = {**MATRIX, **DEMOS}
