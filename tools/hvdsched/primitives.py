"""Cooperative threading primitives for hvdsched model runs.

These are what ``horovod_tpu/utils/invariants.py`` returns under
``HVD_SCHED_CHECK=1``: drop-in ``Lock``/``RLock``/``Condition``/``Event``
duck-types plus ``spawn_thread``/``join_thread``/``sleep``/``monotonic``
helpers. Every operation checks, **per call**, whether the calling
thread is a managed task of the active :class:`~.runtime.Runtime`:

* managed -> the operation routes through the runtime (a schedule
  point; blocking parks the task; timed waits use the virtual clock);
* unmanaged (no model run active, or a thread outside the run) -> the
  operation falls through to a real :mod:`threading` primitive, so a
  ``HVD_SCHED_CHECK=1`` process behaves normally outside model runs
  (imports, test setup, post-run assertions).

The two modes share observable *state* where it matters for post-run
assertions (``Event.is_set``, ``Lock.locked``) but not blocking
semantics: a primitive must not be **contended** across the
managed/unmanaged boundary during a run. In practice that cannot
happen — a model run serializes every managed thread and the
controller never touches model primitives.
"""

from __future__ import annotations

import threading
import time

from . import runtime as _rt


def _managed():
    return _rt.current()


class Lock:
    """Cooperative mutex. Duck-types ``threading.Lock`` (acquire /
    release / locked / context manager) and carries ``name`` like the
    invariants witness's tracked locks."""

    _reentrant = False

    def __init__(self, name: str = "lock"):
        self.name = name
        self._real = self._make_real()
        # cooperative state (touched only while serialized)
        self._owner = None
        self._count = 0
        self._waiters: list = []

    @staticmethod
    def _make_real():
        return threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ctx = _managed()
        if ctx is None:
            _rt.check_exit()
            if timeout is None or timeout < 0:
                return self._real.acquire(blocking)
            return self._real.acquire(blocking, timeout)
        rt, task = ctx
        return rt.lock_acquire(self, task, blocking, timeout)

    def release(self) -> None:
        ctx = _managed()
        if ctx is None:
            self._real.release()
            return
        rt, task = ctx
        rt.lock_release(self, task)

    def locked(self) -> bool:
        if self._owner is not None:
            return True
        locked = getattr(self._real, "locked", None)
        return bool(locked()) if locked is not None else False

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<hvdsched.{type(self).__name__} {self.name!r}>"


class RLock(Lock):
    _reentrant = True

    @staticmethod
    def _make_real():
        return threading.RLock()


class Condition:
    """Cooperative condition variable over a cooperative :class:`Lock`.
    Exposes ``_lock`` (the invariants module's ``holding()`` peeks at
    it) and the stock wait/notify/notify_all surface."""

    def __init__(self, lock: Lock | None = None, name: str = "cv"):
        self._coop_lock = lock if lock is not None else Lock(name)
        self._lock = self._coop_lock
        self.name = self._coop_lock.name
        self._waiters: list = []
        self._real = threading.Condition(self._coop_lock._real)

    def acquire(self, *a, **kw):
        return self._coop_lock.acquire(*a, **kw)

    def release(self):
        self._coop_lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def wait(self, timeout: float | None = None) -> bool:
        ctx = _managed()
        if ctx is None:
            _rt.check_exit()
            return self._real.wait(timeout)
        rt, task = ctx
        return rt.cv_wait(self, task, timeout)

    def notify(self, n: int = 1) -> None:
        ctx = _managed()
        if ctx is None:
            self._real.notify(n)
            return
        rt, task = ctx
        rt.cv_notify(self, task, n)

    def notify_all(self) -> None:
        ctx = _managed()
        if ctx is None:
            self._real.notify_all()
            return
        rt, task = ctx
        rt.cv_notify(self, task, len(self._waiters))

    def __repr__(self):
        return f"<hvdsched.Condition {self.name!r}>"


class Event:
    """Cooperative event. The flag itself is shared between the
    managed and unmanaged paths (a post-run assertion on
    ``entry.event.is_set()`` must see what the model set)."""

    def __init__(self, name: str = "event"):
        self.name = name
        self._flag = False
        self._real = threading.Event()
        self._waiters: list = []

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        ctx = _managed()
        self._flag = True
        self._real.set()
        if ctx is not None:
            rt, task = ctx
            rt.event_set(self, task)

    def clear(self) -> None:
        ctx = _managed()
        self._flag = False
        self._real.clear()
        if ctx is not None:
            rt, task = ctx
            rt.event_clear(self, task)

    def wait(self, timeout: float | None = None) -> bool:
        ctx = _managed()
        if ctx is None:
            _rt.check_exit()
            return self._real.wait(timeout)
        rt, task = ctx
        return rt.event_wait(self, task, timeout)

    def __repr__(self):
        return f"<hvdsched.Event {self.name!r} set={self._flag}>"


def spawn_thread(target, *, name: str, daemon: bool = True,
                 args=(), kwargs=None) -> threading.Thread:
    """Create AND start a thread; registers it as a managed task when
    called from inside a model run, plain daemon thread otherwise."""
    kwargs = kwargs or {}
    ctx = _managed()
    if ctx is not None:
        rt, _task = ctx
        return rt.spawn(target, name=name, daemon=daemon,
                        args=args, kwargs=kwargs)
    t = threading.Thread(target=target, name=name, daemon=daemon,
                         args=args, kwargs=kwargs)
    t.start()
    return t


def join_thread(thread: threading.Thread, timeout=None) -> None:
    ctx = _managed()
    if ctx is not None:
        rt, task = ctx
        if any(t.thread is thread for t in rt.tasks.values()):
            rt.join(thread, task, timeout)
            return
    _rt.check_exit()
    thread.join(timeout)


def sleep(seconds: float) -> None:
    ctx = _managed()
    if ctx is not None:
        rt, task = ctx
        rt.sleep(task, seconds)
        return
    _rt.check_exit()
    time.sleep(seconds)


def monotonic() -> float:
    ctx = _managed()
    if ctx is not None:
        rt, _task = ctx
        return rt.clock
    return time.monotonic()
