"""hvdsched: deterministic schedule-exploration checker for the
concurrency core.

The dynamic counterpart to ``tools/hvdlint`` and the third leg of the
project's concurrency tooling (docs/schedule_checker.md):

* ``hvdlint`` checks the *lexical* shape of the concurrency invariants;
* ``HVD_DEBUG_INVARIANTS=1`` (``utils/invariants.py``) witnesses what
  threads *did* on whatever schedule the OS happened to pick;
* ``HVD_SCHED_CHECK=1`` + hvdsched takes control of the schedule
  itself: every lock/condition/event/thread/sleep in the concurrency
  core routes through a cooperative scheduler that serializes all
  threads to ONE runnable at a time and drives every interleaving
  choice from a seeded PRNG — so the schedule space can be *explored*
  (seed sweeps + DPOR-lite preemption branching) and any failing
  schedule replays byte-for-byte from ``(seed, trace)``.

Usage::

    HVD_SCHED_CHECK=1 python -m tools.hvdsched                # matrix gate
    HVD_SCHED_CHECK=1 python -m tools.hvdsched --demos        # detector sanity
    HVD_SCHED_CHECK=1 python -m tools.hvdsched --model flush-abort \
        --schedules 500

or from tests::

    from tools.hvdsched import explore, run_model, models
    result = explore(models.MATRIX["flush-abort"], schedules=200)
    assert result.ok, result.findings[0]
"""

from __future__ import annotations

from .explore import ExploreResult, explore, run_model
from .runtime import Result, Runtime, SchedError, SchedExit, SchedFailure

__all__ = ["ExploreResult", "Result", "Runtime", "SchedError", "SchedExit",
           "SchedFailure", "explore", "run_model"]
