"""State synchronization helpers.

Rebuild of ``/root/reference/horovod/torch/functions.py`` (269 LoC:
``broadcast_parameters`` / ``broadcast_optimizer_state`` / ``broadcast_object``)
and ``/root/reference/horovod/tensorflow/functions.py`` (``broadcast_variables``).
Reference examples call these at step 0 so every rank starts from rank 0's
weights (``examples/pytorch/pytorch_mnist.py:220-221``).

On TPU under single-controller SPMD, jax arrays are already globally
consistent, so these matter for (a) process-set subsets, (b) multi-process
host state divergence (RNG, python objects), and (c) elastic restarts —
they broadcast through the same collective layer for full parity.
"""

from __future__ import annotations

import jax

from .ops import collectives
from .process_sets import ProcessSet


def broadcast_parameters(params, root_rank: int = 0,
                         process_set: ProcessSet | None = None):
    """Broadcast a pytree of arrays from ``root_rank`` to all ranks
    (reference ``broadcast_parameters``, ``torch/functions.py``).
    Returns the synchronized pytree. Leaves ride the fusion-cycle
    broadcast queue and are fused per dtype into single wire buffers at
    the flush (see ``grouped_broadcast``) — a model broadcast coalesces
    with any other pending broadcasts of the same root before the
    synchronize drains the queue."""
    leaves, treedef = jax.tree.flatten(params)
    handle = collectives.grouped_broadcast_async(
        leaves, root_rank, process_set=process_set)
    return jax.tree.unflatten(treedef, handle.synchronize())


# TF-parity alias (reference ``broadcast_variables``, tensorflow/functions.py)
broadcast_variables = broadcast_parameters


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              process_set: ProcessSet | None = None):
    """Broadcast optimizer state (reference ``broadcast_optimizer_state``).
    optax states are array pytrees, so this is the same fused tree
    broadcast — non-array leaves (step counts as python ints, None) pass
    through. Array leaves ride the fusion-cycle broadcast queue like
    :func:`broadcast_parameters`, so a params + optimizer-state restore
    coalesces into one pipelined flush instead of two dispatch storms."""
    leaves, treedef = jax.tree.flatten(opt_state)
    is_array = [hasattr(x, "dtype") and hasattr(x, "shape") for x in leaves]
    handle = collectives.grouped_broadcast_async(
        [x for x, a in zip(leaves, is_array) if a], root_rank,
        process_set=process_set)
    it = iter(handle.synchronize())
    out = [next(it) if a else x for x, a in zip(leaves, is_array)]
    return jax.tree.unflatten(treedef, out)


broadcast_object = collectives.broadcast_object
allgather_object = collectives.allgather_object
