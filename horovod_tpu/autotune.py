"""Autotuner: runtime search over performance knobs.

TPU-native rebuild of the reference's ``ParameterManager``
(``/root/reference/horovod/common/parameter_manager.cc:1-528``, header
``parameter_manager.h:42-110``): while training runs, score each candidate
knob configuration by observed collective throughput (bytes/sec), explore
the space, and settle on the best configuration. The reference drives the
exploration with Bayesian optimization over a Gaussian-process posterior
(``optim/bayesian_optimization.cc:1-194``). Both strategies exist here,
selected by ``HVD_AUTOTUNE_STRATEGY``:

* ``coordinate`` (default) — cyclic coordinate search over the discrete
  grids: the knob space is tiny (three knobs, <= 8 values each) and
  coordinate descent converges in a handful of samples without the GP
  machinery;
* ``bayesian`` — the reference's GP + expected-improvement loop
  (:mod:`horovod_tpu.optim.bayes`) over the same grids (proposals in
  continuous index space, rounded), with
  ``HVD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE`` as the GP noise ``alpha``;
  converges when EI stays below threshold or the sample budget ends.
  Worth it when the grid grows (more knobs / finer grids) and a full
  coordinate pass becomes expensive in samples.

Tuned knobs (the subset of the reference's set that has a consumer in the
TPU rebuild; ``operations.cc:584-594``):

* ``FUSION_THRESHOLD`` — eager fusion bucket size in bytes: how much of a
  grouped op's payload is packed into one wire buffer / one compiled
  program (consumer: ``ops/collectives._fuse_by_dtype``).
* ``CYCLE_TIME`` — fusion-cycle flush pace for queued async collectives
  (consumer: ``ops/fusion_cycle.FusionScheduler``) and the dynamic-engine
  negotiation cycle in ms (consumer: ``engine_service.DynamicService``);
  both re-read it live.
* ``PENDING_CYCLE_TIME`` — the faster pace both consumers drop to while
  work is in flight.
* ``MAX_INFLIGHT_FLUSHES`` — pipelined flush executor slots (consumer:
  ``ops/fusion_cycle.FusionScheduler``; 1 = synchronous executor).
* ``PIPELINE_CHUNKS`` — chunk count for the large-buffer wire pipeline
  (consumer: ``ops/collectives._chunk_layout`` via the chunked dispatch
  plans, which rebuild on the override-epoch bump).
* ``BUCKET_BYTES`` — gradient bucket size for the eager backward-pass
  comm/compute overlap (consumer: ``optim/_bucketed_allreduce``).
* ``HIERARCHICAL_ALLREDUCE`` — flat vs two-level ICI/DCN schedule
  (consumer: ``ops/hierarchical.hierarchical_enabled_for``).
* ``CACHE_CAPACITY`` — dispatch-plan/response cache on/off (the
  reference's ``cache_enabled`` tunable; consumer:
  ``ops/dispatch_cache``, which re-reads the knob per call and flushes
  plans when the override changes).

Knobs pinned via the environment are **fixed** and excluded from tuning,
exactly like the reference (env-set params are marked untunable,
``operations.cc:490-523``). Discipline follows the reference: the first
``HVD_AUTOTUNE_WARMUP_SAMPLES`` samples are discarded (jit warmup), each
sample scores ``HVD_AUTOTUNE_STEPS_PER_SAMPLE`` recorded collectives, and
exploration stops after ``HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES`` samples or
when a full coordinate pass yields no improvement. ``HVD_AUTOTUNE_LOG``
writes one CSV row per sample (``parameter_manager.h:48,111-113``).

Multi-process jobs must apply identical knob values everywhere — the eager
collectives are SPMD programs over all processes, so a per-process choice
of e.g. hierarchical-vs-flat would deadlock. Rank 0 therefore aggregates
scores and decides; decisions travel over the launcher KV store (the
analog of ``Controller::SynchronizeParameters``, ``controller.h:70``).
"""

from __future__ import annotations

import csv
import json
import math
import threading
import time

import numpy as np

from .utils import envs
from .utils import logging as hvd_logging

KB = 1024
MB = 1024 * 1024

DEFAULT_WARMUP_SAMPLES = 3       # parameter_manager.h:42-110
DEFAULT_STEPS_PER_SAMPLE = 10
DEFAULT_MAX_SAMPLES = 40
DEFAULT_GP_NOISE = 0.8           # reference HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE
_EI_TOL = 1e-3                   # bayesian: converged when EI stays below
_EI_PATIENCE = 2                 # ... for this many consecutive proposals


class Tunable:
    """One knob: a discrete candidate grid plus an applier."""

    def __init__(self, knob: str, candidates, apply_fn=None):
        self.knob = knob
        self.candidates = list(candidates)
        self.apply_fn = apply_fn
        self.fixed = envs.is_env_fixed(knob)
        self.index = 0

    @property
    def value(self):
        return self.candidates[self.index]

    def apply(self):
        envs.set_override(self.knob, self.value)
        if self.apply_fn is not None:
            self.apply_fn(self.value)


def _default_tunables() -> list[Tunable]:
    return [
        Tunable(envs.FUSION_THRESHOLD,
                [1 * MB, 4 * MB, 16 * MB, 64 * MB, 128 * MB, 256 * MB]),
        # CYCLE_TIME now drives TWO consumers: the dynamic engine's
        # negotiation tick AND the fusion-cycle flush pace of queued
        # async collectives (ops/fusion_cycle.py; both re-read the knob
        # live, so tuned values take effect between flushes).
        Tunable(envs.CYCLE_TIME, [1.0, 2.5, 5.0, 10.0, 20.0, 40.0]),
        # Flush pace while work is in flight (fusion cycle) / in-flight
        # negotiation tick floor (engine service).
        Tunable(envs.PENDING_CYCLE_TIME, [0.5, 1.0, 2.0, 5.0]),
        # Pipelined flush executor slots (ops/fusion_cycle.py): first
        # candidate = the default so enabling autotune changes nothing at
        # sample 0; 1 = synchronous executor. Safe to tune per-process
        # tier because slot count never changes flush composition or
        # program issue order (single FIFO dispatch thread), but decisions
        # still sync through rank 0 like every knob.
        Tunable(envs.MAX_INFLIGHT_FLUSHES, [envs.DEFAULT_MAX_INFLIGHT_FLUSHES,
                                            1, 4]),
        # Chunk count for the large-buffer wire pipeline (single-
        # controller only — multi-process plans keep the joined-
        # compatible one-program composition, so tuning it cannot
        # desynchronize programs). Flipping it bumps the envs override
        # epoch, which rebuilds the chunked dispatch plans.
        Tunable(envs.PIPELINE_CHUNKS, [envs.DEFAULT_PIPELINE_CHUNKS, 2, 8]),
        # Gradient bucket size for the eager backward-pass overlap
        # (consumer: optim/_bucketed_allreduce, which re-reads the knob
        # per update). First candidate = the default so enabling autotune
        # changes nothing at sample 0. Bucket layout is a pure function
        # of leaf sizes + this knob, and decisions sync through rank 0,
        # so multi-process composition stays rank-deterministic.
        Tunable(envs.BUCKET_BYTES, [envs.DEFAULT_BUCKET_BYTES,
                                    8 * MB, 16 * MB, 32 * MB, 128 * MB]),
        # Step capture-and-replay (ops/step_capture.py). Default-off
        # first so enabling autotune changes nothing at sample 0; when
        # the tuner flips it on, marked steps record once and replay as
        # one cached program. Flipping the override bumps the envs
        # epoch, which drops cached step plans — a stale capture can
        # never survive a knob change.
        Tunable(envs.STEP_CAPTURE, [0, 1]),
        # GSPMD cached-program fast path (ops/gspmd_cache.py). Default-on
        # first so enabling autotune changes nothing at sample 0; 0
        # restores plain per-call jit for A/B measurement. Flipping the
        # override bumps the envs epoch, which drops cached step
        # executables — a stale program can never survive the change.
        Tunable(envs.GSPMD_CACHE, [1, 0]),
        # Multi-tenant QoS pacing (qos.py; consumed live per gate pump,
        # inert with HVD_QOS=0). Defaults first so enabling autotune
        # changes nothing at sample 0. Safe to tune: quantum/window only
        # re-pace the gate's DETERMINISTIC grant schedule (decisions
        # sync through rank 0 like every knob, and both are pure-config
        # inputs to the grant order, never completion timing).
        Tunable(envs.QOS_QUANTUM, [envs.DEFAULT_QOS_QUANTUM,
                                   16 * 1024, 256 * 1024]),
        Tunable(envs.QOS_WINDOW, [envs.DEFAULT_QOS_WINDOW, 2, 8]),
        Tunable(envs.HIERARCHICAL_ALLREDUCE, [0, 1]),
        # Dispatch-plan/response cache on/off, the reference's cache_enabled
        # tunable (parameter_manager.cc CacheEnabledParameter). Default-on
        # first so enabling autotune never starts with caching disabled;
        # consumer: ops/dispatch_cache (reads the knob per call; flipping
        # the override flushes plans via the envs epoch).
        Tunable(envs.CACHE_CAPACITY, [envs.DEFAULT_CACHE_CAPACITY, 0]),
    ]


class _BayesianSearch:
    """GP + expected-improvement proposals over the active tunables'
    index space (reference ``BayesianOptimization`` driven by
    ``ParameterManager::TuneParameters``). Proposals are continuous
    index vectors rounded to the nearest grid point, so the decision
    payload stays the same index-state the coordinate strategy and the
    KV sync already speak."""

    def __init__(self, active, seed: int = 0):
        import itertools

        from .optim.bayes import BayesianOptimization
        self._bo = BayesianOptimization(
            [(0.0, float(len(t.candidates) - 1)) for t in active],
            alpha=envs.get_float(envs.AUTOTUNE_GAUSSIAN_PROCESS_NOISE,
                                 DEFAULT_GP_NOISE),
            seed=seed)
        # EI is maximized over the exact knob grid: continuous proposals
        # rounded to a coarse grid collapse onto the incumbent and never
        # explore. Grids too large to enumerate are sampled per proposal
        # instead (see _candidates) — a lexicographic prefix would
        # silently bar every high-index value of the leading knobs.
        self._sizes = [len(t.candidates) for t in active]
        total = math.prod(self._sizes)
        if total <= 4096:
            self._grid = np.array(
                list(itertools.product(*[range(s) for s in self._sizes])),
                float)
        else:
            self._grid = None
            self._rng = np.random.default_rng(seed)
        self._ei_low = 0

    def _candidates(self, incumbent) -> np.ndarray:
        """EI candidate set for one proposal. Small grids are enumerated
        exactly; larger ones get a FRESH uniform draw each call (a frozen
        init-time sample would confine every proposal to its points —
        ADVICE round-5 #3) mixed with the incumbent's coordinate
        neighborhood so local refinement stays reachable."""
        if self._grid is not None:
            return self._grid
        fresh = np.column_stack(
            [self._rng.integers(0, s, size=3584) for s in self._sizes]
        ).astype(float)
        # one-coordinate perturbations of the best state seen so far
        base = np.asarray(incumbent, float)
        neigh = []
        for d, s in enumerate(self._sizes):
            for v in (base[d] - 1, base[d] + 1):
                if 0 <= v < s:
                    p = base.copy()
                    p[d] = v
                    neigh.append(p)
        rows = [fresh, np.atleast_2d(base)]
        if neigh:
            rows.append(np.vstack(neigh))
        return np.vstack(rows)

    def propose(self, mgr: "ParameterManager", score: float) -> dict:
        """Observe ``score`` for the CURRENT state, propose the next."""
        active_idx = [mgr.tunables.index(t) for t in mgr._active]
        self._bo.add_sample([float(mgr._state()[i]) for i in active_idx],
                            score)
        incumbent = [float(mgr._best_state[i]) for i in active_idx]
        x_next, ei = self._bo.next_sample(
            candidates=self._candidates(incumbent))
        if math.isfinite(ei) and len(self._bo._y) >= 5:
            self._ei_low = self._ei_low + 1 if ei < _EI_TOL else 0
            if self._ei_low >= _EI_PATIENCE:
                return {"state": mgr._best_state, "converged": True}
        next_state = list(mgr._best_state)
        for pos, t, v in zip(active_idx, mgr._active, x_next):
            next_state[pos] = int(np.clip(round(v),
                                          0, len(t.candidates) - 1))
        return {"state": next_state, "converged": False}


class ParameterManager:
    """Samples bytes/sec and searches the knob grid (coordinate descent
    or the GP/EI loop, per ``HVD_AUTOTUNE_STRATEGY``)."""

    def __init__(self, tunables: list[Tunable] | None = None, *,
                 warmup_samples: int | None = None,
                 steps_per_sample: int | None = None,
                 max_samples: int | None = None,
                 log_path: str | None = None,
                 sync=None):
        self.tunables = tunables if tunables is not None else _default_tunables()
        self.warmup_samples = (warmup_samples if warmup_samples is not None
                               else envs.get_int(envs.AUTOTUNE_WARMUP_SAMPLES,
                                                 DEFAULT_WARMUP_SAMPLES))
        self.steps_per_sample = (steps_per_sample if steps_per_sample is not None
                                 else envs.get_int(envs.AUTOTUNE_STEPS_PER_SAMPLE,
                                                   DEFAULT_STEPS_PER_SAMPLE))
        self.max_samples = (max_samples if max_samples is not None
                            else envs.get_int(envs.AUTOTUNE_BAYES_OPT_MAX_SAMPLES,
                                              DEFAULT_MAX_SAMPLES))
        self.log_path = (log_path if log_path is not None
                         else envs.get(envs.AUTOTUNE_LOG))
        self._sync = sync  # rank-0 decision broadcast; see _synced_decision
        self._mu = threading.Lock()
        self._bytes = 0
        self._steps = 0
        self._sample_start = time.monotonic()
        self._sample_idx = 0
        self._active = [t for t in self.tunables if not t.fixed
                        and len(t.candidates) > 1]
        self._coord = 0          # which tunable is being swept
        self._cand = 0           # candidate index under trial
        self._best_score = None
        self._best_state = [t.index for t in self.tunables]
        self._pass_improved = False
        self.converged = not self._active
        self.strategy = (envs.get(envs.AUTOTUNE_STRATEGY, "coordinate")
                         or "coordinate").lower()
        if self.strategy not in ("coordinate", "bayesian"):
            hvd_logging.warning(
                "unknown HVD_AUTOTUNE_STRATEGY %r; valid values are "
                "'coordinate' and 'bayesian' — falling back to coordinate",
                self.strategy)
            self.strategy = "coordinate"
        self._bayes = (_BayesianSearch(self._active)
                       if self.strategy == "bayesian" and self._active
                       else None)
        self._log_writer = None
        if self.log_path:
            f = open(self.log_path, "w", newline="")
            self._log_writer = csv.writer(f)
            self._log_writer.writerow(
                ["sample", "score_bytes_per_sec", "warmup", "converged"]
                + [t.knob for t in self.tunables])
            self._log_file = f
        for t in self.tunables:
            t.apply()

    # -- recording ---------------------------------------------------------

    def record(self, nbytes: int) -> None:
        """Account one eager collective's wire payload; sample boundaries
        land every ``steps_per_sample`` records. Cheap: one lock, two adds."""
        if self.converged:
            return
        with self._mu:
            self._bytes += int(nbytes)
            self._steps += 1
            if self._steps < self.steps_per_sample:
                return
            elapsed = time.monotonic() - self._sample_start
            score = self._bytes / max(elapsed, 1e-9)
            self._bytes = 0
            self._steps = 0
            self._end_sample(score)
            self._sample_start = time.monotonic()

    # -- search ------------------------------------------------------------

    def _state(self) -> list[int]:
        return [t.index for t in self.tunables]

    def _apply_state(self, state: list[int]) -> None:
        for t, i in zip(self.tunables, state):
            t.index = i
            t.apply()

    def _end_sample(self, score: float) -> None:
        warmup = self._sample_idx < self.warmup_samples
        self._log(score, warmup)
        self._sample_idx += 1
        if warmup:
            return
        decision = self._synced_decision(score)
        self._apply_state(decision["state"])
        if decision["converged"]:
            self._finish(decision["state"])

    def _local_decision(self, score: float) -> dict:
        """Advance the search by one scored sample."""
        if self._best_score is None or score > self._best_score:
            self._best_score = score
            self._best_state = self._state()
            self._pass_improved = True
        if self._sample_idx - self.warmup_samples >= self.max_samples:
            return {"state": self._best_state, "converged": True}
        if self._bayes is not None:
            return self._bayes.propose(self, score)
        # move to the next candidate of the current coordinate, or the next
        # coordinate (restarting from the best state found so far)
        tun = self._active[self._coord]
        self._cand += 1
        if self._cand >= len(tun.candidates):
            self._cand = 0
            self._coord += 1
            if self._coord >= len(self._active):
                # full pass done
                if not self._pass_improved:
                    return {"state": self._best_state, "converged": True}
                self._pass_improved = False
                self._coord = 0
        next_state = list(self._best_state)
        active_tun = self._active[self._coord]
        pos = self.tunables.index(active_tun)
        next_state[pos] = self._cand
        return {"state": next_state, "converged": False}

    def _synced_decision(self, score: float) -> dict:
        """Single process: decide locally. Multi-process: rank 0 averages
        everyone's score for the sample and broadcasts the decision
        (``Controller::SynchronizeParameters`` analog)."""
        if self._sync is None:
            return self._local_decision(score)
        return self._sync(self._sample_idx, score, self._local_decision)

    def _finish(self, state: list[int]) -> None:
        self.converged = True
        self._apply_state(state)
        hvd_logging.info(
            "autotune converged after %d samples: %s (score %.3g B/s)",
            self._sample_idx,
            {t.knob: t.value for t in self.tunables}, self._best_score or 0)
        self._log(self._best_score or 0.0, False)
        if self._log_writer:
            self._log_file.close()
            self._log_writer = None

    def _log(self, score: float, warmup: bool) -> None:
        if not self._log_writer:
            return
        self._log_writer.writerow(
            [self._sample_idx, f"{score:.1f}", int(warmup), int(self.converged)]
            + [t.value for t in self.tunables])
        self._log_file.flush()

    def current_config(self) -> dict:
        return {t.knob: t.value for t in self.tunables}


class KVScoreSync:
    """Rank-0 decide + broadcast over the launcher KV store."""

    def __init__(self, kv, world_size: int, rank: int,
                 prefix: str = "autotune", timeout: float = 600.0):
        self.kv = kv
        self.world_size = world_size
        self.rank = rank
        self.prefix = prefix
        self.timeout = timeout

    def __call__(self, sample_idx: int, score: float, local_decision) -> dict:
        self.kv.put(f"{self.prefix}/score/{sample_idx}/{self.rank}",
                    repr(float(score)).encode())
        if self.rank == 0:
            gather = getattr(self.kv, "gather", None)
            if gather is not None:  # one server-side round (KVClient)
                got = gather(f"{self.prefix}/score/{sample_idx}",
                             self.world_size, timeout=self.timeout)
                total = sum(float(v.decode()) for v in got.values())
            else:  # plain mapping-style stores (tests)
                total = 0.0
                for r in range(self.world_size):
                    data = self.kv.wait(
                        f"{self.prefix}/score/{sample_idx}/{r}",
                        timeout=self.timeout)
                    total += float(data.decode())
            decision = local_decision(total / self.world_size)
            self.kv.put(f"{self.prefix}/decision/{sample_idx}",
                        json.dumps(decision).encode())
        else:
            data = self.kv.wait(f"{self.prefix}/decision/{sample_idx}",
                                timeout=self.timeout)
            decision = json.loads(data.decode())
        # everyone has read sample_idx's keys before anyone writes
        # sample_idx+2 (a rank must finish its own idx+1 reads first), so
        # deleting the previous sample's keys bounds KV memory
        if sample_idx > 0:
            try:
                self.kv.delete(
                    f"{self.prefix}/score/{sample_idx - 1}/{self.rank}")
                if self.rank == 0:
                    self.kv.delete(f"{self.prefix}/decision/{sample_idx - 1}")
            except Exception:  # hvdlint: disable=silent-except
                pass  # best-effort memory bound; stale keys are harmless
        return decision


# ---------------------------------------------------------------------------
# process-wide manager (mirrors engine_service's lazy singleton)
# ---------------------------------------------------------------------------

_manager: ParameterManager | None = None
_manager_lock = threading.Lock()
_checked = False


def get_manager() -> ParameterManager | None:
    """The process's autotuner, or None when HVD_AUTOTUNE is off."""
    global _manager, _checked
    if _manager is not None or _checked:
        return _manager
    with _manager_lock:
        if _manager is not None or _checked:
            return _manager
        _checked = True
        if not envs.get_bool(envs.AUTOTUNE):
            return None
        sync = None
        from . import runtime
        if runtime.is_initialized() and runtime.process_count() > 1:
            kv_addr = envs.get(envs.KV_ADDR)
            if not kv_addr:
                # Without a decision channel each process would explore the
                # grid independently — and a per-process flip of
                # HIERARCHICAL_ALLREDUCE changes the SPMD program, which
                # deadlocks the job. Refuse rather than risk it (the
                # reference likewise tunes through the controller,
                # SynchronizeParameters).
                hvd_logging.warning(
                    "HVD_AUTOTUNE requested but this multi-process job has "
                    "no launcher KV store to synchronize decisions; "
                    "autotuning disabled (launch via hvdrun to enable)")
                return None
            from .runner.http_kv import KVClient
            kv = KVClient(kv_addr, envs.get_int(envs.KV_PORT, 0),
                          secret=envs.get(envs.SECRET_KEY))
            sync = KVScoreSync(kv, runtime.process_count(),
                               runtime.process_rank())
        _manager = ParameterManager(sync=sync)
        hvd_logging.info("autotune enabled: %s", _manager.current_config())
    return _manager


def record(nbytes: int) -> None:
    """Hot-path hook called by the eager collectives."""
    mgr = get_manager() if envs.get_bool(envs.AUTOTUNE) else None
    if mgr is not None:
        mgr.record(nbytes)


def reset() -> None:
    """Tear down (tests / elastic re-init)."""
    global _manager, _checked
    with _manager_lock:
        if _manager is not None:
            for t in _manager.tunables:
                envs.clear_override(t.knob)
            if _manager._log_writer:
                _manager._log_file.close()
        _manager = None
        _checked = False
