"""Autotuner: runtime search over performance knobs.

TPU-native rebuild of the reference's ``ParameterManager``
(``/root/reference/horovod/common/parameter_manager.cc:1-528``, header
``parameter_manager.h:42-110``): while training runs, score each candidate
knob configuration by observed collective throughput (bytes/sec), explore
the space, and settle on the best configuration. The reference drives the
exploration with Bayesian optimization over a Gaussian-process posterior
(``optim/bayesian_optimization.cc:1-194``); here a cyclic coordinate search
over small discrete grids is used — the knob space is tiny (three knobs,
<= 8 values each) and coordinate descent converges in a handful of samples
without the GP machinery.

Tuned knobs (the subset of the reference's set that has a consumer in the
TPU rebuild; ``operations.cc:584-594``):

* ``FUSION_THRESHOLD`` — eager fusion bucket size in bytes: how much of a
  grouped op's payload is packed into one wire buffer / one compiled
  program (consumer: ``ops/collectives._fuse_by_dtype``).
* ``CYCLE_TIME`` — dynamic-engine negotiation cycle in ms (consumer:
  ``engine_service.DynamicService``; re-read every cycle).
* ``HIERARCHICAL_ALLREDUCE`` — flat vs two-level ICI/DCN schedule
  (consumer: ``ops/hierarchical.hierarchical_enabled_for``).

Knobs pinned via the environment are **fixed** and excluded from tuning,
exactly like the reference (env-set params are marked untunable,
``operations.cc:490-523``). Discipline follows the reference: the first
``HVD_AUTOTUNE_WARMUP_SAMPLES`` samples are discarded (jit warmup), each
sample scores ``HVD_AUTOTUNE_STEPS_PER_SAMPLE`` recorded collectives, and
exploration stops after ``HVD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES`` samples or
when a full coordinate pass yields no improvement. ``HVD_AUTOTUNE_LOG``
writes one CSV row per sample (``parameter_manager.h:48,111-113``).

Multi-process jobs must apply identical knob values everywhere — the eager
collectives are SPMD programs over all processes, so a per-process choice
of e.g. hierarchical-vs-flat would deadlock. Rank 0 therefore aggregates
scores and decides; decisions travel over the launcher KV store (the
analog of ``Controller::SynchronizeParameters``, ``controller.h:70``).
"""

from __future__ import annotations

import csv
import json
import threading
import time

from .utils import envs
from .utils import logging as hvd_logging

KB = 1024
MB = 1024 * 1024

DEFAULT_WARMUP_SAMPLES = 3       # parameter_manager.h:42-110
DEFAULT_STEPS_PER_SAMPLE = 10
DEFAULT_MAX_SAMPLES = 40


class Tunable:
    """One knob: a discrete candidate grid plus an applier."""

    def __init__(self, knob: str, candidates, apply_fn=None):
        self.knob = knob
        self.candidates = list(candidates)
        self.apply_fn = apply_fn
        self.fixed = envs.is_env_fixed(knob)
        self.index = 0

    @property
    def value(self):
        return self.candidates[self.index]

    def apply(self):
        envs.set_override(self.knob, self.value)
        if self.apply_fn is not None:
            self.apply_fn(self.value)


def _default_tunables() -> list[Tunable]:
    return [
        Tunable(envs.FUSION_THRESHOLD,
                [1 * MB, 4 * MB, 16 * MB, 64 * MB, 128 * MB, 256 * MB]),
        Tunable(envs.CYCLE_TIME, [1.0, 2.5, 5.0, 10.0, 20.0, 40.0]),
        Tunable(envs.HIERARCHICAL_ALLREDUCE, [0, 1]),
    ]


class ParameterManager:
    """Samples bytes/sec and coordinate-searches the knob grid."""

    def __init__(self, tunables: list[Tunable] | None = None, *,
                 warmup_samples: int | None = None,
                 steps_per_sample: int | None = None,
                 max_samples: int | None = None,
                 log_path: str | None = None,
                 sync=None):
        self.tunables = tunables if tunables is not None else _default_tunables()
        self.warmup_samples = (warmup_samples if warmup_samples is not None
                               else envs.get_int(envs.AUTOTUNE_WARMUP_SAMPLES,
                                                 DEFAULT_WARMUP_SAMPLES))
        self.steps_per_sample = (steps_per_sample if steps_per_sample is not None
                                 else envs.get_int(envs.AUTOTUNE_STEPS_PER_SAMPLE,
                                                   DEFAULT_STEPS_PER_SAMPLE))
        self.max_samples = (max_samples if max_samples is not None
                            else envs.get_int(envs.AUTOTUNE_BAYES_OPT_MAX_SAMPLES,
                                              DEFAULT_MAX_SAMPLES))
        self.log_path = (log_path if log_path is not None
                         else envs.get(envs.AUTOTUNE_LOG))
        self._sync = sync  # rank-0 decision broadcast; see _synced_decision
        self._mu = threading.Lock()
        self._bytes = 0
        self._steps = 0
        self._sample_start = time.monotonic()
        self._sample_idx = 0
        self._active = [t for t in self.tunables if not t.fixed
                        and len(t.candidates) > 1]
        self._coord = 0          # which tunable is being swept
        self._cand = 0           # candidate index under trial
        self._best_score = None
        self._best_state = [t.index for t in self.tunables]
        self._pass_improved = False
        self.converged = not self._active
        self._log_writer = None
        if self.log_path:
            f = open(self.log_path, "w", newline="")
            self._log_writer = csv.writer(f)
            self._log_writer.writerow(
                ["sample", "score_bytes_per_sec", "warmup", "converged"]
                + [t.knob for t in self.tunables])
            self._log_file = f
        for t in self.tunables:
            t.apply()

    # -- recording ---------------------------------------------------------

    def record(self, nbytes: int) -> None:
        """Account one eager collective's wire payload; sample boundaries
        land every ``steps_per_sample`` records. Cheap: one lock, two adds."""
        if self.converged:
            return
        with self._mu:
            self._bytes += int(nbytes)
            self._steps += 1
            if self._steps < self.steps_per_sample:
                return
            elapsed = time.monotonic() - self._sample_start
            score = self._bytes / max(elapsed, 1e-9)
            self._bytes = 0
            self._steps = 0
            self._end_sample(score)
            self._sample_start = time.monotonic()

    # -- search ------------------------------------------------------------

    def _state(self) -> list[int]:
        return [t.index for t in self.tunables]

    def _apply_state(self, state: list[int]) -> None:
        for t, i in zip(self.tunables, state):
            t.index = i
            t.apply()

    def _end_sample(self, score: float) -> None:
        warmup = self._sample_idx < self.warmup_samples
        self._log(score, warmup)
        self._sample_idx += 1
        if warmup:
            return
        decision = self._synced_decision(score)
        self._apply_state(decision["state"])
        if decision["converged"]:
            self._finish(decision["state"])

    def _local_decision(self, score: float) -> dict:
        """Advance the coordinate search by one scored sample."""
        if self._best_score is None or score > self._best_score:
            self._best_score = score
            self._best_state = self._state()
            self._pass_improved = True
        if self._sample_idx - self.warmup_samples >= self.max_samples:
            return {"state": self._best_state, "converged": True}
        # move to the next candidate of the current coordinate, or the next
        # coordinate (restarting from the best state found so far)
        tun = self._active[self._coord]
        self._cand += 1
        if self._cand >= len(tun.candidates):
            self._cand = 0
            self._coord += 1
            if self._coord >= len(self._active):
                # full pass done
                if not self._pass_improved:
                    return {"state": self._best_state, "converged": True}
                self._pass_improved = False
                self._coord = 0
        next_state = list(self._best_state)
        active_tun = self._active[self._coord]
        pos = self.tunables.index(active_tun)
        next_state[pos] = self._cand
        return {"state": next_state, "converged": False}

    def _synced_decision(self, score: float) -> dict:
        """Single process: decide locally. Multi-process: rank 0 averages
        everyone's score for the sample and broadcasts the decision
        (``Controller::SynchronizeParameters`` analog)."""
        if self._sync is None:
            return self._local_decision(score)
        return self._sync(self._sample_idx, score, self._local_decision)

    def _finish(self, state: list[int]) -> None:
        self.converged = True
        self._apply_state(state)
        hvd_logging.info(
            "autotune converged after %d samples: %s (score %.3g B/s)",
            self._sample_idx,
            {t.knob: t.value for t in self.tunables}, self._best_score or 0)
        self._log(self._best_score or 0.0, False)
        if self._log_writer:
            self._log_file.close()
            self._log_writer = None

    def _log(self, score: float, warmup: bool) -> None:
        if not self._log_writer:
            return
        self._log_writer.writerow(
            [self._sample_idx, f"{score:.1f}", int(warmup), int(self.converged)]
            + [t.value for t in self.tunables])
        self._log_file.flush()

    def current_config(self) -> dict:
        return {t.knob: t.value for t in self.tunables}


class KVScoreSync:
    """Rank-0 decide + broadcast over the launcher KV store."""

    def __init__(self, kv, world_size: int, rank: int,
                 prefix: str = "autotune", timeout: float = 600.0):
        self.kv = kv
        self.world_size = world_size
        self.rank = rank
        self.prefix = prefix
        self.timeout = timeout

    def __call__(self, sample_idx: int, score: float, local_decision) -> dict:
        self.kv.put(f"{self.prefix}/score/{sample_idx}/{self.rank}",
                    repr(float(score)).encode())
        if self.rank == 0:
            gather = getattr(self.kv, "gather", None)
            if gather is not None:  # one server-side round (KVClient)
                got = gather(f"{self.prefix}/score/{sample_idx}",
                             self.world_size, timeout=self.timeout)
                total = sum(float(v.decode()) for v in got.values())
            else:  # plain mapping-style stores (tests)
                total = 0.0
                for r in range(self.world_size):
                    data = self.kv.wait(
                        f"{self.prefix}/score/{sample_idx}/{r}",
                        timeout=self.timeout)
                    total += float(data.decode())
            decision = local_decision(total / self.world_size)
            self.kv.put(f"{self.prefix}/decision/{sample_idx}",
                        json.dumps(decision).encode())
        else:
            data = self.kv.wait(f"{self.prefix}/decision/{sample_idx}",
                                timeout=self.timeout)
            decision = json.loads(data.decode())
        # everyone has read sample_idx's keys before anyone writes
        # sample_idx+2 (a rank must finish its own idx+1 reads first), so
        # deleting the previous sample's keys bounds KV memory
        if sample_idx > 0:
            try:
                self.kv.delete(
                    f"{self.prefix}/score/{sample_idx - 1}/{self.rank}")
                if self.rank == 0:
                    self.kv.delete(f"{self.prefix}/decision/{sample_idx - 1}")
            except Exception:
                pass
        return decision


# ---------------------------------------------------------------------------
# process-wide manager (mirrors engine_service's lazy singleton)
# ---------------------------------------------------------------------------

_manager: ParameterManager | None = None
_manager_lock = threading.Lock()
_checked = False


def get_manager() -> ParameterManager | None:
    """The process's autotuner, or None when HVD_AUTOTUNE is off."""
    global _manager, _checked
    if _manager is not None or _checked:
        return _manager
    with _manager_lock:
        if _manager is not None or _checked:
            return _manager
        _checked = True
        if not envs.get_bool(envs.AUTOTUNE):
            return None
        sync = None
        from . import runtime
        if runtime.is_initialized() and runtime.process_count() > 1:
            kv_addr = envs.get(envs.KV_ADDR)
            if not kv_addr:
                # Without a decision channel each process would explore the
                # grid independently — and a per-process flip of
                # HIERARCHICAL_ALLREDUCE changes the SPMD program, which
                # deadlocks the job. Refuse rather than risk it (the
                # reference likewise tunes through the controller,
                # SynchronizeParameters).
                hvd_logging.warning(
                    "HVD_AUTOTUNE requested but this multi-process job has "
                    "no launcher KV store to synchronize decisions; "
                    "autotuning disabled (launch via hvdrun to enable)")
                return None
            from .runner.http_kv import KVClient
            kv = KVClient(kv_addr, envs.get_int(envs.KV_PORT, 0),
                          secret=envs.get(envs.SECRET_KEY))
            sync = KVScoreSync(kv, runtime.process_count(),
                               runtime.process_rank())
        _manager = ParameterManager(sync=sync)
        hvd_logging.info("autotune enabled: %s", _manager.current_config())
    return _manager


def record(nbytes: int) -> None:
    """Hot-path hook called by the eager collectives."""
    mgr = get_manager() if envs.get_bool(envs.AUTOTUNE) else None
    if mgr is not None:
        mgr.record(nbytes)


def reset() -> None:
    """Tear down (tests / elastic re-init)."""
    global _manager, _checked
    with _manager_lock:
        if _manager is not None:
            for t in _manager.tunables:
                envs.clear_override(t.knob)
            if _manager._log_writer:
                _manager._log_file.close()
        _manager = None
        _checked = False
