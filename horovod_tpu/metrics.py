"""Unified metrics registry: counters/gauges/histograms with labels,
Prometheus exposition, and per-rank views.

The reference Horovod's operational surfaces — the timeline's NEGOTIATE
lanes and the stall inspector naming lagging ranks — answer "where does
time go" and "which rank is slow". This module is the rebuild's one
telemetry namespace for those questions: every subsystem that used to
keep an ad-hoc stats dict (``fusion_stats``, ``dispatch_cache_stats``,
``health_stats`` retry counters) now records into — or mirrors onto —
instruments registered **here**, and two exposition surfaces read them
back:

* ``GET /metrics`` — Prometheus text format, served by the launcher KV
  server (``runner/http_kv.py``) and, per worker, by a standalone
  exposition server on ``HVD_METRICS_PORT`` (+ process rank);
* ``hvd.metrics_dump()`` — the same samples as JSON-shaped dicts.

**Catalog discipline.** Every instrument is declared below, at module
level, with a literal name — hvdlint pass 8 (``metrics-registry``)
round-trips this catalog against docs/metrics.md in both directions and
bans ad-hoc module-level telemetry counters elsewhere in the tree, the
same pattern the knob-registry pass applies to ``utils/envs.py``.

**Worlds and the ``rank`` label.** Values live in per-world *stores*:
the process-wide store, plus one per loopback :class:`RankContext` —
a rank thread's increments land in its own store, so one rank's
counters never bleed into a peer's view (``metrics_dump()`` on a rank
thread reads that rank's world). Exposition iterates every live store
and injects the store's global rank as a ``rank`` label — unless the
series already carries one (``hvd_straggler_rounds_total{rank=...}``
names the *straggler*, not the reporter, and aggregates across
reporters).

**Overhead contract** (gated by ``bench.py --metrics-bench`` in ci.sh):
with ``HVD_METRICS=0`` every hot-path instrument's record method is a
cached-bool no-op (the ``utils/faults.py`` fast-path idiom).
Instruments marked ``always=True`` back a legacy ``*_stats()`` API and
keep recording regardless — they replaced equally-priced dict
mutations, so disabling them would change an existing API's behavior
without saving anything.

Deliberately light on imports (envs + the stdlib + the loopback context
seam) and deliberately on **plain** ``threading.Lock``, not the
``utils/invariants.py`` constructor seam: the metrics lock is a leaf —
nothing is ever acquired under it and it never blocks on anything — so
routing it through the cooperative scheduler would only multiply
hvdsched's schedule space without adding a single explorable conflict.
"""

from __future__ import annotations

import json
import threading
import weakref

from .loopback import context as _lbctx
from .utils import envs

__all__ = [
    "Counter", "Gauge", "Histogram", "counter", "gauge", "histogram",
    "enabled", "refresh", "set_enabled", "instruments", "snapshot",
    "delta", "prometheus_text", "dump", "metrics_dump", "serve",
    "maybe_serve", "stop_serving", "reset",
]

# --------------------------------------------------------------------------
# enable gate (cached; near-zero when off)
# --------------------------------------------------------------------------

_force_enabled: bool | None = None  # tests/bench override; None = knob


def _read_enabled() -> bool:
    if _force_enabled is not None:
        return _force_enabled
    return envs.get_bool(envs.METRICS, True)


_enabled = _read_enabled()


def enabled() -> bool:
    """Whether hot-path instruments record (``HVD_METRICS``, default on).
    ``always=True`` instruments (legacy ``*_stats()`` storage) record
    regardless — see the module docstring's overhead contract."""
    return _enabled


def refresh() -> None:
    """Re-read ``HVD_METRICS`` (tests toggle it after import)."""
    global _enabled
    _enabled = _read_enabled()


def set_enabled(value: bool | None) -> None:
    """Force the gate on/off (``None`` restores the knob) — the bench's
    interleaved on/off passes and tests use this; production uses the
    knob."""
    global _force_enabled
    _force_enabled = value
    refresh()


# --------------------------------------------------------------------------
# per-world value stores
# --------------------------------------------------------------------------

_mu = threading.Lock()  # leaf lock: guards stores + series maps only


class _Store:
    """One world's sample values: ``{(name, labelitems): value}`` where
    ``labelitems`` is a sorted tuple of ``(label, value)`` pairs.
    Histogram series hold a ``_Hist``."""

    __slots__ = ("values", "rank")

    def __init__(self, rank: str = ""):
        self.values: dict = {}
        self.rank = rank  # exposition's injected rank label ("" unknown)


class _Hist:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, nbuckets: int):
        self.counts = [0] * nbuckets  # cumulative at exposition, raw here
        self.sum = 0.0
        self.count = 0


_process_store = _Store()
# RankContext -> _Store; weak keys so an elastic run's dead worlds don't
# pin their stores (RankContext carries __weakref__ for exactly this).
_ctx_stores: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _store() -> _Store:
    """The calling thread's world store (rank ctx or process-wide)."""
    ctx = _lbctx.current()
    if ctx is None:
        return _process_store
    store = _ctx_stores.get(ctx)
    if store is None:
        with _mu:
            store = _ctx_stores.get(ctx)
            if store is None:
                store = _Store(rank=str(ctx.rank))
                _ctx_stores[ctx] = store
    return store


def _process_rank_label() -> str:
    """The process store's rank label: the launcher-seeded process rank
    when this is a worker, else empty (single-controller drivers have no
    rank identity worth asserting)."""
    r = envs.get(envs.RANK)
    return r if r is not None else ""


def _all_stores() -> list[_Store]:
    """Every live store, process store first (exposition iterates these;
    rank stores carry their rank label)."""
    _process_store.rank = _process_rank_label()
    with _mu:
        return [_process_store] + sorted(
            _ctx_stores.values(), key=lambda s: s.rank)


def reset_all(*instruments) -> None:
    """Drop the named instruments' series in EVERY live store — the
    process store and all rank worlds' (``_Instrument.reset`` only
    touches the calling thread's own store). Bench lanes that run
    several loopback worlds in one process use this to isolate each
    lane's counters."""
    names = {inst.name for inst in instruments}
    with _mu:
        for store in [_process_store] + list(_ctx_stores.values()):
            for k in [k for k in store.values if k[0] in names]:
                del store.values[k]


# --------------------------------------------------------------------------
# instruments
# --------------------------------------------------------------------------

_registry: "dict[str, _Instrument]" = {}


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, help: str, labels=(),
                 always: bool = False):
        self.name = name
        self.help = help
        self.labelnames = tuple(labels)
        self.always = always

    # -- recording ---------------------------------------------------------

    def _on(self) -> bool:
        return _enabled or self.always

    def _key(self, labels) -> tuple:
        if labels is None:
            if self.labelnames:
                raise ValueError(
                    f"{self.name} requires labels {self.labelnames}")
            return (self.name, ())
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name} labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return (self.name,
                tuple(sorted((k, str(v)) for k, v in labels.items())))

    # -- reading -----------------------------------------------------------

    def series(self, store: _Store | None = None) -> dict:
        """``{labelitems: value}`` for this instrument in ``store``
        (default: the calling thread's world)."""
        store = store if store is not None else _store()
        with _mu:
            return {k[1]: v for k, v in store.values.items()
                    if k[0] == self.name}

    def value(self, labels=None, default=0.0):
        key = self._key(labels)
        store = _store()  # resolve BEFORE _mu: a first-touch store
        with _mu:         # creation re-acquires the registry lock
            return store.values.get(key, default)

    def reset(self) -> None:
        """Drop this instrument's series in the calling thread's world
        (the legacy ``reset_stats()`` surfaces)."""
        store = _store()
        with _mu:
            for k in [k for k in store.values if k[0] == self.name]:
                del store.values[k]

    def bind(self, labels=None) -> "_Bound":
        """Pre-resolve a label set into a bound series handle: the
        label-validation + sort cost is paid once, and the hot path
        (``inc``/``set``/``observe`` on the handle) is a dict update
        under the leaf lock. Callers on per-call hot paths (the fusion
        scheduler's per-tenant counters) cache these."""
        return _Bound(self, self._key(labels))


# Shared recording bodies: the unbound instrument methods and the bound
# handles both land here, so the storage semantics live in one place.

def _rec_add(inst: "_Instrument", key: tuple, amount: float) -> None:
    if not inst._on():
        return
    store = _store()
    with _mu:
        store.values[key] = store.values.get(key, 0.0) + amount


def _rec_set(inst: "_Instrument", key: tuple, value: float) -> None:
    if not inst._on():
        return
    store = _store()
    with _mu:
        store.values[key] = float(value)


def _rec_observe(inst: "Histogram", key: tuple, value: float) -> None:
    if not inst._on():
        return
    store = _store()
    with _mu:
        h = store.values.get(key)
        if h is None:
            h = store.values[key] = _Hist(len(inst.buckets))
        for i, bound in enumerate(inst.buckets):
            if value <= bound:
                h.counts[i] += 1
                break
        # past the last bound: lands only in the implicit +Inf bucket,
        # which exposition derives from the total count
        h.sum += value
        h.count += 1


class _Bound:
    __slots__ = ("inst", "key")

    def __init__(self, inst: "_Instrument", key: tuple):
        self.inst = inst
        self.key = key

    def inc(self, amount: float = 1) -> None:
        _rec_add(self.inst, self.key, amount)

    def set(self, value: float) -> None:
        _rec_set(self.inst, self.key, value)

    def observe(self, value: float) -> None:
        _rec_observe(self.inst, self.key, value)


class Counter(_Instrument):
    kind = "counter"

    def inc(self, amount: float = 1, labels=None) -> None:
        _rec_add(self, self._key(labels), amount)


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, labels=None) -> None:
        _rec_set(self, self._key(labels), value)

    def add(self, amount: float, labels=None) -> None:
        _rec_add(self, self._key(labels), amount)


# Default histogram buckets: negotiation rounds over an HTTP KV span
# single-digit ms (loopback, one host) to seconds (pod-scale fan-in);
# the straggler threshold default (1 s) sits inside the range.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0)


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, name: str, help: str, labels=(),
                 buckets=DEFAULT_BUCKETS, always: bool = False):
        super().__init__(name, help, labels, always)
        self.buckets = tuple(sorted(buckets))

    def observe(self, value: float, labels=None) -> None:
        _rec_observe(self, self._key(labels), value)


def _register(inst: _Instrument) -> _Instrument:
    if inst.name in _registry:
        raise ValueError(f"metric {inst.name!r} already registered")
    _registry[inst.name] = inst
    return inst


def counter(name: str, help: str, labels=(), always: bool = False) -> Counter:
    return _register(Counter(name, help, labels, always))


def gauge(name: str, help: str, labels=(), always: bool = False) -> Gauge:
    return _register(Gauge(name, help, labels, always))


def histogram(name: str, help: str, labels=(), buckets=DEFAULT_BUCKETS,
              always: bool = False) -> Histogram:
    return _register(Histogram(name, help, labels, buckets, always))


def instruments() -> dict:
    """The registered catalog: ``{name: instrument}``."""
    return dict(_registry)


# --------------------------------------------------------------------------
# THE INSTRUMENT CATALOG
# (docs/metrics.md round-trips with this block — hvdlint pass 8)
# --------------------------------------------------------------------------

# -- negotiation protocol (engine_service.KVTransport / DynamicService) ----
NEGOTIATION_ROUNDS = counter(
    "hvd_negotiation_rounds_total",
    "Busy negotiation rounds (cycles with local work pending).",
    labels=("process_set",))
NEGOTIATION_ROUND_SECONDS = histogram(
    "hvd_negotiation_round_seconds",
    "Wall time of one busy negotiation exchange (publish -> all "
    "members' frames gathered).",
    labels=("process_set",))
NEGOTIATION_SUBMIT_LAG = histogram(
    "hvd_negotiation_submit_lag_seconds",
    "Per-rank submit->ready breakdown: how far behind the round's first "
    "submitter each rank's frame reached the KV server (server receipt "
    "clock, skew-free).",
    labels=("rank",))
STRAGGLER_ROUNDS = counter(
    "hvd_straggler_rounds_total",
    "Rounds in which the labeled global rank was last to submit by more "
    "than HVD_STRAGGLER_THRESHOLD (the stall-check analog).",
    labels=("rank",))
RESPONSE_CACHE_HITS = counter(
    "hvd_response_cache_hits_total",
    "Negotiation requests served locally from the coordinator "
    "ResponseCache (HVD_RESPONSE_CACHE) — zero KV rounds.",
    labels=("process_set",))
RESPONSE_CACHE_MISSES = counter(
    "hvd_response_cache_misses_total",
    "Cacheable negotiation requests that took a full round (entry "
    "absent, unconfirmed, invalidated, or a join in flight).",
    labels=("process_set",))

# -- KV transport (runner/http_kv.KVClient) --------------------------------
KV_OPS = counter(
    "hvd_kv_ops_total",
    "KV client operations by verb (gather = one server-side long-poll); "
    "divide by hvd_negotiation_rounds_total for KV ops/round.",
    labels=("op",))

# -- fusion scheduler (ops/fusion_cycle.py) --------------------------------
FUSION_FLUSHES = counter(
    "hvd_fusion_flushes_total",
    "Fusion-cycle queue flushes by trigger and tenant (process set).",
    labels=("process_set", "trigger"))
FUSION_FLUSHED_TENSORS = counter(
    "hvd_fusion_flushed_tensors_total",
    "Tensors coalesced through fusion-cycle flushes, per tenant.",
    labels=("process_set",))
FUSION_FLUSHED_BYTES = counter(
    "hvd_fusion_flushed_bytes_total",
    "Payload bytes coalesced through fusion-cycle flushes, per tenant.",
    labels=("process_set",))
FUSION_ENQUEUED_TENSORS = counter(
    "hvd_fusion_enqueued_tensors_total",
    "Async submissions accepted into fusion-cycle pending queues, per "
    "tenant.",
    labels=("process_set",))
FUSION_PENDING_BYTES = gauge(
    "hvd_fusion_pending_bytes",
    "Bytes currently queued across all fusion-cycle pending queues "
    "(backpressure drains at HVD_FUSION_MAX_PENDING).")
PIPELINE_INFLIGHT_DEPTH = gauge(
    "hvd_pipeline_inflight_depth",
    "Device-incomplete earlier flushes observed at the last executor "
    "slot admission (docs/pipeline.md overlap semantics).")

# -- multi-tenant QoS (qos.py; docs/qos.md) --------------------------------
QOS_ADMISSION_WAIT = histogram(
    "hvd_qos_admission_wait_seconds",
    "Time a flush batch spent parked in the QoS admission gate (submit "
    "-> grant), per tenant (process set).",
    labels=("process_set",))
QOS_GRANTED_BYTES = counter(
    "hvd_qos_granted_bytes_total",
    "Payload bytes granted into the flush executor's slots by the QoS "
    "arbiter, per tenant.",
    labels=("process_set",))
QOS_SLOT_SHARE = gauge(
    "hvd_qos_slot_share",
    "Tenant's cumulative share (0-1) of all bytes granted into the "
    "executor slots — converges to the configured weight ratio under "
    "saturation.",
    labels=("process_set",))
QOS_SHED = counter(
    "hvd_qos_shed_total",
    "Async submissions shed at enqueue by a tenant pending-bytes quota "
    "(policy=shed); the handle raises QosAdmissionError.",
    labels=("process_set",))
QOS_QUOTA_BLOCKS = counter(
    "hvd_qos_quota_blocks_total",
    "Producer enqueues that blocked on a tenant pending-bytes quota "
    "(policy=block) until in-flight work settled.",
    labels=("process_set",))

# -- step capture (ops/step_capture.py) ------------------------------------
STEP_CAPTURE_PHASE = gauge(
    "hvd_step_capture_phase",
    "Capture lifecycle phase: 0 idle, 1 record, 2 replay (armed), "
    "3 replayed, 4 bypass.")
STEP_CAPTURE_STEPS = counter(
    "hvd_step_capture_steps_total",
    "Step-capture lifecycle events by kind (recorded / replayed / "
    "fallback / invalidated / uncapturable).",
    labels=("event",))

# -- GSPMD cached-program fast path (ops/gspmd_cache.py) -------------------
GSPMD_CACHE_PHASE = gauge(
    "hvd_gspmd_cache_phase",
    "GSPMD cached-step lifecycle phase (the step-capture vocabulary): "
    "0 idle, 1 record (building), 3 replayed, 4 bypass.")
GSPMD_CACHE_STEPS = counter(
    "hvd_gspmd_cache_steps_total",
    "GSPMD cached-step lifecycle events by kind (recorded / replayed / "
    "fallback / invalidated / bypass).",
    labels=("event",))
GSPMD_PASSTHROUGH_SYNCS = counter(
    "hvd_gspmd_passthrough_syncs_total",
    "Gradient syncs traced through DistributedOptimizer's GSPMD "
    "passthrough branch (once per TRACE, not per step — frozen while "
    "cached steps replay).")

# -- dispatch plan cache (ops/dispatch_cache.py; backs
#    hvd.dispatch_cache_stats() -- always on) ------------------------------
DISPATCH_HITS = counter(
    "hvd_dispatch_plan_hits_total",
    "Dispatch-plan cache hits by source (call / flush / step / gspmd).",
    labels=("source",), always=True)
DISPATCH_MISSES = counter(
    "hvd_dispatch_plan_misses_total",
    "Dispatch-plan cache misses (plan built per call).", always=True)
DISPATCH_INVALIDATIONS = counter(
    "hvd_dispatch_plan_invalidations_total",
    "Plans dropped by epoch flushes / service resets / removals.",
    always=True)
DISPATCH_EVICTIONS = counter(
    "hvd_dispatch_plan_evictions_total",
    "Plans LRU-evicted past HVD_CACHE_CAPACITY.", always=True)
DISPATCH_NEGOTIATION_SKIPS = counter(
    "hvd_dispatch_negotiation_skips_total",
    "Negotiation rounds skipped (pinned no-service decision or engine "
    "response-cache hit).", always=True)
DISPATCH_CHUNKED_BUILDS = counter(
    "hvd_dispatch_chunked_builds_total",
    "Chunk-pipelined plan variants built (fused wire buffers past "
    "HVD_PIPELINE_THRESHOLD).", always=True)
DISPATCH_STEP_BUILDS = counter(
    "hvd_dispatch_step_builds_total",
    "Whole-step capture plans built (ops/step_capture.py).", always=True)
DISPATCH_GSPMD_BUILDS = counter(
    "hvd_dispatch_gspmd_builds_total",
    "Compiled GSPMD step programs built (ops/gspmd_cache.py: one "
    "lower+compile per new step signature).", always=True)

# -- retry ladder (utils/retry.py; backs hvd.health_stats()["retries"]
#    -- always on) ---------------------------------------------------------
RETRY_RETRIES = counter(
    "hvd_retry_retries_total",
    "Retries taken per RPC/KV site (the HVD_RETRY_* backoff ladder).",
    labels=("site",), always=True)
RETRY_GIVEUPS = counter(
    "hvd_retry_giveups_total",
    "Retryable failures that exhausted attempts/deadline per site.",
    labels=("site",), always=True)

# -- health watchdog (health.py) -------------------------------------------
HEALTH_BEATS = counter(
    "hvd_health_beats_total",
    "Liveness beats published by this rank's watchdogs.")
HEALTH_BEAT_ERRORS = counter(
    "hvd_health_beat_errors_total",
    "Beat publishes that failed through the whole retry ladder.")
HEALTH_PEER_FAILURES = counter(
    "hvd_health_peer_failures_total",
    "Peer-death decisions, labeled with the dead global rank.",
    labels=("rank",))

# -- fault injection (utils/faults.py) -------------------------------------
FAULT_FIRES = counter(
    "hvd_fault_fires_total",
    "Injected faults fired per site (HVD_FAULT_SPEC chaos runs only).",
    labels=("site",))

# -- elastic churn / warm re-form (elastic/, docs/elastic.md) --------------
ELASTIC_EVENTS = counter(
    "hvd_elastic_events_total",
    "Elastic membership + recovery events by kind: scripted churn "
    "(add / remove / preempt), worker-side recoveries (hosts-updated "
    "interrupt, peer-failure restore).",
    labels=("kind",))
ELASTIC_REFORM_SECONDS = histogram(
    "hvd_elastic_reform_seconds",
    "Worker-side re-form duration: interrupt/failure caught -> "
    "re-rendezvoused into the new round, state synced, training "
    "re-entered (the recovery-time SLO numerator).",
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0, 45.0, 90.0, 180.0))
ELASTIC_STEPS_LOST = counter(
    "hvd_elastic_steps_lost_total",
    "In-flight steps rolled back by a failure restore (commit-per-step "
    "convention: each HorovodInternalError recovery counts its one "
    "uncommitted step; graceful interrupts count zero).")
ELASTIC_WARM_REUSE = counter(
    "hvd_elastic_warm_reuse_total",
    "Shape-keyed state reused across an elastic re-form, by kind: "
    "plan (dispatch plans grafted from the warm pool), step (whole-step "
    "capture plans), response (coordinator response-cache entries "
    "re-armed after the warm confirmation round).",
    labels=("kind",), always=True)

# -- closed-loop autoscaling (elastic/policy.py, docs/elastic.md) ----------
ELASTIC_STEP_SECONDS = histogram(
    "hvd_elastic_step_seconds",
    "Wall time between consecutive elastic state commits (the per-step "
    "latency the autoscale policy's SLO rule watches).",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
             30.0))
ELASTIC_SLO_VIOLATIONS = counter(
    "hvd_elastic_slo_violations_total",
    "Committed steps whose commit-to-commit wall time exceeded the "
    "HVD_AUTOSCALE_SLO_MS target (recorded only with a nonzero target).")
ELASTIC_POLICY_DECISIONS = counter(
    "hvd_elastic_policy_decisions_total",
    "Autoscale policy decisions by action (add / remove / evict / hold) "
    "and reason (slo-breach / idle / straggler / stale-round / protected "
    "/ restore-cost / error); rank names the blamed global rank on "
    "evictions, empty otherwise.",
    labels=("action", "reason", "rank"), always=True)

# -- checkpoint state plane (checkpoint.py, docs/checkpoint.md) ------------
CKPT_SNAPSHOT_SECONDS = histogram(
    "hvd_ckpt_snapshot_seconds",
    "Background snapshot duration on the writer thread: this rank's "
    "shard pickled + written + fsync-renamed (rank 0 adds the manifest "
    "wait/write) — off the training critical path by construction.",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0))
CKPT_SHARDS_WRITTEN = counter(
    "hvd_ckpt_shards_written_total",
    "Snapshot shards durably written by this rank (one per triggered "
    "snapshot that completed its atomic rename).")
CKPT_RESTORE_SECONDS = histogram(
    "hvd_ckpt_restore_seconds",
    "Re-form state re-sync duration: manifest-agree round entered -> "
    "attributes restored (peer shard pulls, or the degraded rank-0 "
    "broadcast). The restore half of the recovery-SLO lane.",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0))
CKPT_PEER_SHARDS_PULLED = counter(
    "hvd_ckpt_peer_shards_pulled_total",
    "Shards this rank pulled from survivors during peer-restore, by "
    "transport (hub = in-world loopback rendezvous, kv = the fallback "
    "KV channel).",
    labels=("transport",))
CKPT_RESTORE_BYTES = counter(
    "hvd_ckpt_restore_bytes_total",
    "State-restore payload bytes this rank received, by source (rank0 "
    "= served by rank 0: degraded broadcasts plus shards rank 0 "
    "happened to own; peer = shards served by other survivors). The "
    "recovery lane gates peer-restore moving strictly fewer rank0 "
    "bytes than the broadcast baseline.",
    labels=("source",))
CKPT_DEGRADED_RESTORES = counter(
    "hvd_ckpt_degraded_restores_total",
    "Re-forms that fell back to the rank-0 full-tree broadcast, by "
    "reason (quorum = too few consistent survivors, structure = the "
    "joiner's state tree shape disagreed, pull-failed = shard pulls "
    "exhausted their failover retry).",
    labels=("reason",))


# --------------------------------------------------------------------------
# snapshot / delta
# --------------------------------------------------------------------------

def snapshot() -> dict:
    """Flat copy of the calling thread's world: ``{(name, labelitems):
    value}``; histogram series flatten to ``(name+"_sum"/"_count", ...)``
    entries so deltas stay numeric."""
    store = _store()
    out: dict = {}
    with _mu:
        items = list(store.values.items())
    for (name, labelitems), v in items:
        if isinstance(v, _Hist):
            out[(name + "_sum", labelitems)] = v.sum
            out[(name + "_count", labelitems)] = v.count
        else:
            out[(name, labelitems)] = v
    return out


def delta(new: dict, old: dict) -> dict:
    """Per-series difference ``new - old`` (series absent from ``old``
    count from zero; gauges subtract like everything else)."""
    return {k: v - old.get(k, 0.0) for k, v in new.items()}


# --------------------------------------------------------------------------
# exposition
# --------------------------------------------------------------------------

def _escape(value: str) -> str:
    return (value.replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _inject_store_rank(labels: dict, store_rank: str) -> dict:
    """Merged-store disambiguation: a store's global rank is injected as
    ``rank`` — or as ``reporter`` when the series already names a peer
    in its ``rank`` label (``hvd_straggler_rounds_total{rank=...}``
    names the *straggler*; the reporter keeps its own series so merged
    exposition never emits two samples with identical labels)."""
    if store_rank:
        if "rank" not in labels:
            labels["rank"] = store_rank
        elif "reporter" not in labels:
            labels["reporter"] = store_rank
    return labels


def _fmt_num(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _merged_series(stores) -> dict:
    """``{(name, labelitems-after-rank-injection): value}`` across
    ``stores``. Identical label sets from different stores MERGE —
    counters and histograms sum, gauges take the last writer. Two live
    ranks never collide (the injected ``rank``/``reporter`` labels
    differ); merging covers *incarnations* of the same rank — elastic
    re-forms, a previous loopback world in the same interpreter — whose
    counter totals should accumulate, exactly like a restarted process
    behind one Prometheus target."""
    merged: dict = {}
    for store in stores:
        with _mu:
            items = list(store.values.items())
        for (name, labelitems), v in items:
            key = (name, tuple(sorted(_inject_store_rank(
                dict(labelitems), store.rank).items())))
            prior = merged.get(key)
            if prior is None:
                if isinstance(v, _Hist):
                    copy = _Hist(len(v.counts))
                    copy.counts = list(v.counts)
                    copy.sum, copy.count = v.sum, v.count
                    merged[key] = copy
                else:
                    merged[key] = v
            elif isinstance(v, _Hist):
                prior.counts = [a + b
                                for a, b in zip(prior.counts, v.counts)]
                prior.sum += v.sum
                prior.count += v.count
            else:
                inst = _registry.get(name)
                if inst is not None and inst.kind == "gauge":
                    merged[key] = v  # last incarnation wins
                else:
                    merged[key] = prior + v
    return merged


def _plain_labels(labelitems) -> str:
    if not labelitems:
        return ""
    return ("{" + ",".join(f'{k}="{_escape(str(v))}"'
                           for k, v in labelitems) + "}")


def prometheus_text(all_worlds: bool = True) -> str:
    """The ``/metrics`` payload (Prometheus text format 0.0.4): every
    registered instrument emits its HELP/TYPE header even with no
    samples yet (the CI completeness gate relies on that), then one
    sample line per merged series, the store's global rank injected as
    a ``rank`` label unless the series carries its own (then as
    ``reporter`` — see :func:`_merged_series`)."""
    stores = _all_stores() if all_worlds else [_store()]
    per_name: dict[str, list[str]] = {}
    for (name, labelitems), v in _merged_series(stores).items():
        lines = per_name.setdefault(name, [])
        labels = _plain_labels(labelitems)
        inst = _registry.get(name)
        if isinstance(v, _Hist):
            cum = 0
            bounds = inst.buckets if inst is not None else ()
            base = list(labelitems)
            for i, bound in enumerate(bounds):
                cum += v.counts[i] if i < len(v.counts) else 0
                bl = _plain_labels(
                    tuple(sorted(base + [("le", f"{bound:g}")])))
                lines.append(f"{name}_bucket{bl} {cum}")
            bl = _plain_labels(tuple(sorted(base + [("le", "+Inf")])))
            lines.append(f"{name}_bucket{bl} {v.count}")
            lines.append(f"{name}_sum{labels} {_fmt_num(v.sum)}")
            lines.append(f"{name}_count{labels} {v.count}")
        else:
            lines.append(f"{name}{labels} {_fmt_num(v)}")
    out: list[str] = []
    for name, inst in sorted(_registry.items()):
        out.append(f"# HELP {name} {_escape(inst.help)}")
        out.append(f"# TYPE {name} {inst.kind}")
        out.extend(sorted(per_name.get(name, ())))
    return "\n".join(out) + "\n"


def dump(all_worlds: bool = False) -> dict:
    """``hvd.metrics_dump()``: the registered instruments with their
    series as JSON-shaped dicts. Default scope is the calling thread's
    world (a loopback rank dumps its own view); ``all_worlds=True``
    merges every live store with injected ``rank`` labels, like
    ``/metrics``."""
    stores = _all_stores() if all_worlds else [_store()]
    out: dict = {}
    for name, inst in sorted(_registry.items()):
        entry = {"type": inst.kind, "help": inst.help,
                 "labels": list(inst.labelnames), "series": []}
        if inst.kind == "histogram":
            entry["buckets"] = list(inst.buckets)
        out[name] = entry
    for (name, labelitems), v in _merged_series(stores).items():
        entry = out.get(name)
        if entry is None:
            continue
        labels = dict(labelitems)
        if isinstance(v, _Hist):
            entry["series"].append({
                "labels": labels, "count": v.count, "sum": v.sum,
                "bucket_counts": list(v.counts)})
        else:
            entry["series"].append({"labels": labels, "value": v})
    for entry in out.values():
        entry["series"].sort(key=lambda s: sorted(s["labels"].items()))
    return out


metrics_dump = dump  # the hvd.metrics_dump alias


# --------------------------------------------------------------------------
# standalone exposition server (HVD_METRICS_PORT)
# --------------------------------------------------------------------------

_server = None
_server_thread = None


def serve(port: int = 0) -> int:
    """Serve ``GET /metrics`` (all worlds) on ``port`` from a daemon
    thread; returns the bound port. Idempotent: a running server keeps
    its port. The launcher KV server serves the same payload on its own
    ``/metrics`` route; this standalone server is for workers that do
    not own the KV server."""
    global _server, _server_thread
    with _mu:
        if _server is not None:
            return _server.server_address[1]
    import http.server

    class _Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # silence stderr chatter
            pass

        def do_GET(self):
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            body = prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    class _Server(http.server.ThreadingHTTPServer):
        daemon_threads = True
        allow_reuse_address = True

    srv = _Server(("0.0.0.0", port), _Handler)
    thread = threading.Thread(target=srv.serve_forever, daemon=True,
                              name="hvd-metrics-server")
    with _mu:
        if _server is not None:  # lost a start race
            srv.server_close()
            return _server.server_address[1]
        _server = srv
        _server_thread = thread
    thread.start()
    return srv.server_address[1]


def maybe_serve() -> int | None:
    """Start the standalone exposition server when ``HVD_METRICS_PORT``
    is seeded (by the user or ``hvdrun --metrics-port``); the bound port
    is base + the launcher process rank so co-hosted workers don't
    collide. Called from ``runtime.init()``; loopback rank threads skip
    it — their world's KV server (same process) already serves
    ``/metrics`` for every rank."""
    if not _enabled or _lbctx.current() is not None:
        return None
    base = envs.get_int(envs.METRICS_PORT, 0)
    if base <= 0:
        return None
    port = base + envs.get_int(envs.RANK, 0)
    try:
        return serve(port)
    except OSError as e:
        from .utils import logging as hvd_logging
        hvd_logging.warning("metrics exposition server failed on port "
                            "%d: %s", port, e)
        return None


def stop_serving() -> None:
    global _server, _server_thread
    with _mu:
        srv, _server = _server, None
        _server_thread = None
    if srv is not None:
        srv.shutdown()
        srv.server_close()


# --------------------------------------------------------------------------
# test / teardown helpers
# --------------------------------------------------------------------------

def reset(all_worlds: bool = False) -> None:
    """Drop every sample in the calling thread's world (or all worlds).
    Instrument registrations survive — the catalog is static."""
    if all_worlds:
        stores = _all_stores()
    else:
        stores = [_store()]
    with _mu:
        for store in stores:
            store.values.clear()
