"""Ray integration: run framework jobs on Ray actors.

TPU-native rebuild of the reference's ``RayExecutor``
(``/root/reference/horovod/ray/runner.py:168-423``: colocated actors, NIC
discovery, per-worker env setup, then the user function runs as a Horovod
rank inside each actor). The rebuild is deliberately thin — it reuses the
``hvdrun`` launcher's rendezvous internals (:class:`JobRendezvous`
KV server + coordinator endpoint, the same ``HVD_*`` env contract,
``runner/launch.py:202-343``) and lets Ray replace only ssh process
placement:

    from horovod_tpu.ray import RayExecutor

    executor = RayExecutor(num_workers=4)
    executor.start()
    results = executor.run(train_fn, args=(config,))
    executor.shutdown()

Each actor seeds the launcher env and the user function starts with the
usual ``hvd.init()``. Ray itself is imported lazily — the module imports
fine without Ray installed (Spark integration is a documented non-goal;
see README "Scope").
"""

from __future__ import annotations

from typing import Any, Callable

from ..runner import hosts as hosts_mod
from ..runner.launch import JobRendezvous


def _make_worker_cls(ray):
    class _HvdWorker:
        """One rank. Plain class wrapped by ``ray.remote`` at runtime."""

        def __init__(self):
            self._env: dict[str, str] = {}

        def node_ip(self) -> str:
            try:
                return ray.util.get_node_ip_address()
            except Exception:
                import socket
                return socket.gethostbyname(socket.gethostname())

        def find_free_port(self) -> int:
            import socket
            s = socket.socket()
            s.bind(("", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        def set_env(self, env: dict) -> None:
            import os
            self._env = dict(env)
            os.environ.update(self._env)

        def execute(self, fn, args, kwargs):
            return fn(*args, **(kwargs or {}))

    return _HvdWorker


class RayExecutor:
    """Launch ``num_workers`` ranks as Ray actors (reference
    ``RayExecutor``). ``cpus_per_worker``/``resources_per_worker`` map to
    the actor's resource request (a TPU-slice worker typically requests
    the host resource tagging its TPU VM)."""

    def __init__(self, num_workers: int, *, cpus_per_worker: int = 1,
                 resources_per_worker: dict | None = None,
                 env_vars: dict | None = None):
        self.num_workers = int(num_workers)
        self.cpus_per_worker = cpus_per_worker
        self.resources_per_worker = resources_per_worker or {}
        self.env_vars = dict(env_vars or {})
        self.workers: list = []
        self._rdv: JobRendezvous | None = None
        self._ray = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Create the actors and seed the rendezvous env on each."""
        import ray  # lazy: the module must import without Ray installed

        self._ray = ray
        if not ray.is_initialized():
            ray.init()
        worker_cls = ray.remote(_make_worker_cls(ray))
        opts = dict(num_cpus=self.cpus_per_worker)
        if self.resources_per_worker:
            opts["resources"] = self.resources_per_worker
        self.workers = [worker_cls.options(**opts).remote()
                        for _ in range(self.num_workers)]

        ips = ray.get([w.node_ip.remote() for w in self.workers])
        slots = self._build_slots(ips)
        self._rdv = JobRendezvous(slots)
        # the jax.distributed coordinator lives in rank 0's actor
        self._rdv.coord_addr = ips[0]
        self._rdv.coord_port = ray.get(self.workers[0].find_free_port.remote())
        ray.get([
            w.set_env.remote(self._rdv.worker_env(slot, self.env_vars))
            for w, slot in zip(self.workers, slots)])

    def _build_slots(self, ips: list) -> list:
        """Rank assignment from actor colocation (shared planner,
        ``hosts.slots_from_ips``)."""
        return hosts_mod.slots_from_ips(ips)

    # -- execution ---------------------------------------------------------

    def run_remote(self, fn: Callable, args=(), kwargs=None) -> list:
        """Dispatch ``fn`` on every worker; returns the Ray futures."""
        if not self.workers:
            raise RuntimeError("RayExecutor.start() has not been called")
        return [w.execute.remote(fn, args, kwargs) for w in self.workers]

    def run(self, fn: Callable, args=(), kwargs=None) -> list:
        """Run ``fn(*args, **kwargs)`` as rank ``i`` on worker ``i`` and
        return the per-rank results (reference ``RayExecutor.run``)."""
        futures = self.run_remote(fn, args, kwargs)
        return self._ray.get(futures)

    def execute_single(self, fn: Callable, args=(), kwargs=None) -> Any:
        """Run ``fn`` on rank 0 only (reference ``execute_single``)."""
        if not self.workers:
            raise RuntimeError("RayExecutor.start() has not been called")
        return self._ray.get(
            self.workers[0].execute.remote(fn, args, kwargs))

    def shutdown(self) -> None:
        """Kill the actors and stop the rendezvous KV server."""
        if self._ray is not None:
            for w in self.workers:
                try:
                    self._ray.kill(w)
                except Exception:  # hvdlint: disable=silent-except
                    pass  # actor already dead / cluster gone at shutdown
        self.workers = []
        if self._rdv is not None:
            self._rdv.stop()
            self._rdv = None
