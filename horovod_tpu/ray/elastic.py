"""Elastic Ray integration: fault-tolerant jobs on Ray actors.

TPU-native rebuild of the reference's unified elastic Ray executor
(``/root/reference/horovod/ray/elastic_v2.py:1-547`` and ``elastic.py``):
host discovery reads Ray's live cluster state, workers run as actors
pinned to discovered nodes, and dead nodes are replaced mid-run. The
rebuild reuses the framework's elastic core unchanged — the
:class:`~horovod_tpu.elastic.driver.ElasticDriver` round protocol, the
signed KV rendezvous, blacklisting, and the worker-side
``hvd.elastic.run`` state recovery all behave exactly as under
``hvdrun --min-np``; Ray replaces only *process placement* (the same
design split as the static :class:`~horovod_tpu.ray.runner.RayExecutor`).

    from horovod_tpu.ray import ElasticRayExecutor

    ex = ElasticRayExecutor(min_workers=2, max_workers=8)
    ex.start()
    results = ex.run(train_fn)   # fn uses hvd.elastic.run internally
    ex.shutdown()
"""

from __future__ import annotations

import threading
from typing import Any, Callable

from ..runner import hosts as hosts_mod
from ..runner.launch import worker_env
from ..utils import logging as hvd_logging


class RayHostDiscovery:
    """Discover usable hosts from Ray's cluster state (reference
    ``RayHostDiscovery``, ``elastic_v2.py``): every alive node contributes
    ``floor(node_cpus / cpus_per_worker)`` slots, optionally bounded by
    custom resource requirements. Plugs into the elastic driver's
    ``HostManager`` exactly like a discovery script."""

    def __init__(self, ray_module, cpus_per_worker: int = 1,
                 resources_per_worker: dict | None = None,
                 max_slots_per_host: int | None = None):
        self._ray = ray_module
        self.cpus_per_worker = max(int(cpus_per_worker), 1)
        self.resources_per_worker = dict(resources_per_worker or {})
        self.max_slots_per_host = max_slots_per_host

    def find_available_hosts_and_slots(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for node in self._ray.nodes():
            if not node.get("Alive"):
                continue
            host = node.get("NodeManagerAddress")
            res = node.get("Resources", {}) or {}
            slots = int(res.get("CPU", 0) // self.cpus_per_worker)
            for name, need in self.resources_per_worker.items():
                if need > 0:
                    slots = min(slots, int(res.get(name, 0) // need))
            if self.max_slots_per_host is not None:
                slots = min(slots, self.max_slots_per_host)
            if host and slots > 0:
                out[host] = slots
        return out


class _ActorProcess:
    """Adapt a (Ray actor, in-flight ObjectRef) pair to the process-handle
    contract the elastic driver supervises (``poll``/``wait``/
    ``terminate`` with exit codes, like ``safe_exec.ExecutedProcess``).
    ``sys.exit(code)`` inside the worker fn (the slot-lost self-exit path)
    maps onto the same codes a subprocess worker would return."""

    def __init__(self, ray_module, actor, ref):
        self._ray = ray_module
        self._actor = actor
        self._ref = ref
        self._code: int | None = None
        self._result: Any = None

    def _settle(self, timeout: float | None) -> int | None:
        if self._code is not None:
            return self._code
        done, _ = self._ray.wait([self._ref], timeout=timeout)
        if not done:
            return None
        try:
            status, payload = self._ray.get(self._ref)
            if status == "ok":
                self._code, self._result = 0, payload
            else:  # ("exit", code) — worker self-exited
                self._code = int(payload)
        except Exception as e:
            hvd_logging.debug("elastic ray worker raised: %s", e)
            self._code = 1
        return self._code

    def poll(self) -> int | None:
        return self._settle(0)

    def wait(self, timeout: float | None = None) -> int:
        code = self._settle(timeout)
        if code is None:
            raise TimeoutError("ray worker still running")
        return code

    def result(self):
        return self._result

    def terminate(self) -> None:
        if self._code is None:
            self._code = 143
        try:
            self._ray.kill(self._actor)
        except Exception:  # hvdlint: disable=silent-except
            pass  # actor already dead / cluster gone at terminate


class _ElasticWorker:
    """One elastic rank: seeds the launcher env then runs the user fn
    (which drives ``hvd.elastic.run`` / ``WorkerRendezvous`` exactly as a
    subprocess worker would)."""

    def execute(self, env: dict, fn, args, kwargs):
        import os
        os.environ.update(env)
        try:
            return ("ok", fn(*args, **(kwargs or {})))
        except SystemExit as e:  # slot-lost / driver-stop self-exit
            return ("exit", int(e.code or 0))


def _make_elastic_worker_cls(ray_module=None):
    """Worker class hook (tests substitute an env-passing variant)."""
    return _ElasticWorker


class ElasticRayExecutor:
    """Elastic job on Ray actors (reference ``ElasticRayExecutor``,
    ``elastic_v2.py:260-547``). The user fn must wrap its training loop in
    ``hvd.elastic.run`` (state commit/restore), exactly as under elastic
    ``hvdrun``."""

    def __init__(self, min_workers: int, max_workers: int | None = None,
                 *, cpus_per_worker: int = 1,
                 resources_per_worker: dict | None = None,
                 env_vars: dict | None = None,
                 elastic_timeout: float | None = None,
                 reset_limit: int | None = None,
                 override_discovery=None):
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers) if max_workers else None
        self.cpus_per_worker = cpus_per_worker
        self.resources_per_worker = dict(resources_per_worker or {})
        self.env_vars = dict(env_vars or {})
        self.elastic_timeout = elastic_timeout
        self.reset_limit = reset_limit
        self._override_discovery = override_discovery
        self._ray = None
        self._infra = None
        self._driver = None
        self._worker_cls = None
        self._handles: dict = {}
        self._handles_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        import ray  # lazy; the module imports without Ray installed

        self._ray = ray
        if not ray.is_initialized():
            ray.init()

    def _spawn(self, slot: hosts_mod.SlotInfo, env: dict, fn, args,
               kwargs) -> _ActorProcess:
        ray = self._ray
        if self._worker_cls is None:
            # one remote-class registration per executor, not per spawn
            self._worker_cls = ray.remote(_make_elastic_worker_cls(ray))
        worker_cls = self._worker_cls
        opts: dict = {"num_cpus": self.cpus_per_worker}
        resources = dict(self.resources_per_worker)
        # Ray's per-node custom resource pins the actor to the discovered
        # host (the reference pins with the same node resource,
        # elastic_v2.py worker placement).
        resources[f"node:{slot.hostname}"] = 0.001
        opts["resources"] = resources
        actor = worker_cls.options(**opts).remote()
        ref = actor.execute.remote(env, fn, args, kwargs)
        handle = _ActorProcess(ray, actor, ref)
        with self._handles_lock:
            self._handles[(slot.hostname, slot.local_rank)] = handle
        return handle

    def run(self, fn: Callable, args=(), kwargs: dict | None = None) -> list:
        """Run the elastic job; returns the results of the workers that
        completed the final round successfully (reference
        ``ElasticRayExecutor.run``)."""
        if self._ray is None:
            raise RuntimeError("ElasticRayExecutor.start() has not been "
                               "called")
        with self._handles_lock:
            self._handles.clear()  # a prior run()'s workers must not leak
        from ..elastic.bootstrap import make_elastic_infra

        discovery = self._override_discovery or RayHostDiscovery(
            self._ray, self.cpus_per_worker, self.resources_per_worker)
        infra = make_elastic_infra(
            discovery, self.min_workers, self.max_workers,
            timeout=self.elastic_timeout, reset_limit=self.reset_limit)
        self._infra = infra
        self._driver = infra.driver

        def create_worker_fn(slot: hosts_mod.SlotInfo, spec_round: int):
            spec = infra.round_spec(spec_round)
            env = worker_env(
                slot,
                coordinator_addr=spec["coord_addr"],
                coordinator_port=spec["coord_port"],
                kv_addr=infra.kv_addr, kv_port=infra.kv_port,
                secret=infra.secret,
                extra=infra.worker_extra_env(spec_round, self.env_vars))
            return self._spawn(slot, env, fn, args, kwargs)

        try:
            infra.driver.start(self.min_workers, create_worker_fn)
            infra.driver.join()
            results = infra.driver.get_results()
            if results.error_message:
                raise RuntimeError(
                    f"elastic ray job failed: {results.error_message}")
            if not infra.driver.succeeded:
                raise RuntimeError("elastic ray job stopped without a "
                                   "successful worker")
            # Only workers holding a slot in the FINAL round contribute
            # results: a worker from an earlier shrunk round that exited 0
            # on a slot the last round never reused would otherwise inject
            # a stale/duplicate result (ADVICE r4).
            final_slots = {(s.hostname, s.local_rank)
                           for slots in infra.driver.host_assignments.values()
                           for s in slots}
            out = []
            with self._handles_lock:
                for key, handle in self._handles.items():
                    if key in final_slots and handle.poll() == 0:
                        out.append(handle.result())
            return out
        finally:
            infra.stop()
            self._infra = None

    def shutdown(self) -> None:
        with self._handles_lock:
            for handle in self._handles.values():
                handle.terminate()
            self._handles.clear()
        if self._infra is not None:
            self._infra.stop()
            self._infra = None
