from .runner import RayExecutor

__all__ = ["RayExecutor"]
