from .elastic import ElasticRayExecutor, RayHostDiscovery
from .runner import RayExecutor

__all__ = ["RayExecutor", "ElasticRayExecutor", "RayHostDiscovery"]
