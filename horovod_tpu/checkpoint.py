"""Persistent (on-disk / cloud) checkpointing of sharded training state.

The reference has no general checkpoint subsystem — its three scoped
mechanisms (SURVEY.md §5.4) are the in-memory elastic ``State``
commit/restore, init-time ``broadcast_parameters``, and the Spark
estimators' ``Store`` persisting model state between epochs
(``/root/reference/horovod/spark/common/store.py:1-582``, HDFS/S3/local
backends). This module is the TPU-native unification SURVEY §5.4 calls
for: orbax-backed checkpoints of sharded jax pytrees, usable standalone or
as the durable layer under elastic training (commit to memory every few
steps, checkpoint to disk every epoch; after a full job restart,
``restore`` + ``hvd.broadcast_parameters`` resumes).

    import horovod_tpu as hvd
    mgr = hvd.checkpoint.Checkpointer("/ckpts/run1", max_to_keep=3)
    mgr.save(step, {"params": params, "opt_state": opt_state})
    ...
    state = mgr.restore(target={"params": params0, "opt_state": opt0})

Orbax writes each shard from the process that owns it (the multi-host
path), supports local paths and ``gs://`` buckets (via tensorstore), and
restores arrays with the shardings of the ``target`` template — the
mechanics the Spark ``Store`` delegates to HDFS clients.
"""

from __future__ import annotations

import os
from typing import Any

from .utils import logging as hvd_logging


class Checkpointer:
    """Step-indexed checkpoint directory with retention (the orbax
    ``CheckpointManager`` wrapped in the framework's conventions)."""

    def __init__(self, directory: str, *, max_to_keep: int | None = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory) \
            if "://" not in directory else directory
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    # -- save / restore ----------------------------------------------------

    def save(self, step: int, tree: Any, *, wait: bool = False) -> None:
        """Save a pytree of (possibly sharded) arrays at ``step``. Every
        process must call this (each writes its own shards). Async by
        default; ``wait=True`` blocks until durable."""
        self._mgr.save(int(step),
                       args=self._ocp.args.StandardSave(tree))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, *, step: int | None = None, target: Any = None) -> Any:
        """Restore the pytree saved at ``step`` (default: latest). With a
        ``target`` template, arrays come back with the template leaves'
        shardings/dtypes — pass your freshly-initialized state so restored
        arrays land directly on the mesh."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self.directory}")
        if target is not None:
            args = self._ocp.args.StandardRestore(target)
        else:
            args = self._ocp.args.StandardRestore()
        return self._mgr.restore(int(step), args=args)

    # -- bookkeeping -------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        try:
            self._mgr.wait_until_finished()
        finally:
            self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def save(directory: str, step: int, tree: Any) -> None:
    """One-shot save (epoch-end Spark ``Store`` idiom)."""
    with Checkpointer(directory, max_to_keep=None) as mgr:
        mgr.save(step, tree, wait=True)


def restore(directory: str, *, step: int | None = None,
            target: Any = None) -> Any:
    """One-shot restore of ``step`` (default latest)."""
    with Checkpointer(directory) as mgr:
        return mgr.restore(step=step, target=target)


def restore_or_none(directory: str, *, target: Any = None) -> Any | None:
    """Restore the latest checkpoint, or None when the directory has none
    (the resume-if-present idiom)."""
    try:
        with Checkpointer(directory) as mgr:
            if mgr.latest_step() is None:
                return None
            return mgr.restore(target=target)
    except FileNotFoundError:
        return None
    except Exception as e:
        hvd_logging.warning("checkpoint restore from %s failed: %s",
                            directory, e)
        return None
