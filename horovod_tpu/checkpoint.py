"""Persistent (on-disk / cloud) checkpointing of sharded training state,
plus the elastic **state plane**: background sharded snapshots and
peer-restore on re-form (docs/checkpoint.md).

The reference has no general checkpoint subsystem — its three scoped
mechanisms (SURVEY.md §5.4) are the in-memory elastic ``State``
commit/restore, init-time ``broadcast_parameters``, and the Spark
estimators' ``Store`` persisting model state between epochs
(``/root/reference/horovod/spark/common/store.py:1-582``, HDFS/S3/local
backends). This module unifies both halves:

* :class:`Checkpointer` — orbax-backed checkpoints of sharded jax
  pytrees, usable standalone or as the durable layer under elastic
  training (commit to memory every few steps, checkpoint to disk every
  epoch; after a full job restart, ``restore`` +
  ``hvd.broadcast_parameters`` resumes).

* :class:`StatePlane` — the state twin of the elastic warm shelf
  (docs/elastic.md): with ``HVD_CKPT_DIR`` set, a background thread
  per rank copies the committed step's state off the critical path
  every ``HVD_CKPT_INTERVAL`` commits, sharded by rank over the
  flattened tree (each rank owns ``leaf_range(rank, world)``), each
  shard an atomic temp-file+rename write with a crc digest sidecar;
  rank 0 seals the step with an atomic manifest and only then moves
  the ``latest`` pointer, so a reader can never observe a torn tree.
  The restore half (:meth:`~horovod_tpu.elastic.state.JaxState.sync`)
  re-syncs a re-formed world by pulling shards from survivors instead
  of rank 0 rebroadcasting the whole tree — over the loopback hub
  in-world, the KV transport as fallback — with digest verification on
  every pulled shard and the rank-0 broadcast as the typed, metered
  degraded path.

    import horovod_tpu as hvd
    mgr = hvd.checkpoint.Checkpointer("/ckpts/run1", max_to_keep=3)
    mgr.save(step, {"params": params, "opt_state": opt_state})
    ...
    state = mgr.restore(target={"params": params0, "opt_state": opt0})

Orbax writes each shard from the process that owns it (the multi-host
path), supports local paths and ``gs://`` buckets (via tensorstore), and
restores arrays with the shardings of the ``target`` template — the
mechanics the Spark ``Store`` delegates to HDFS clients. The state
plane's own format is deliberately stdlib-only (pickle + crc32 + atomic
renames): restores must work in the narrow window where a re-formed
world has not finished re-initializing its accelerator runtime.
"""

from __future__ import annotations

import json
import os
import pickle
import weakref
import zlib
from typing import Any

from . import conformance as _conformance
from . import metrics as _metrics
from .loopback import context as _lbctx
from .utils import envs
from .utils import faults as _faults
from .utils import invariants as _inv
from .utils import logging as hvd_logging


class Checkpointer:
    """Step-indexed checkpoint directory with retention (the orbax
    ``CheckpointManager`` wrapped in the framework's conventions)."""

    def __init__(self, directory: str, *, max_to_keep: int | None = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory) \
            if "://" not in directory else directory
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep))

    # -- save / restore ----------------------------------------------------

    def save(self, step: int, tree: Any, *, wait: bool = False) -> None:
        """Save a pytree of (possibly sharded) arrays at ``step``. Every
        process must call this (each writes its own shards). Async by
        default; ``wait=True`` blocks until durable."""
        self._mgr.save(int(step),
                       args=self._ocp.args.StandardSave(tree))
        if wait:
            self._mgr.wait_until_finished()

    def restore(self, *, step: int | None = None, target: Any = None) -> Any:
        """Restore the pytree saved at ``step`` (default: latest). With a
        ``target`` template, arrays come back with the template leaves'
        shardings/dtypes — pass your freshly-initialized state so restored
        arrays land directly on the mesh."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self.directory}")
        if target is not None:
            args = self._ocp.args.StandardRestore(target)
        else:
            args = self._ocp.args.StandardRestore()
        return self._mgr.restore(int(step), args=args)

    # -- bookkeeping -------------------------------------------------------

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list:
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        try:
            self._mgr.wait_until_finished()
        finally:
            self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def save(directory: str, step: int, tree: Any) -> None:
    """One-shot save (epoch-end Spark ``Store`` idiom)."""
    with Checkpointer(directory, max_to_keep=None) as mgr:
        mgr.save(step, tree, wait=True)


def restore(directory: str, *, step: int | None = None,
            target: Any = None) -> Any:
    """One-shot restore of ``step`` (default latest)."""
    with Checkpointer(directory) as mgr:
        return mgr.restore(step=step, target=target)


def restore_or_none(directory: str, *, target: Any = None) -> Any | None:
    """Restore the latest checkpoint, or None when the directory has none
    (the resume-if-present idiom). State-plane snapshot manifests are
    preferred when present: the newest step whose manifest *and* every
    shard digest verify wins, so a process killed mid-snapshot (torn
    shards, no manifest) resumes from the previous complete step —
    never a torn tree."""
    plane = sharded_restore_or_none(directory, target=target)
    if plane is not None:
        return plane
    try:
        with Checkpointer(directory) as mgr:
            if mgr.latest_step() is None:
                return None
            return mgr.restore(target=target)
    except FileNotFoundError:
        return None
    except Exception as e:
        hvd_logging.warning("checkpoint restore from %s failed: %s",
                            directory, e)
        return None


# ===========================================================================
# Production state plane: sharded async snapshots + peer-restore
# (docs/checkpoint.md; the elastic warm shelf's state twin)
# ===========================================================================

MANIFEST_SCHEMA = 1

# KV key prefix for peer shard hand-offs when no loopback hub carries
# them (process worlds). Round-scoped keys; the driver GCs the whole
# prefix at every round publication — a new round makes every pending
# transfer stale by definition.
PEER_KEY_PREFIX = "ckpt/peer/"

# Snapshot-thread park slice: short enough that stop()/teardown is
# prompt, long enough not to spin. Virtualized under HVD_SCHED_CHECK.
_WAIT_SLICE_S = 0.2

# How long rank 0's writer waits for the other ranks' shards before
# abandoning a step's manifest (a peer's writer may be wedged or its
# rank dead); an abandoned manifest simply leaves `latest` at the
# previous complete step.
_MANIFEST_WAIT_S = 60.0

# Test seam: when set, the serving side maps a shard payload through
# this hook (fn(tag, payload) -> payload) AFTER its digest is computed —
# the deterministic way to manufacture a digest-mismatched shard and
# exercise the reject/re-pull path.
_corrupt_shard_hook = None


def leaf_range(i: int, n: int, total: int) -> tuple[int, int]:
    """Contiguous ``[lo, hi)`` slice of ``total`` flattened leaves owned
    by participant ``i`` of ``n`` — balanced so the first ``total % n``
    participants take one extra leaf. The single partition function both
    the snapshot writers and the restore re-partitioning use: when the
    world (or survivor set) size changes, ranges are simply recomputed
    over the new ``n``."""
    base, extra = divmod(total, n)
    lo = i * base + min(i, extra)
    return lo, lo + base + (1 if i < extra else 0)


def shard_digest(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def structure_digest(leaves, treedef) -> int:
    """Shape fingerprint of a flattened state tree: the treedef plus
    every leaf's (shape, dtype) — or its type for non-array leaves.
    Content-free on purpose: survivors and a fresh joiner built from the
    same model code agree on structure while disagreeing on values."""
    parts = [repr(treedef)]
    for leaf in leaves:
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            parts.append((tuple(leaf.shape), str(leaf.dtype)))
        else:
            parts.append(("pyobj", type(leaf).__name__))
    return zlib.crc32(repr(parts).encode()) & 0xFFFFFFFF


def tree_nbytes(leaves) -> int:
    """Approximate payload size of a flattened tree (array nbytes; 64 a
    leaf for plain objects) — the broadcast-path restore meter."""
    total = 0
    for leaf in leaves:
        total += int(getattr(leaf, "nbytes", 64))
    return total


def _shard_stem(lo: int, hi: int) -> str:
    return f"shard-{lo}-{hi}"


def manifest_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"manifest-{step}.json")


def latest_path(directory: str) -> str:
    return os.path.join(directory, "latest")


def step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step-{step}")


def _atomic_write(path: str, data: bytes, tag: str) -> None:
    """Temp-file + rename: a reader sees the whole file or no file."""
    tmp = f"{path}.tmp-{tag}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


class StatePlane:
    """One rank's background snapshot writer.

    Created lazily at the first triggering commit (the ``State.commit``
    seam, next to the autoscale observer) and dropped on every re-form
    (``State.on_reset``) — rank numbers and world size are per-round
    facts, so a plane never outlives its round. The writer thread is
    built on the ``utils/invariants.py`` seam, so hvdsched's
    ``ckpt-snapshot`` model explores it racing commits and teardown.

    Hand-off is copy-free: the committed tree's leaves are host numpy
    arrays that ``State.save()`` *replaces* (never mutates) on the next
    commit, so the flattened slice handed to the thread is effectively
    immutable. Latest-wins: a snapshot still pending when the next
    trigger lands is replaced — the plane prefers a fresh restore point
    over a complete history (the durable-history layer is
    :class:`Checkpointer`)."""

    def __init__(self, directory: str, *, rank: int, world: int,
                 interval: int):
        self.directory = directory
        self.rank = rank
        self.world = world
        self.interval = max(1, interval)
        self.last_manifest_step = -1  # rank 0 only
        self._cv = _inv.make_condition("checkpoint.plane.cv")
        self._pending = None  # (step, shard leaves, lo, hi, n_leaves)
        self._stopped = False
        self._thread = None
        self._ctx = _lbctx.current()  # liveness probe for abrupt kills

    # -- trigger (training thread, the commit boundary) --------------------

    def note_commit(self, state) -> None:
        """The ``State.commit()`` seam: on every ``interval``-th commit,
        flatten the just-committed tree, take this rank's leaf range,
        and hand it to the writer. The trigger itself is the lockstep
        decision (every rank triggers at the same commit count with the
        same partition); the write happens off-thread."""
        step = state._commits
        if step % self.interval != 0:
            return
        import jax
        leaves, _treedef = jax.tree_util.tree_flatten(state._saved_state)
        lo, hi = leaf_range(self.rank, self.world, len(leaves))
        _conformance.record("checkpoint.py::StatePlane.note_commit",
                            "snapshot", (step, self.world, len(leaves)))
        with self._cv:
            if self._stopped:
                return
            self._pending = (step, leaves[lo:hi], lo, hi, len(leaves))
            if self._thread is None:
                self._thread = _inv.spawn_thread(
                    self._loop, name=f"hvd-ckpt-snapshot-r{self.rank}")
            self._cv.notify_all()

    def stop(self, *, join: bool = True) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        t, self._thread = self._thread, None
        if t is not None and join:
            _inv.join_thread(t, timeout=5)

    # -- writer thread -----------------------------------------------------

    def _dead(self) -> bool:
        return self._ctx is not None and self._ctx.dead

    def _loop(self) -> None:
        while True:
            with self._cv:
                while self._pending is None and not self._stopped:
                    if self._dead():
                        return
                    self._cv.wait(_WAIT_SLICE_S)
                if self._pending is None:
                    return  # stopped with nothing queued
                job, self._pending = self._pending, None
            try:
                self._write_snapshot(*job)
            except Exception as e:
                # A failed snapshot costs freshness, never the job: the
                # previous manifest stays `latest` and complete.
                hvd_logging.warning(
                    "ckpt: snapshot for step %d failed on rank %d: %s",
                    job[0], self.rank, e)

    def _write_snapshot(self, step: int, leaves, lo: int, hi: int,
                        n_leaves: int) -> None:
        t0 = _inv.monotonic()
        # Chaos seam `ckpt.write` (docs/robustness.md): an injected error
        # here is a rank killed mid-snapshot — shards already renamed
        # stay, the sidecar/manifest never lands, `latest` never moves.
        _faults.inject("ckpt.write", rank=self.rank, step=step)
        payload = pickle.dumps(leaves, protocol=pickle.HIGHEST_PROTOCOL)
        digest = shard_digest(payload)
        sdir = step_dir(self.directory, step)
        os.makedirs(sdir, exist_ok=True)
        stem = os.path.join(sdir, _shard_stem(lo, hi))
        _atomic_write(stem + ".bin", payload, f"r{self.rank}")
        # The sidecar is the shard's commit record: written (atomically)
        # only after the payload rename, so sidecar-present implies
        # shard-complete — the manifest writer polls sidecars only.
        meta = {"lo": lo, "hi": hi, "digest": digest,
                "nbytes": len(payload), "rank": self.rank}
        _atomic_write(stem + ".json", json.dumps(meta).encode(),
                      f"r{self.rank}")
        _metrics.CKPT_SHARDS_WRITTEN.inc()
        if self.rank == 0:
            self._write_manifest(step, n_leaves)
        _metrics.CKPT_SNAPSHOT_SECONDS.observe(_inv.monotonic() - t0)

    def _write_manifest(self, step: int, n_leaves: int) -> None:
        """Seal ``step``: wait for every rank's sidecar, then write the
        manifest and move ``latest`` — both atomic, in that order, so
        ``latest`` can only ever name a step whose manifest (and hence
        every shard) is complete."""
        expected = [leaf_range(r, self.world, n_leaves)
                    for r in range(self.world)]
        expected = [(lo, hi) for lo, hi in expected if hi > lo]
        sdir = step_dir(self.directory, step)
        deadline = _inv.monotonic() + _MANIFEST_WAIT_S
        while True:
            shards = []
            for lo, hi in expected:
                try:
                    with open(os.path.join(
                            sdir, _shard_stem(lo, hi) + ".json"), "rb") as f:
                        shards.append(json.loads(f.read().decode()))
                except (OSError, ValueError):
                    shards = None
                    break
            if shards is not None:
                break
            with self._cv:
                newer = self._pending is not None or self._stopped
            if newer or self._dead() or _inv.monotonic() > deadline:
                hvd_logging.warning(
                    "ckpt: abandoning manifest for step %d (peer shards "
                    "missing; latest stays at %d)", step,
                    self.last_manifest_step)
                return
            _inv.sleep(_WAIT_SLICE_S / 4)
        _faults.inject("ckpt.manifest", rank=self.rank, step=step)
        manifest = {
            "schema": MANIFEST_SCHEMA,
            "step": step,
            "world": self.world,
            "n_leaves": n_leaves,
            "shards": shards,
        }
        _atomic_write(manifest_path(self.directory, step),
                      json.dumps(manifest).encode(), "m")
        _atomic_write(latest_path(self.directory), str(step).encode(), "l")
        self.last_manifest_step = step


# -- per-world plane registry (the State.commit seam) -----------------------

# RankContext -> StatePlane | False; weak keys so a dead round's planes
# are collected with their contexts. `False` caches "state plane off"
# so the per-commit fast path is one dict probe.
_ctx_planes: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_process_plane: "StatePlane | bool | None" = None


def _make_plane() -> "StatePlane | bool":
    d = envs.ckpt_dir()
    if not d:
        return False
    from . import runtime
    if runtime.is_initialized():
        rank, world = runtime.process_rank(), runtime.process_count()
    else:
        rank, world = envs.get_int(envs.RANK, 0), envs.get_int(envs.SIZE, 1)
    return StatePlane(d, rank=rank, world=world,
                      interval=envs.ckpt_interval())


def note_commit(state) -> None:
    """The ``State.commit()`` seam: near-zero when ``HVD_CKPT_DIR`` is
    unset (one registry probe + cached miss)."""
    ctx = _lbctx.current()
    if ctx is None:
        global _process_plane
        plane = _process_plane
        if plane is None:
            plane = _process_plane = _make_plane()
    else:
        plane = _ctx_planes.get(ctx)
        if plane is None:
            plane = _make_plane()
            _ctx_planes[ctx] = plane
    if plane is not False:
        plane.note_commit(state)


def current_plane() -> "StatePlane | None":
    """The calling thread's live plane, if one was created (tests and
    the restore protocol's manifest fingerprint)."""
    ctx = _lbctx.current()
    plane = _process_plane if ctx is None else _ctx_planes.get(ctx)
    return plane if isinstance(plane, StatePlane) else None


def reset_plane() -> None:
    """Stop and drop the calling thread's plane (re-form / teardown /
    tests); the next commit re-reads the knobs under the new round's
    rank and world."""
    global _process_plane
    ctx = _lbctx.current()
    if ctx is None:
        plane, _process_plane = _process_plane, None
    else:
        plane = _ctx_planes.pop(ctx, None)
    if isinstance(plane, StatePlane):
        plane.stop()


# -- on-disk restore (full job restart) -------------------------------------

def sharded_restore_or_none(directory: str, *, target: Any = None,
                            step: int | None = None) -> Any | None:
    """Reassemble a state-plane snapshot from ``directory``: the newest
    step (or ``step``) whose manifest exists and whose every shard
    passes its digest — walking older manifests when the newest is
    incomplete or corrupt. Returns the unflattened tree (using
    ``target``'s structure when given, else the survivors' recorded
    structure cannot be recovered — the caller's template is the
    treedef source) or None."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    steps = []
    for name in names:
        if name.startswith("manifest-") and name.endswith(".json"):
            try:
                steps.append(int(name[len("manifest-"):-len(".json")]))
            except ValueError:
                continue
    for s in sorted(steps, reverse=True):
        if step is not None and s != step:
            continue
        tree = _load_manifest_step(directory, s, target)
        if tree is not None:
            return tree
    return None


def _load_manifest_step(directory: str, step: int, target) -> Any | None:
    try:
        with open(manifest_path(directory, step), "rb") as f:
            manifest = json.loads(f.read().decode())
        leaves: list = [None] * int(manifest["n_leaves"])
        for meta in manifest["shards"]:
            lo, hi = int(meta["lo"]), int(meta["hi"])
            with open(os.path.join(step_dir(directory, step),
                                   _shard_stem(lo, hi) + ".bin"),
                      "rb") as f:
                payload = f.read()
            if shard_digest(payload) != int(meta["digest"]):
                raise ValueError(
                    f"shard [{lo},{hi}) digest mismatch at step {step}")
            part = pickle.loads(payload)
            if len(part) != hi - lo:
                raise ValueError(
                    f"shard [{lo},{hi}) holds {len(part)} leaves")
            leaves[lo:hi] = part
        if any(leaf is None for leaf in leaves):
            raise ValueError(f"step {step} manifest leaves incomplete")
        if target is None:
            return leaves
        import jax
        t_leaves, treedef = jax.tree_util.tree_flatten(target)
        if len(t_leaves) != len(leaves):
            raise ValueError(
                f"target has {len(t_leaves)} leaves, snapshot "
                f"{len(leaves)}")
        return jax.tree_util.tree_unflatten(treedef, leaves)
    except Exception as e:
        hvd_logging.warning(
            "ckpt: snapshot step %d under %s unusable (%s); trying "
            "older manifests", step, directory, e)
        return None


# -- peer-restore protocol (re-form state re-sync) --------------------------

def peer_restore_active() -> bool:
    """Whether re-form state re-sync should run the peer-restore
    protocol instead of the rank-0 broadcast. Purely knob-driven
    (``HVD_CKPT_PEER_RESTORE``, default on): the protocol serves from
    the survivors' live committed trees, so it needs no snapshot
    directory — ``HVD_CKPT_DIR`` only adds the on-disk restart story."""
    return envs.ckpt_peer_restore_enabled()


class RestorePlan:
    """Every rank's identical view of one re-form restore, derived from
    the allgathered fingerprints: who holds the committed step
    (survivors), who needs it (needy), and why the world must degrade
    to the rank-0 broadcast (``degraded_reason``) when it must."""

    __slots__ = ("step", "world", "n_leaves", "survivors", "needy",
                 "fresh", "degraded_reason")

    def __init__(self, step, world, n_leaves, survivors, needy, fresh,
                 degraded_reason):
        self.step = step
        self.world = world
        self.n_leaves = n_leaves
        self.survivors = tuple(survivors)
        self.needy = tuple(needy)
        self.fresh = fresh
        self.degraded_reason = degraded_reason

    def transfers(self, attempt: int, failed=()) -> list:
        """The shard-pull schedule for ``attempt``: ``(needy, owner, k,
        lo, hi)`` rows, sorted. Attempt 0 fans every needy rank over
        every survivor's range; attempt 1 re-pulls only the failed
        ``(needy, k)`` pairs from the NEXT survivor in the ring — the
        bounded failover that turns one bad survivor into a retry, not
        a degraded broadcast. Both sides walk this list in order, and
        the orders nest (survivors serve needy-ascending, needy pull
        range-ascending), so the rendezvous graph is acyclic."""
        k_range = range(len(self.survivors))
        items = ([(d, k) for d in self.needy for k in k_range]
                 if attempt == 0 else sorted(failed))
        out = []
        for d, k in sorted(items):
            owner = self.survivors[(k + attempt) % len(self.survivors)]
            lo, hi = leaf_range(k, len(self.survivors), self.n_leaves)
            if hi > lo:
                out.append((d, owner, k, lo, hi))
        return out


def fingerprint_blob(rank: int, commits: int, leaves, treedef) -> dict:
    plane = current_plane()
    return {
        "rank": rank,
        "commits": int(commits),
        "n_leaves": len(leaves),
        "struct": structure_digest(leaves, treedef),
        "manifest": plane.last_manifest_step if plane else -1,
    }


def make_restore_plan(blobs: list, *, world: int,
                      quorum: int | None = None) -> RestorePlan:
    """Derive the restore plan from every rank's fingerprint. Pure and
    deterministic — each rank computes it independently from the same
    allgathered input, which is what makes the plan itself a lockstep
    conformance event."""
    if quorum is None:
        quorum = envs.ckpt_shard_quorum()
    groups: dict[tuple, list[int]] = {}
    for b in blobs:
        key = (int(b["commits"]), int(b["n_leaves"]), int(b["struct"]))
        groups.setdefault(key, []).append(int(b["rank"]))
    max_commits = max(k[0] for k in groups)
    if max_commits <= 0:
        # Nobody has committed: the initial sync — rank 0's broadcast
        # IS the correct (reference) behavior, not a degraded path.
        return RestorePlan(0, world, 0, (), (), True, None)
    best = [k for k in groups if k[0] == max_commits]
    if len(best) > 1:
        # Equally-committed survivors disagree on state structure: no
        # consistent manifest exists to restore from.
        return RestorePlan(max_commits, world, 0, (), (), False, "quorum")
    key = best[0]
    survivors = sorted(groups[key])
    needy = sorted(r for k, ranks in groups.items() if k != key
                   for r in ranks)
    if len(survivors) < quorum:
        return RestorePlan(key[0], world, key[1], survivors, needy,
                           False, "quorum")
    for k, ranks in groups.items():
        if k != key and (k[1], k[2]) != (key[1], key[2]):
            # A needy rank's tree shape disagrees: its template cannot
            # absorb the survivors' leaves.
            return RestorePlan(key[0], world, key[1], survivors, needy,
                               False, "structure")
    return RestorePlan(key[0], world, key[1], survivors, needy,
                       False, None)


def _transfer_timeout_s() -> float:
    """Shard-pull deadline on the KV fallback channel: comfortably past
    the watchdog budget (a dead owner must surface as the watchdog's
    typed failure first, not as an anonymous pull timeout), floored for
    slow shared CI filesystems."""
    from . import health as _health
    return max(2.0 * _health.watchdog_budget_s(), 20.0)


def _kv_client():
    addr = envs.get(envs.KV_ADDR)
    if not addr:
        return None
    from .runner.http_kv import KVClient
    return KVClient(addr, envs.get_int(envs.KV_PORT, 0),
                    secret=envs.get(envs.SECRET_KEY))


def peer_key(round_id: int, step: int, needy: int, owner: int,
             lo: int, hi: int, attempt: int) -> str:
    return (f"{PEER_KEY_PREFIX}{round_id}/{step}/"
            f"{needy}-{owner}-{lo}-{hi}-{attempt}")


def _serve_shard(tag, envelope, kv, round_id) -> None:
    from .loopback import dispatch as _dispatch
    ch = _dispatch.peer_channel(tag, 0)
    if ch is not None:
        ch.transfer(envelope)
        return
    if kv is None:
        raise RuntimeError("no peer transport (no loopback hub, no KV)")
    kv.put(peer_key(round_id, *tag), pickle.dumps(envelope))


def _pull_shard(tag, kv, round_id) -> tuple:
    """Returns ``(envelope, transport)``."""
    from .loopback import dispatch as _dispatch
    ch = _dispatch.peer_channel(tag, 1)
    if ch is not None:
        return ch.transfer(None), "hub"
    if kv is None:
        raise RuntimeError("no peer transport (no loopback hub, no KV)")
    key = peer_key(round_id, *tag)
    envelope = pickle.loads(kv.wait(key, timeout=_transfer_timeout_s()))
    try:
        kv.delete(key)
    except Exception:  # hvdlint: disable=silent-except
        pass  # best-effort GC: the driver deletes the round prefix anyway
    return envelope, "kv"


def run_peer_transfers(plan: RestorePlan, me: int, leaves, *,
                       allgather, round_id: int = -1):
    """Execute both sides of the shard-pull schedule for this rank.

    Returns ``(new_leaves, reason)``: on success, needy ranks get the
    fully assembled leaf list (survivors get None) and ``reason`` is
    None; on an agreed failure every rank gets ``(None, reason)`` and
    must take the degraded broadcast. All control decisions (who
    failed, what retries, success) come out of ``allgather`` rounds,
    so every rank branches identically."""
    if not plan.needy:
        return None, None
    kv = None
    from .loopback import dispatch as _dispatch
    if _dispatch.peer_channel((plan.step, "probe"), 0) is None:
        try:
            kv = _kv_client()
        except Exception as e:
            hvd_logging.warning("ckpt: KV fallback unavailable: %s", e)
    pulled: dict[int, list] = {}  # k -> leaves
    failed: list = []
    for attempt in (0, 1):
        transfers = plan.transfers(attempt, failed)
        my_failures = []
        for d, owner, k, lo, hi in transfers:
            tag = (plan.step, d, owner, lo, hi, attempt)
            if me == owner:
                try:
                    # Chaos seam `ckpt.shard_pull`: an injected error is
                    # a survivor failing to serve — it travels to the
                    # puller as a typed refusal, which fails over to the
                    # next survivor instead of degrading blind.
                    _faults.inject("ckpt.shard_pull", rank=me,
                                   step=plan.step)
                    payload = pickle.dumps(
                        leaves[lo:hi], protocol=pickle.HIGHEST_PROTOCOL)
                    digest = shard_digest(payload)
                    if _corrupt_shard_hook is not None:
                        payload = _corrupt_shard_hook(tag, payload)
                    envelope = ("ok", digest, payload)
                except _faults.FaultInjected as e:
                    envelope = ("err", str(e))
                try:
                    _serve_shard(tag, envelope, kv, round_id)
                except _RECOVERABLE_TRANSFER_ERRORS as e:
                    hvd_logging.warning(
                        "ckpt: serving shard %s failed: %s", tag, e)
            elif me == d:
                try:
                    envelope, transport = _pull_shard(tag, kv, round_id)
                    part = _verify_shard(envelope, leaves, lo, hi)
                    pulled[k] = part
                    _metrics.CKPT_PEER_SHARDS_PULLED.inc(
                        labels={"transport": transport})
                    _metrics.CKPT_RESTORE_BYTES.inc(
                        len(envelope[2]), labels={
                            "source": "rank0" if owner == 0 else "peer"})
                except _RECOVERABLE_TRANSFER_ERRORS as e:
                    hvd_logging.warning(
                        "ckpt: pull of shard %s failed (%s); will fail "
                        "over", tag, e)
                    my_failures.append((d, k))
        statuses = allgather(("ckpt-status", attempt,
                              sorted(my_failures)))
        failed = sorted({(int(d), int(k)) for s in statuses
                         for d, k in s[2]})
        if not failed:
            break
    if failed:
        return None, "pull-failed"
    if me not in plan.needy:
        return None, None
    new_leaves: list = [None] * plan.n_leaves
    for k, part in pulled.items():
        lo, hi = leaf_range(k, len(plan.survivors), plan.n_leaves)
        new_leaves[lo:hi] = part
    if any(leaf is None for leaf in new_leaves):
        # Cannot happen once every transfer succeeded; belt-and-braces
        # against a plan/partition bug.
        return None, "pull-failed"
    return new_leaves, None


class _ShardRejected(ValueError):
    """A pulled shard failed verification (digest or shape) — recoverable
    by failing over to another survivor."""


_RECOVERABLE_TRANSFER_ERRORS: tuple = ()


def _init_recoverable():
    # Deliberately narrow: PeerFailureError / HostsUpdatedInterrupt are
    # RuntimeError subclasses and MUST propagate (they are the elastic
    # recovery loop's re-form triggers, the real failover for a survivor
    # dying mid-serve), so no broad RuntimeError here — only the typed
    # per-shard failures that the next survivor can absorb.
    global _RECOVERABLE_TRANSFER_ERRORS
    from .loopback.hub import ExchangeTimeout
    _RECOVERABLE_TRANSFER_ERRORS = (
        ExchangeTimeout, TimeoutError, OSError, _ShardRejected,
        pickle.UnpicklingError, ValueError)


_init_recoverable()


def _verify_shard(envelope, template_leaves, lo: int, hi: int) -> list:
    """Digest + structure verification on every pulled shard: the wire
    digest guards the bytes, and each leaf must match this rank's own
    template slice in shape/dtype — a self-consistently lying owner
    cannot smuggle a mis-shaped tree past its puller."""
    if not isinstance(envelope, tuple) or not envelope:
        raise _ShardRejected(f"malformed envelope {type(envelope)}")
    if envelope[0] != "ok":
        raise _ShardRejected(f"owner refused: {envelope[1:]}")
    _okc, digest, payload = envelope
    if shard_digest(payload) != digest:
        raise _ShardRejected("digest mismatch")
    part = pickle.loads(payload)
    if len(part) != hi - lo:
        raise _ShardRejected(
            f"expected {hi - lo} leaves, got {len(part)}")
    for got, want in zip(part, template_leaves[lo:hi]):
        if hasattr(want, "shape") and hasattr(want, "dtype"):
            if (tuple(getattr(got, "shape", ())) != tuple(want.shape)
                    or str(getattr(got, "dtype", "")) != str(want.dtype)):
                raise _ShardRejected(
                    f"leaf shape/dtype mismatch: {getattr(got, 'shape', None)}"
                    f"/{getattr(got, 'dtype', None)} vs "
                    f"{want.shape}/{want.dtype}")
    return part
