"""Hierarchical negotiation control plane + coordinator ResponseCache.

The control-plane twin of ``ops/hierarchical.py``'s ICI-then-DCN data
path (docs/negotiation.md): ranks are partitioned into leader groups
(:mod:`~horovod_tpu.negotiation.layout`), a negotiation round travels
member → leader → cross-leader exchange → fan-down
(:mod:`~horovod_tpu.negotiation.hierarchy`), and a per-service
:class:`~horovod_tpu.negotiation.response_cache.ResponseCache` serves
steady-state rounds locally once the protocol's AND-ed cache bit vector
has proven every rank holds the response — PAPER.md's coordinator
ResponseCache applied at the service seam, so ``negotiate_many_submit``
/ ``_wait`` keep their ticket contract and everything above
(``fusion_cycle``, QoS, step capture) is untouched.
"""

from .layout import GroupLayout
from .response_cache import ResponseCache
from .hierarchy import HierarchicalTransport

__all__ = ["GroupLayout", "ResponseCache", "HierarchicalTransport"]
