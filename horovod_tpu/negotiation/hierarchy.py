"""Two-level negotiation exchange: member → leader → cross-leader → fan-down.

The control-plane twin of ``ops/hierarchical.py``'s ICI-then-DCN data
path. The flat :class:`~horovod_tpu.engine_service.KVTransport` has
every rank put its frame and gather **all** ``world`` frames — the KV
server assembles ``world`` keys for ``world`` gathers every round.
Here one round is:

1. every rank PUTs its frame under its group's scope;
2. each group's **leader** gathers its ≤G member frames (one long-poll),
   packs them — with the server-clock receipt time of each — into one
   group blob, and PUTs it to the cross-leader scope;
3. leaders gather the ``world/G`` group blobs (the one cross-leader
   exchange), merge them into the full rank-ordered table, and PUT it
   as their group's fan-down key;
4. members long-poll their group's single fan-down key.

Per round a member performs O(1) KV ops and the server assembles
O(G) keys per leader group gather plus O(world/G) per cross-leader
gather — O(world/G + G) instead of O(world) per gather. Bytes are
unchanged (every rank still receives every frame: the engine ingests
all ranks); *ops and fan-in* are what shrink, which is exactly what the
single coordinator's ceiling is made of.

The wire **frame** is byte-identical to the flat transport's
(``<u32 len>request_bytes cache_bits``), and the transport exposes the
same surface (``kv``/``world_size``/``rank``/``prefix``/
``last_round_s``/``last_lags``/``exchange``), so ``DynamicService``,
the watchdog wiring, and the straggler tracker run unmodified on
either. Leader/member role branches live HERE, below the collective
submission surface — conditioning a *collective* on leader role is the
rank-divergence hang class hvdlint pass 7 flags.

Leader failure: a dead leader stops beating like any rank; the
watchdog's leader-aggregated beat channel (``health.py``) names it
within the health budget and the coordinated abort fails every parked
exchange. On the next (re-formed) round the layout is re-derived from
the new world, promoting the next surviving rank to leader
(``negotiation/layout.py``).
"""

from __future__ import annotations

import struct
import time

from .layout import GroupLayout
from ..utils import envs
from ..utils import faults as _faults


def _pack_entries(entries: list[tuple[int, float, bytes]]) -> bytes:
    """``[(rank, server_receipt_s, frame)]`` → one blob."""
    out = [struct.pack("<I", len(entries))]
    for rank, receipt, frame in entries:
        out.append(struct.pack("<IdI", rank, receipt, len(frame)))
        out.append(frame)
    return b"".join(out)


def _unpack_entries(blob: bytes) -> list[tuple[int, float, bytes]]:
    (n,) = struct.unpack_from("<I", blob, 0)
    pos = 4
    entries = []
    for _ in range(n):
        rank, receipt, ln = struct.unpack_from("<IdI", blob, pos)
        pos += 16
        entries.append((rank, receipt, blob[pos:pos + ln]))
        pos += ln
    return entries


class HierarchicalTransport:
    """Drop-in replacement for the flat ``KVTransport`` running the
    two-level protocol over the same launcher KV server."""

    def __init__(self, kv_client, world_size: int, rank: int,
                 prefix: str = "engine", group_size: int | None = None):
        self.kv = kv_client
        self.world_size = world_size
        self.rank = rank
        self.prefix = prefix
        self.group_layout = GroupLayout(
            world_size,
            group_size if group_size is not None
            else envs.negotiation_group_size())
        self._gid = self.group_layout.group_of(rank)
        self._leads = self.group_layout.is_leader(rank)
        # same observability surface as KVTransport (read by the
        # service's round-metrics hook and the straggler tracker)
        self.last_round_s = 0.0
        self.last_lags: dict[int, float] = {}

    def exchange(self, cycle: int, req_bytes: bytes, bits: bytes,
                 timeout: float) -> tuple[list[bytes], list[bytes]]:
        """One two-level round; returns the same rank-ordered
        ``(datas, bitvs)`` the flat transport returns."""
        _faults.inject("svc.exchange")
        t0 = time.monotonic()
        frame = struct.pack("<I", len(req_bytes)) + req_bytes + bits
        base = f"{self.prefix}/h/{cycle}"
        gid = self._gid
        self.kv.put(f"{base}/g{gid}/{self.rank}", frame)
        if self._leads:
            members = self.group_layout.members_of(gid)
            got, times = self.kv.gather(f"{base}/g{gid}", len(members),
                                        timeout=timeout, with_times=True)
            entries = []
            for k, v in got.items():
                try:
                    r = int(k.rsplit("/", 1)[1])
                except ValueError:
                    continue
                entries.append((r, times.get(k, 0.0), v))
            entries.sort()
            self.kv.put(f"{base}/x/{gid}", _pack_entries(entries))
            xs = self.kv.gather(f"{base}/x", self.group_layout.n_groups,
                                timeout=timeout)
            merged: list[tuple[int, float, bytes]] = []
            for blob in xs.values():
                merged.extend(_unpack_entries(blob))
            merged.sort()
            combined = _pack_entries(merged)
            self.kv.put(f"{base}/r{gid}/all", combined)
        else:
            got = self.kv.gather(f"{base}/r{gid}", 1, timeout=timeout)
            merged = _unpack_entries(next(iter(got.values())))
        self.last_round_s = time.monotonic() - t0
        datas: list = [b""] * self.world_size
        bitvs: list = [b""] * self.world_size
        receipt: dict[int, float] = {}
        for r, t, fr in merged:
            if not 0 <= r < self.world_size:
                continue
            (ln,) = struct.unpack_from("<I", fr, 0)
            datas[r] = fr[4:4 + ln]
            bitvs[r] = fr[4 + ln:]
            receipt[r] = t
        first = min(receipt.values()) if receipt else 0.0
        self.last_lags = {r: t - first for r, t in sorted(receipt.items())}
        # Same memory bound as the flat transport: everyone read cycle-c
        # data before anyone writes cycle c+2, so cycle c-1's keys are
        # dead — each rank clears its own, leaders also their two
        # aggregate keys.
        if cycle > 0:
            prev = f"{self.prefix}/h/{cycle - 1}"
            stale = [f"{prev}/g{gid}/{self.rank}"]
            if self._leads:
                stale += [f"{prev}/x/{gid}", f"{prev}/r{gid}/all"]
            for key in stale:
                try:
                    self.kv.delete(key)
                except Exception:  # hvdlint: disable=silent-except
                    pass  # best-effort memory bound; keys are round-scoped
        return datas, bitvs
