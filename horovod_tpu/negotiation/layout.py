"""Static leader-group layout for the hierarchical control plane.

A pure function of ``(world_size, group_size)`` — no knob reads, no
runtime state — so every rank derives the identical layout and the
hvdlint rank-divergence rule can treat layout *shape* queries
(``n_groups``, ``leaders()``, ``members_of``) as rank-symmetric. The
self-role predicate (:meth:`GroupLayout.is_leader`) is rank-LOCAL: a
collective submission conditioned on it is the mismatched-collective
hang class and is flagged by hvdlint pass 7 (leader-role taint).

Group ``g`` covers ranks ``[g*G, min((g+1)*G, world))``; its leader is
the group's smallest rank. ``G ∤ world`` simply leaves the last group
short — a one-member group is its own leader with no member traffic.
After an elastic re-form the layout is recomputed from the new world
size, so a dead leader's group is re-led by the next surviving rank on
the very next round (member promotion = re-derivation, never a
stateful election).
"""

from __future__ import annotations


class GroupLayout:
    """Partition of ``world_size`` transport-local ranks into leader
    groups of at most ``group_size``."""

    __slots__ = ("world_size", "group_size", "n_groups")

    def __init__(self, world_size: int, group_size: int):
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        if group_size < 1:
            raise ValueError(f"group_size must be >= 1, got {group_size}")
        self.world_size = int(world_size)
        self.group_size = int(group_size)
        self.n_groups = -(-self.world_size // self.group_size)  # ceil

    def group_of(self, rank: int) -> int:
        self._check(rank)
        return rank // self.group_size

    def leader_of(self, group: int) -> int:
        if not 0 <= group < self.n_groups:
            raise ValueError(f"group {group} out of range "
                             f"[0, {self.n_groups})")
        return group * self.group_size

    def leaders(self) -> list[int]:
        return [g * self.group_size for g in range(self.n_groups)]

    def members_of(self, group: int) -> range:
        """Every rank of ``group`` (leader included), ascending."""
        if not 0 <= group < self.n_groups:
            raise ValueError(f"group {group} out of range "
                             f"[0, {self.n_groups})")
        lo = group * self.group_size
        return range(lo, min(lo + self.group_size, self.world_size))

    def is_leader(self, rank: int) -> bool:
        """Whether ``rank`` leads its group — a rank-LOCAL role; never
        condition a collective submission on it (hvdlint pass 7)."""
        self._check(rank)
        return rank % self.group_size == 0

    def _check(self, rank: int) -> None:
        if not 0 <= rank < self.world_size:
            raise ValueError(
                f"rank {rank} out of range [0, {self.world_size})")

    def __repr__(self):
        return (f"GroupLayout(world={self.world_size}, "
                f"G={self.group_size}, groups={self.n_groups})")
