"""Coordinator ResponseCache: steady-state negotiation served locally.

PAPER.md's ResponseCache design applied at the *service* seam: once a
tensor's negotiation response is cached on every rank — a fact the
protocol itself proves — later submissions of the identical request are
answered from the local cache with **zero** KV rounds, so steady-state
negotiation cost is independent of world size (docs/negotiation.md).

**Coherence rule.** An entry is only *confirmed* (serveable) after a
real round returned its response with ``from_cache=True``. That flag is
produced by the native engines' AND-ed cache **bit vector**
(``commit_cache_bits(and_bitvectors(...))``): it is set iff *every*
rank's native cache held the entry that cycle, and the symmetric
protocol delivers it at the same negotiation index on every rank — so
all ranks flip from "negotiate" to "serve locally" deterministically at
the same occurrence, keeping downstream pairing (loopback hub
occurrence counters, cross-process program issue order) aligned with no
extra wire traffic. A rank whose bit diverges (capacity eviction,
metadata drift) drops the AND, the response comes back
``from_cache=False``, and the entry stays unconfirmed — bit-vector
divergence forces re-negotiation by construction.

**Invalidation.** Serving additionally requires the native cache to
still hold the name (``NativeEngine.cache_has``): native invalidation
is driven by the globally-ingested request stream, so every rank stops
serving on the same cycle a peer's changed-metadata request lands.
Whole-cache invalidation on knob-override epoch bumps, service
reset/stop (process-set change, elastic re-form — a re-formed world
builds fresh services and therefore fresh caches), and coordinated
abort. While any rank is JOINed (``NativeEngine.join_pending``) the
service bypasses the cache entirely: the joined rank only learns about
scheduled collectives — for its zero executions — from real rounds.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from .. import conformance as _conformance
from .. import metrics as _metrics
from ..dynamic import (
    REQ_ALLGATHER,
    REQ_BARRIER,
    REQ_JOIN,
    Response,
)


def cacheable(req: dict) -> bool:
    """Whether a request is eligible for response caching — mirrors the
    native cache's own rules (``cache_bits``): allgathers carry per-rank
    first dims no rank can vouch for alone, uneven alltoalls have
    call-specific recv splits, barriers/joins are never cached."""
    t = req.get("request_type")
    if t in (REQ_ALLGATHER, REQ_BARRIER, REQ_JOIN):
        return False
    if tuple(req.get("splits") or ()):
        return False
    return True


def signature(req: dict) -> tuple:
    """Full request identity: a cached response may only answer a
    request that matches in every negotiated dimension (the native
    cache compares the same params and calls a drift INVALID)."""
    return (
        req["name"],
        req.get("request_type"),
        req.get("dtype", 0),
        req.get("element_size", 4),
        tuple(req.get("shape", ())),
        req.get("root_rank", -1),
        req.get("group_id", -1),
        req.get("reduce_op", -1),
        float(req.get("prescale", 1.0)),
        float(req.get("postscale", 1.0)),
        req.get("splits_crc", 0),
    )


class _Entry:
    __slots__ = ("response", "confirmed", "warm")

    def __init__(self, response: Response, confirmed: bool,
                 warm: bool = False):
        self.response = response
        self.confirmed = confirmed
        # Restored across an elastic re-form to the same process-set
        # shape (docs/elastic.md): unconfirmed until the one-time warm
        # digest round proves every member restored identical content.
        self.warm = warm


# --------------------------------------------------------------------------
# Elastic warm re-form shelf (docs/elastic.md): a gracefully stopping
# service shelves its entries keyed by (world scope, process set, world
# size, rank); the same-shape successor restores them WARM — unconfirmed
# until the coordinator's one-time warm-digest exchange (engine_service)
# proves every member restored byte-identical content, at which point
# one real round re-arms local serving (vs. two cold: populate+confirm).
# --------------------------------------------------------------------------

# Shelf keys are PER RANK — one world-W churn cycle keeps ~3W keys
# live at once (W at the old size, W-1 at the new, W re-shelved while
# the next round's takes are still draining). The floor covers small
# worlds; past it the cap scales with the largest world currently
# shelved, so a world-16 cycle cannot LRU-evict its own shapes
# mid-cycle (the exact failure the ISSUE-15 world=16 run surfaced:
# most ranks' digests went empty and vetoed the warm re-arm for all).
_SHELF_SHAPES = 16
_shelf_mu = threading.Lock()
_shelf: "OrderedDict[tuple, list]" = OrderedDict()


def _shelf_cap() -> int:
    """Caller holds ``_shelf_mu``. Key layout: (scope, pset, world,
    rank) — index 2 is the world size."""
    worlds = [k[2] for k in _shelf
              if len(k) > 2 and isinstance(k[2], int)]
    return max(_SHELF_SHAPES, 4 * max(worlds, default=0))


def shelve(shape: tuple, items: list) -> None:
    """Park a stopping service's entries under its shape key."""
    if not items:
        return
    with _shelf_mu:
        _shelf[shape] = items
        _shelf.move_to_end(shape)
        cap = _shelf_cap()
        while len(_shelf) > cap:
            _shelf.popitem(last=False)


def take_shelved(shape: tuple) -> list | None:
    with _shelf_mu:
        return _shelf.pop(shape, None)


def clear_shelf() -> None:
    with _shelf_mu:
        _shelf.clear()


class ResponseCache:
    """One negotiation service's response cache (LRU, ``capacity``
    entries — the ``HVD_RESPONSE_CACHE`` knob). Thread-safe: submit
    paths look up under ``_mu`` while the wait path inserts."""

    def __init__(self, capacity: int, pset_key: str = "global"):
        self.capacity = int(capacity)
        self._pset = pset_key
        self._mu = threading.Lock()
        self._entries: "OrderedDict[str, tuple[tuple, _Entry]]" = \
            OrderedDict()  # name -> (signature, entry)
        self._hits = 0
        self._misses = 0
        self._served_batches = 0
        self._invalidations = 0
        label = {"process_set": pset_key}
        self._m_hits = _metrics.RESPONSE_CACHE_HITS.bind(label)
        self._m_misses = _metrics.RESPONSE_CACHE_MISSES.bind(label)

    # -- lookup ------------------------------------------------------------

    def lookup_confirmed(self, req: dict) -> Response | None:
        """The cached response for ``req`` when its entry is confirmed
        globally coherent AND matches the full signature; else None.
        Does not count hit/miss — the service counts per *decision*
        (a batch is served all-or-nothing)."""
        if self.capacity <= 0 or not cacheable(req):
            return None
        sig = signature(req)
        with self._mu:
            held = self._entries.get(req["name"])
            if held is None:
                return None
            held_sig, entry = held
            if held_sig != sig or not entry.confirmed:
                return None
            self._entries.move_to_end(req["name"])
            return entry.response

    # -- population --------------------------------------------------------

    def note_response(self, req: dict, resp: Response) -> None:
        """Record a delivered negotiation response. ``from_cache=True``
        responses confirm the entry (the AND-ed bit vector proved every
        rank holds it — see the module docstring); fresh responses
        insert/update unconfirmed."""
        if self.capacity <= 0 or not cacheable(req) or resp.is_error:
            return
        if len(resp.tensor_names) != 1:
            return  # fused multi-tensor responses are not per-name reusable
        sig = signature(req)
        with self._mu:
            held = self._entries.get(req["name"])
            if held is not None and held[0] == sig:
                flipped = resp.from_cache and not held[1].confirmed
                held[1].confirmed = held[1].confirmed or resp.from_cache
                held[1].response = resp
                self._entries.move_to_end(req["name"])
            else:
                flipped = resp.from_cache
                self._entries[req["name"]] = (
                    sig, _Entry(resp, resp.from_cache))
                self._entries.move_to_end(req["name"])
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
            if flipped:
                # Lockstep decision point (docs/conformance.md): the
                # AND-ed bit vector flips every rank from "negotiate"
                # to "serve" at the same negotiation index — a rank
                # confirming a different name (or at a different point
                # in the stream) IS the divergence hvdtrace localizes.
                _conformance.record(
                    "negotiation/response_cache.py::"
                    "ResponseCache.note_response",
                    "confirm", (self._pset, req["name"]))

    # -- accounting (service-side decisions) -------------------------------

    def count_served(self, n: int) -> None:
        with self._mu:
            self._hits += n
            self._served_batches += 1
            # Lockstep decision point (docs/conformance.md): serve
            # decisions are all-or-nothing per batch and must flip at
            # the same serve index on every rank.
            _conformance.record(
                "negotiation/response_cache.py::"
                "ResponseCache.count_served",
                "served", (self._pset, n, self._served_batches))
        self._m_hits.inc(n)

    def count_missed(self, n: int) -> None:
        if n <= 0:
            return
        with self._mu:
            self._misses += n
        self._m_misses.inc(n)

    # -- elastic warm re-form ----------------------------------------------

    def export_entries(self) -> list:
        """Shelvable snapshot: (name, signature, response) for every
        confirmed-or-warm entry, in insertion order (the digest is
        order-insensitive; sorted on computation)."""
        with self._mu:
            return [(name, held[0], held[1].response)
                    for name, held in self._entries.items()
                    if held[1].confirmed or held[1].warm]

    def restore_warm(self, items: list) -> int:
        """Adopt a shelved snapshot as WARM entries: present but
        unserveable until :meth:`confirm_warm` (the digest round proved
        world-wide agreement) — a lone rank restoring entries its peers
        lost must never serve locally while they negotiate."""
        n = 0
        with self._mu:
            for name, sig, resp in items:
                if len(self._entries) >= self.capacity:
                    break
                self._entries[name] = (sig, _Entry(resp, False, warm=True))
                n += 1
        # Local event (docs/conformance.md): restore counts are
        # legitimately rank-asymmetric (a fresh member has no shelf) —
        # FSM-ordered per rank, never chained cross-rank.
        _conformance.record(
            "negotiation/response_cache.py::ResponseCache.restore_warm",
            "warm_restore", (self._pset, n))
        return n

    def warm_count(self) -> int:
        with self._mu:
            return sum(1 for _, e in self._entries.values() if e.warm)

    def warm_digest(self) -> bytes:
        """Content digest of the warm set (8 bytes): equal digests on
        every member mean every member restored identical entries. An
        empty warm set digests to the distinct empty marker so a fresh
        replacement rank (no shelf) forces the cold path everywhere."""
        import zlib
        with self._mu:
            items = sorted(
                (name, held[0], repr(held[1].response))
                for name, held in self._entries.items() if held[1].warm)
        if not items:
            return b"\x00" * 8
        crc = 0
        for name, sig, resp_repr in items:
            crc = zlib.crc32(repr((name, sig, resp_repr)).encode(), crc)
        return len(items).to_bytes(4, "little") + crc.to_bytes(4, "little")

    def confirm_warm(self) -> int:
        """Every member proved it restored the identical warm set: flip
        warm entries to confirmed. Serving still additionally requires
        the NATIVE cache to hold each name (one real round per name),
        so a warm re-form re-arms after one confirmation round."""
        n = 0
        with self._mu:
            for _, e in self._entries.values():
                if e.warm:
                    e.warm = False
                    e.confirmed = True
                    n += 1
        # Local event: FSM rule — a confirm requires a preceding
        # restore in this rank's trace (docs/conformance.md).
        _conformance.record(
            "negotiation/response_cache.py::ResponseCache.confirm_warm",
            "warm_confirm", (self._pset, n))
        return n

    def drop_warm(self) -> int:
        """Digest disagreement (a fresh member, divergent shelves) or
        the digest round failed: fall back to the cold two-round path."""
        with self._mu:
            stale = [name for name, held in self._entries.items()
                     if held[1].warm]
            for name in stale:
                del self._entries[name]
        # Local event: the cold-path fallback decision (veto or digest
        # failure) — FSM-ordered per rank (docs/conformance.md).
        _conformance.record(
            "negotiation/response_cache.py::ResponseCache.drop_warm",
            "warm_drop", (self._pset, len(stale)))
        return len(stale)

    # -- invalidation ------------------------------------------------------

    def invalidate(self, reason: str = "") -> int:
        """Drop everything (knob-override epoch, service reset/stop,
        coordinated abort). Returns the number of entries dropped."""
        with self._mu:
            n = len(self._entries)
            self._entries.clear()
            if n:
                self._invalidations += 1
        return n

    def drop_name(self, name: str) -> None:
        with self._mu:
            self._entries.pop(name, None)

    # -- introspection -----------------------------------------------------

    def __len__(self) -> int:
        with self._mu:
            return len(self._entries)

    def confirmed_count(self) -> int:
        with self._mu:
            return sum(1 for _, e in self._entries.values() if e.confirmed)

    def stats(self) -> dict:
        with self._mu:
            total = self._hits + self._misses
            return {
                "capacity": self.capacity,
                "entries": len(self._entries),
                "confirmed": sum(1 for _, e in self._entries.values()
                                 if e.confirmed),
                "warm": sum(1 for _, e in self._entries.values() if e.warm),
                "hits": self._hits,
                "misses": self._misses,
                "served_batches": self._served_batches,
                "invalidations": self._invalidations,
                "hit_rate": (self._hits / total) if total else 0.0,
            }
