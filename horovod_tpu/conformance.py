"""Cross-rank lockstep conformance recorder (``HVD_CONFORMANCE``).

Every subsystem grown since PR 2 rests on one contract: all ranks make
byte-identical **rank-deterministic decisions** — fusion flush
composition, QoS grant order, step-capture seal keys and phase moves,
response-cache confirm/serve flips, dispatch/gspmd plan-key builds.
One divergent decision presents as a 600 s exchange-deadline hang with
no localization (the reference's stall inspector names *missing*
tensors, never *why* ranks diverged). This module is the runtime half
of the instrument that proves the contract mechanically: a per-rank
recorder hooks every decision point, content-hashes each event into
chained crc digests, and dumps per-rank trace files that
``python -m tools.hvdtrace`` (the offline half) cross-diffs down to the
FIRST divergent event.

**Event classes.** Not every decision is cross-rank comparable:

* ``lockstep`` events fold into per-stream digest chains — the claim is
  "every rank's chain for this stream is identical". Flush composition,
  QoS grants, capture seal/phase, response-cache confirm/serve, and
  knob-override epoch moves are lockstep.
* ``local`` events are recorded (and FSM-validated offline) but **not**
  chained: plan-key builds and warm-reform shelve/graft decisions are
  legitimately rank-asymmetric (a fresh replacement rank builds cold
  while survivors graft warm), as are service lifecycle and join
  events.

**Streams, not one chain.** Decisions from different subsystems are
made under different locks on different threads (the cycle thread
confirms cache entries while a producer thread drains a flush), so
their *interleaving* is timing, not contract. Each subsystem therefore
chains into its own stream (``flush``/``qos``/``capture``/``rcache``/
``epoch``); within a stream the owning lock totally orders events and
the order IS rank-deterministic.

**Cost contract.** With ``HVD_CONFORMANCE`` unset, :func:`record` is
one cached module-bool read and an early return (the ``utils/faults.py``
fast-path idiom); ``bench.py --conformance-bench`` gates the enabled
recorder at <= 3% on the pipelined allreduce stream. The record path is
timer-purity legal: content hashing is ``zlib.crc32`` over ``repr``
(the ``faults.py`` deterministic-draw idiom) — no wall clock, no
randomness, no set iteration.

**Coverage contract.** :data:`SITES` below is the registry of decision
points; hvdlint pass 9 (``trace-coverage``) checks both directions —
every registered site contains a ``conformance.record(...)`` call, no
``record()`` call sits outside a registered site, and the registry
round-trips against docs/conformance.md like the knob registry does
against docs/knobs.md.

Deliberately light on imports (stdlib + envs + the loopback context
seam) and deliberately on **plain** ``threading.Lock`` like metrics.py:
the recorder lock is a leaf — nothing is acquired under it and it never
blocks — so routing it through the invariants seam would only multiply
hvdsched's schedule space without adding an explorable conflict.
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import weakref
import zlib

from .loopback import context as _lbctx
from .utils import envs

__all__ = [
    "LOCKSTEP", "LOCAL", "SITES", "STREAMS", "TRACE_SCHEMA",
    "Recorder", "record", "enabled", "refresh", "set_enabled",
    "conformance_dump", "conformance_stats", "maybe_dump", "reset",
]

TRACE_SCHEMA = 1

LOCKSTEP = "lockstep"
LOCAL = "local"

# ---------------------------------------------------------------------------
# decision-point registry (hvdlint pass 9 round-trips this against
# docs/conformance.md and against the call sites themselves)
# ---------------------------------------------------------------------------

# site ("<module>.py::<qualname>") -> (stream, event class). The site key
# format matches hvdlint's function index (paths relative to the
# horovod_tpu package root).
SITES = {
    # fusion flush composition order — THE founding lockstep decision
    "ops/fusion_cycle.py::FusionScheduler.flush_queue":
        ("flush", LOCKSTEP),
    # QoS grant history: the deterministic multi-tenant arbiter's output
    "qos.py::QosGate._grant_locked": ("qos", LOCKSTEP),
    # step-capture phase transitions + seal keys + replay completion
    "ops/step_capture.py::CaptureState.boundary": ("capture", LOCKSTEP),
    "ops/step_capture.py::CaptureState._seal_locked":
        ("capture", LOCKSTEP),
    "ops/step_capture.py::CaptureState._diverge_locked":
        ("capture", LOCKSTEP),
    "ops/step_capture.py::CaptureState._execute_replay":
        ("capture", LOCKSTEP),
    # response-cache confirm flips + serve decisions at negotiation index
    "negotiation/response_cache.py::ResponseCache.note_response":
        ("rcache", LOCKSTEP),
    "negotiation/response_cache.py::ResponseCache.count_served":
        ("rcache", LOCKSTEP),
    # warm re-form machinery: legitimately rank-asymmetric -> local
    "negotiation/response_cache.py::ResponseCache.restore_warm":
        ("rcache", LOCAL),
    "negotiation/response_cache.py::ResponseCache.confirm_warm":
        ("rcache", LOCAL),
    "negotiation/response_cache.py::ResponseCache.drop_warm":
        ("rcache", LOCAL),
    # dispatch/gspmd plan-key builds + warm shelve/graft decisions
    "ops/dispatch_cache.py::store": ("plans", LOCAL),
    "ops/dispatch_cache.py::shelve_for_reform": ("plans", LOCAL),
    "ops/dispatch_cache.py::restore_for_reform": ("plans", LOCAL),
    "ops/dispatch_cache.py::_warm_graft_locked": ("plans", LOCAL),
    # negotiation-service lifecycle + join latch (FSM-validated)
    "engine_service.py::DynamicService.__init__": ("service", LOCAL),
    "engine_service.py::DynamicService.stop": ("service", LOCAL),
    "engine_service.py::DynamicService._on_peer_failure":
        ("service", LOCAL),
    "engine_service.py::DynamicService.join": ("service", LOCAL),
    # checkpoint state plane: snapshot triggers fire at the commit
    # boundary on the training thread (the async writer only copies),
    # and the re-form restore protocol's agree/source decisions are
    # collective outputs — all three are lockstep by construction
    "checkpoint.py::StatePlane.note_commit": ("ckpt", LOCKSTEP),
    "elastic/state.py::JaxState.sync": ("ckpt", LOCKSTEP),
    "elastic/state.py::JaxState._peer_restore": ("ckpt", LOCKSTEP),
}

# The internal stream the recorder feeds itself: knob-override epoch
# moves (autotune) are lockstep context every divergence report quotes.
_EPOCH_STREAM = "epoch"

STREAMS = ("flush", "qos", "capture", "rcache", "plans", "service",
           "ckpt", _EPOCH_STREAM)

_STREAM_OF = {site: stream for site, (stream, _cls) in SITES.items()}
_CLASS_OF = {site: cls for site, (_stream, cls) in SITES.items()}


# ---------------------------------------------------------------------------
# enable gate (cached; near-zero when off)
# ---------------------------------------------------------------------------

_force_enabled: bool | None = None  # tests/bench override; None = knob


def _read_enabled() -> bool:
    if _force_enabled is not None:
        return _force_enabled
    return envs.conformance_enabled()


_enabled = _read_enabled()


def enabled() -> bool:
    """Whether decision-point hooks record (``HVD_CONFORMANCE``,
    default off)."""
    return _enabled


def refresh() -> None:
    """Re-read ``HVD_CONFORMANCE`` (tests toggle it after import)."""
    global _enabled
    _enabled = _read_enabled()


def set_enabled(value: bool | None) -> None:
    """Force the gate on/off (``None`` restores the knob) — the bench's
    interleaved on/off passes and tests use this; production uses the
    knob."""
    global _force_enabled
    _force_enabled = value
    refresh()


# ---------------------------------------------------------------------------
# per-rank recorders
# ---------------------------------------------------------------------------


def _crc(prev: int, *parts) -> int:
    """Chain one event into a crc digest — deterministic, wall-clock
    free, and cheap enough for the flush drain's critical section (the
    ``faults.py`` draw idiom keeps this legal in timer-reachable
    code)."""
    return zlib.crc32(repr(parts).encode(), prev) & 0xFFFFFFFF


class Recorder:
    """One rank's (or the process's) conformance event log: per-stream
    digest chains, the compact per-event index, and the bounded
    full-payload ring."""

    __slots__ = ("header", "chains", "events", "ring", "seq",
                 "dump_count", "_epoch", "_mu")

    def __init__(self):
        ctx = _lbctx.current()
        label = _lbctx.current_rank_label() or "proc"
        self.header = {
            "schema": TRACE_SCHEMA,
            "label": label,
            "rank": envs.get_int(envs.RANK, -1),
            "size": envs.get_int(envs.SIZE, -1),
            # the rendezvous coordinates group traces into comparable
            # worlds: loopback seeds the world NAME and the round index
            # here (LoopbackWorld.rank_env), processes their launcher's
            "world": envs.get(envs.COORDINATOR_ADDR, "") or "",
            "round": envs.get(envs.COORDINATOR_PORT, "") or "",
            "elastic_round": envs.get(envs.ELASTIC_ROUND, "") or "",
            "generation": getattr(ctx, "generation", 0) if ctx else 0,
        }
        self.chains = {s: 0 for s in STREAMS}
        # compact, unbounded: [seq, stream, cls, site, kind, crc] — crc
        # is the stream chain AFTER the event (lockstep) or the event's
        # own content crc (local); the chain localizes, the ring quotes
        self.events: list[list] = []
        self.ring = collections.deque(maxlen=envs.conformance_ring())
        self.seq = 0
        self.dump_count = 0
        self._epoch = envs.override_epoch()
        self._mu = threading.Lock()

    # -- recording ---------------------------------------------------------

    def note(self, site: str, kind: str, payload) -> None:
        stream = _STREAM_OF.get(site)
        if stream is None:
            # an unregistered call site is a schema bug pass 9 catches
            # statically; at runtime keep the event rather than lose it
            stream, cls = "service", LOCAL
        else:
            cls = _CLASS_OF[site]
        with self._mu:
            epoch = envs.override_epoch()
            if epoch != self._epoch:
                self._note_locked(
                    _EPOCH_STREAM, LOCKSTEP,
                    "conformance.py::Recorder.note", "epoch",
                    (self._epoch, epoch))
                self._epoch = epoch
            self._note_locked(stream, cls, site, kind, payload)

    def _note_locked(self, stream: str, cls: str, site: str, kind: str,
                     payload) -> None:
        seq = self.seq
        self.seq = seq + 1
        if cls == LOCKSTEP:
            crc = _crc(self.chains[stream], kind, payload)
            self.chains[stream] = crc
        else:
            crc = _crc(0, kind, payload)
        self.events.append([seq, stream, cls, site, kind, crc])
        if self.ring.maxlen:
            self.ring.append([seq, site, kind, repr(payload)])

    # -- export ------------------------------------------------------------

    def trace(self) -> dict:
        """The JSON-shaped trace document ``tools/hvdtrace`` consumes."""
        with self._mu:
            return {
                **self.header,
                "chains": dict(self.chains),
                "events": [list(e) for e in self.events],
                "ring": [list(r) for r in self.ring],
                "n_events": self.seq,
            }

    def stats(self) -> dict:
        with self._mu:
            per_stream: dict[str, int] = {s: 0 for s in STREAMS}
            for _seq, stream, _cls, _site, _kind, _crc in self.events:
                per_stream[stream] = per_stream.get(stream, 0) + 1
            return {
                "enabled": _enabled,
                "label": self.header["label"],
                "events": self.seq,
                "by_stream": per_stream,
                "chains": dict(self.chains),
                "ring": len(self.ring),
            }


_process_recorder: Recorder | None = None
# RankContext -> Recorder; weak keys so a dead loopback world's log is
# collected with it (RankContext carries __weakref__ for exactly this).
_ctx_recorders: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_reg_mu = threading.Lock()


def _recorder(ctx=None) -> Recorder:
    if ctx is None:
        ctx = _lbctx.current()
    if ctx is None:
        global _process_recorder
        with _reg_mu:
            if _process_recorder is None:
                _process_recorder = Recorder()
            return _process_recorder
    with _reg_mu:
        rec = _ctx_recorders.get(ctx)
        if rec is None:
            with _lbctx.activate(ctx):
                rec = Recorder()
            _ctx_recorders[ctx] = rec
        return rec


def _peek_recorder(ctx=None) -> Recorder | None:
    if ctx is None:
        ctx = _lbctx.current()
    with _reg_mu:
        return _process_recorder if ctx is None else _ctx_recorders.get(ctx)


def record(site: str, kind: str, payload) -> None:
    """Record one decision event at a registered ``site``. Near-zero
    when off: one cached module-bool read and an early return. Safe
    from timer-reachable code (no wall clock, no randomness)."""
    if not _enabled:
        return
    _recorder().note(site, kind, payload)


# ---------------------------------------------------------------------------
# dumping
# ---------------------------------------------------------------------------


def _trace_filename(header: dict, dump_count: int) -> str:
    raw = "hvdtrace-{}-r{}-g{}-{}".format(
        header.get("world") or "world", header.get("round") or "0",
        header.get("generation") or 0, header.get("label") or "proc")
    if dump_count:
        raw += f"-d{dump_count}"
    return re.sub(r"[^A-Za-z0-9._-]+", "_", raw) + ".json"


def conformance_dump(path: str | None = None) -> dict:
    """Snapshot the calling thread's (rank's) conformance trace. Writes
    it to ``path`` when given, else to ``HVD_CONFORMANCE_DIR`` when that
    knob is set; always returns the trace document (``hvd.
    conformance_dump()`` — the on-demand twin of the shutdown dump)."""
    rec = _recorder()
    doc = rec.trace()
    target = path
    if target is None:
        d = envs.conformance_dir()
        if d:
            target = os.path.join(d, _trace_filename(doc, rec.dump_count))
    if target is not None:
        os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
        with open(target, "w") as f:
            json.dump(doc, f)
        doc["path"] = target
    return doc


def maybe_dump(reason: str, ctx=None) -> str | None:
    """Shutdown/abort-path dump: when the recorder is enabled AND
    ``HVD_CONFORMANCE_DIR`` names a directory, write this world's trace
    file and return its path (else None). ``ctx`` lets the loopback
    supervisor dump a dead rank's trace from another thread. Never
    raises — a failed trace write must not mask the teardown (or abort)
    it rides on."""
    if not _enabled:
        return None
    rec = _peek_recorder(ctx)
    if rec is None or rec.seq == 0:
        return None
    try:
        with _lbctx.activate(ctx) if ctx is not None else _noop():
            d = envs.conformance_dir()
            if not d:
                return None
            doc = rec.trace()
            doc["dump_reason"] = reason
            target = os.path.join(
                d, _trace_filename(doc, rec.dump_count))
            rec.dump_count += 1
            os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
            with open(target, "w") as f:
                json.dump(doc, f)
            return target
    except Exception:  # pragma: no cover - diagnostic path
        from .utils import logging as hvd_logging
        hvd_logging.exception("conformance trace dump failed (%s)", reason)
        return None


class _noop:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


def conformance_stats() -> dict:
    """Recorder counters for the calling thread's world (tests; the
    ``hvd.response_cache_stats()``-style observability twin)."""
    rec = _peek_recorder()
    if rec is None:
        return {"enabled": _enabled, "events": 0, "by_stream": {},
                "chains": {}, "ring": 0, "label": ""}
    return rec.stats()


def reset() -> None:
    """Drop the calling thread's recorder (process teardown / tests) —
    the next event starts a fresh trace incarnation."""
    global _process_recorder
    ctx = _lbctx.current()
    with _reg_mu:
        if ctx is None:
            _process_recorder = None
        else:
            _ctx_recorders.pop(ctx, None)
