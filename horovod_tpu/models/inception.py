"""Inception V3 in flax, TPU-first.

The third of the reference's published scaling-efficiency models
(``/root/reference/docs/benchmarks.rst:13-14``: Inception V3 at 90% on
512 GPUs). Architecture per Szegedy et al. 2015 ("Rethinking the
Inception Architecture", the V3 configuration): factorized 7x7 branches,
grid reductions, 299x299 native input (any HxW >= 75 works — global
pooling at the head). NHWC, bfloat16 compute, float32
parameters/batch-norm.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class ConvBN(nn.Module):
    features: int
    kernel: tuple = (3, 3)
    strides: tuple = (1, 1)
    padding: str | tuple = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype,
                         param_dtype=jnp.float32)(x)
        return nn.relu(x)


def _pool_avg(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(64, (1, 1))(x, train)
        b2 = c(64, (5, 5))(c(48, (1, 1))(x, train), train)
        b3 = c(96, (3, 3))(c(96, (3, 3))(c(64, (1, 1))(x, train), train),
                           train)
        b4 = c(self.pool_features, (1, 1))(_pool_avg(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionA(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(384, (3, 3), (2, 2), "VALID")(x, train)
        b2 = c(96, (3, 3), (2, 2), "VALID")(
            c(96, (3, 3))(c(64, (1, 1))(x, train), train), train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionB(nn.Module):
    """Factorized 7x7 branches (the V3 signature block)."""
    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(ConvBN, dtype=self.dtype)
        cc = self.channels_7x7
        b1 = c(192, (1, 1))(x, train)
        b2 = c(192, (7, 1))(c(cc, (1, 7))(c(cc, (1, 1))(x, train), train),
                            train)
        b3 = x
        for k, ch in (((1, 1), cc), ((7, 1), cc), ((1, 7), cc),
                      ((7, 1), cc), ((1, 7), 192)):
            b3 = c(ch, k)(b3, train)
        b4 = c(192, (1, 1))(_pool_avg(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionB(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(320, (3, 3), (2, 2), "VALID")(c(192, (1, 1))(x, train),
                                             train)
        b2 = c(192, (1, 1))(x, train)
        b2 = c(192, (1, 7))(b2, train)
        b2 = c(192, (7, 1))(b2, train)
        b2 = c(192, (3, 3), (2, 2), "VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    """Expanded-filter-bank output blocks (8x8 grid)."""
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(ConvBN, dtype=self.dtype)
        b1 = c(320, (1, 1))(x, train)
        b2 = c(384, (1, 1))(x, train)
        b2 = jnp.concatenate([c(384, (1, 3))(b2, train),
                              c(384, (3, 1))(b2, train)], axis=-1)
        b3 = c(448, (1, 1))(x, train)
        b3 = c(384, (3, 3))(b3, train)
        b3 = jnp.concatenate([c(384, (1, 3))(b3, train),
                              c(384, (3, 1))(b3, train)], axis=-1)
        b4 = c(192, (1, 1))(_pool_avg(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # stem
        x = c(32, (3, 3), (2, 2), "VALID")(x, train)
        x = c(32, (3, 3), padding="VALID")(x, train)
        x = c(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = c(80, (1, 1), padding="VALID")(x, train)
        x = c(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # 35x35
        x = InceptionA(32, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = ReductionA(self.dtype)(x, train)
        # 17x17
        x = InceptionB(128, self.dtype)(x, train)
        x = InceptionB(160, self.dtype)(x, train)
        x = InceptionB(160, self.dtype)(x, train)
        x = InceptionB(192, self.dtype)(x, train)
        x = ReductionB(self.dtype)(x, train)
        # 8x8
        x = InceptionC(self.dtype)(x, train)
        x = InceptionC(self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)
