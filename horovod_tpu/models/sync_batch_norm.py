"""Standalone synchronized batch normalization.

TPU-native rebuild of the reference's ``SyncBatchNorm``
(``/root/reference/horovod/torch/sync_batch_norm.py:1-218``, which
allgathers per-rank mean/var and hand-computes the backward pass). On TPU
the cross-replica moment reduction is one ``lax.pmean`` over the mesh axis
inside the SPMD program — flax's ``BatchNorm`` already supports exactly
that via ``axis_name``, and XLA differentiates through the psum, so the
reference's 150 lines of manual backward collapse into configuration. This
module pins the defaults so users get the reference's drop-in behavior:

    norm = hvd.SyncBatchNorm()        # stats synced over hvd.mesh()
    y = norm(x, use_running_average=not train)

Must run inside traced code with the mesh axis bound (``jax.shard_map``
over ``hvd.mesh()``); outside, it falls back to local batch stats exactly
like single-process torch SyncBatchNorm.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from .. import runtime


class SyncBatchNorm(nn.BatchNorm):
    """``flax.linen.BatchNorm`` with cross-replica statistics over the
    framework's mesh axis by default (reference
    ``hvd.SyncBatchNorm``). All ``nn.BatchNorm`` fields apply; set
    ``axis_name`` explicitly to sync over a different axis (e.g. both axes
    of a 2-D hierarchical mesh: ``axis_name=("hvd_dcn", "hvd_ici")``)."""

    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = None
    axis_name: Any = None

    @nn.compact
    def __call__(self, x, use_running_average: bool | None = None):
        from ..ops.collectives import _axis_is_bound

        axis = self.axis_name
        if axis is None:
            try:
                axis = runtime.axis_name()
            except Exception:
                axis = None
        # Outside shard_map the axis isn't bound: fall back to local stats
        # (under plain-jit GSPMD the partitioner reduces the batch mean
        # globally anyway; flax also skips the pmean during init).
        if axis is not None and not self.is_initializing():
            axes = axis if isinstance(axis, (tuple, list)) else (axis,)
            if not all(_axis_is_bound(a) for a in axes):
                axis = None
        if use_running_average is None:
            use_running_average = self.use_running_average
        # forward every nn.BatchNorm field (robust to fields flax adds),
        # overriding only the axis_name resolution above
        fields = {f for f in nn.BatchNorm.__dataclass_fields__
                  if f not in ("parent", "name")}
        kwargs = {f: getattr(self, f) for f in fields}
        kwargs.update(use_running_average=use_running_average,
                      axis_name=axis)
        return nn.BatchNorm(name="sync_bn", **kwargs)(x)
