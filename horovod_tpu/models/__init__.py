from .resnet import ResNet, ResNet18, ResNet34, ResNet50, ResNet101, ResNet152
from .sync_batch_norm import SyncBatchNorm
from .transformer import TransformerConfig, TransformerLM, param_shardings

__all__ = [
    "ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101", "ResNet152",
    "SyncBatchNorm", "TransformerConfig", "TransformerLM", "param_shardings",
]
