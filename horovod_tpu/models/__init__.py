from .inception import InceptionV3
from .resnet import ResNet, ResNet18, ResNet34, ResNet50, ResNet101, ResNet152
from .sync_batch_norm import SyncBatchNorm
from .transformer import TransformerConfig, TransformerLM, param_shardings
from .vgg import VGG, VGG16, VGG19

__all__ = [
    "ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101", "ResNet152",
    "VGG", "VGG16", "VGG19", "InceptionV3",
    "SyncBatchNorm", "TransformerConfig", "TransformerLM", "param_shardings",
]
