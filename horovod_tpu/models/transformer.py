"""GPT-style Transformer LM, written for multi-axis mesh sharding.

No reference equivalent (Horovod is model-agnostic); this is the flagship
model for demonstrating the framework's tensor/sequence/data-parallel
shardings beyond the reference's data-parallel scope (SURVEY.md §2.3).

TPU-first: bfloat16 compute/fp32 params, head and MLP dims sized for the
MXU, and a ``shardings()`` helper producing PartitionSpecs for a
``('dp', 'tp')``(+ optional 'sp') mesh — Megatron-style column/row-parallel
splits expressed as GSPMD sharding constraints, letting XLA insert the
all-reduces over ICI.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


# THE valid attention schedules — single source of truth for the config
# validator, the Attention dispatch, and the position-offset check.
RING_SCHEDULES = {"ring": "contiguous", "ring_zigzag": "zigzag"}
SEQ_PARALLEL_MODES = tuple(RING_SCHEDULES) + ("ulysses",)
ATTN_MODES = ("full",) + SEQ_PARALLEL_MODES


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    d_model: int = 512
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    # long-context schedule: "full" (exact local attention), "ring"
    # (horovod_tpu.parallel.ring_attention — sequence sharded over
    # seq_axis, KV blocks rotate over ICI), "ring_zigzag" (the ring with
    # the causal load-balanced zigzag chunk schedule — the 2x causal
    # saving lands in wall-clock, not just FLOPs), or "ulysses"
    # (all-to-all seq<->head switch). All but "full" require the model to
    # run inside shard_map with seq_axis bound and the sequence sharded.
    attn_mode: str = "full"
    seq_axis: str = "sp"
    # expert parallelism: moe_experts > 0 replaces the dense MLP with an
    # expert-parallel MoE FFN (horovod_tpu.parallel.moe_alltoall) — one
    # expert per chip of moe_axis, which must be bound (shard_map) with
    # size == moe_experts at run time. The Switch load-balance loss is
    # sown under ("intermediates", "moe_aux"); collect it with
    # apply(..., mutable=["intermediates"]) and add it to the objective.
    moe_experts: int = 0
    moe_axis: str = "ep"
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25

    def __post_init__(self):
        # An unknown mode would silently fall through to full LOCAL
        # attention per shard — training runs, logits are wrong.
        if self.attn_mode not in ATTN_MODES:
            raise ValueError(
                f"unknown attn_mode {self.attn_mode!r}; valid: "
                f"{ATTN_MODES}")


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        head_dim = cfg.d_model // cfg.num_heads
        dense = lambda name, features: nn.DenseGeneral(
            features, axis=-1, name=name, dtype=cfg.dtype,
            param_dtype=jnp.float32, use_bias=False)
        # qkv: column-parallel (heads split over 'tp')
        q = dense("q", (cfg.num_heads, head_dim))(x)
        k = dense("k", (cfg.num_heads, head_dim))(x)
        v = dense("v", (cfg.num_heads, head_dim))(x)
        if cfg.attn_mode in RING_SCHEDULES and not self.is_initializing():
            from ..parallel import ring_attention
            out = ring_attention(q, k, v, cfg.seq_axis, causal=True,
                                 schedule=RING_SCHEDULES[cfg.attn_mode])
        elif cfg.attn_mode == "ulysses" and not self.is_initializing():
            from ..parallel import ulysses_attention
            out = ulysses_attention(q, k, v, cfg.seq_axis, causal=True)
        else:
            q = q / jnp.sqrt(head_dim).astype(cfg.dtype)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k)
            seq = x.shape[1]
            mask = jnp.tril(jnp.ones((seq, seq), bool))
            logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
            probs = jax.nn.softmax(logits.astype(jnp.float32),
                                   axis=-1).astype(cfg.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        # output proj: row-parallel
        return nn.DenseGeneral(cfg.d_model, axis=(-2, -1), name="o",
                               dtype=cfg.dtype, param_dtype=jnp.float32,
                               use_bias=False)(out)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.Dense(cfg.d_ff, dtype=cfg.dtype, param_dtype=jnp.float32,
                     use_bias=False, name="wi")(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.d_model, dtype=cfg.dtype, param_dtype=jnp.float32,
                        use_bias=False, name="wo")(h)


class MoeMLP(nn.Module):
    """Expert-parallel MoE FFN: one expert per chip of ``cfg.moe_axis``,
    routed through :func:`horovod_tpu.parallel.moe_alltoall`.

    Expert weights are stored REPLICATED with a leading (n_experts, ...)
    dim (flax's param shape check ties the stored leaf to its declared
    shape, so a per-chip-sharded leaf cannot flow through ``self.param``).
    Each chip produces nonzero grads only for its own expert's slice, so
    the module pre-scales the selected expert weights' gradient by
    axis_size (a forward-identical ``w·n − stop_gradient(w)·(n−1)``):
    the framework's standard AVERAGE gradient sync then yields exactly
    the per-expert gradient, with no special-casing of expert leaves.
    For the memory-scaling expert-parallel layout (each chip storing only
    its expert), call :func:`~horovod_tpu.parallel.moe_alltoall` directly
    with your own parameter pytree, as ``examples/moe.py`` does — plain
    pytrees shard freely where flax module params cannot.
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        n_e, d = cfg.moe_experts, cfg.d_model
        router = nn.Dense(n_e, name="router", dtype=jnp.float32,
                          param_dtype=jnp.float32, use_bias=False)
        init = nn.initializers.lecun_normal()
        w_in = self.param("w_in", init, (n_e, d, cfg.d_ff), jnp.float32)
        w_out = self.param("w_out", init, (n_e, cfg.d_ff, d), jnp.float32)
        b, s, _ = x.shape
        flat = x.reshape(b * s, d).astype(cfg.dtype)
        logits = router(flat)
        if self.is_initializing():
            # no mesh axis bound at init: a dense pass through expert 0
            # creates the params; routing never runs here
            h = nn.gelu(flat @ w_in[0].astype(cfg.dtype))
            return (h @ w_out[0].astype(cfg.dtype)).reshape(b, s, d)

        from ..parallel import moe_alltoall

        idx = jax.lax.axis_index(cfg.moe_axis)

        def grad_boost(w):
            # forward-identical (up to one rounding step), backward xn:
            # each chip contributes grads for ONE expert, so the AVERAGE
            # sync's 1/n is pre-cancelled here and expert leaves need no
            # special treatment in the optimizer
            return w * n_e - jax.lax.stop_gradient(w) * (n_e - 1)

        def expert_fn(t):
            # replicated leaves: select this chip's expert
            wi = jax.lax.dynamic_index_in_dim(w_in, idx, 0, keepdims=False)
            wo = jax.lax.dynamic_index_in_dim(w_out, idx, 0, keepdims=False)
            h = nn.gelu(t @ grad_boost(wi).astype(t.dtype))
            return h @ grad_boost(wo).astype(t.dtype)

        y, aux = moe_alltoall(flat, logits, expert_fn, cfg.moe_axis,
                              k=cfg.moe_top_k,
                              capacity_factor=cfg.moe_capacity_factor)
        self.sow("intermediates", "moe_aux", aux)
        return y.reshape(b, s, d).astype(cfg.dtype)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        y = nn.LayerNorm(dtype=self.cfg.dtype, param_dtype=jnp.float32)(x)
        x = x + Attention(self.cfg, name="attn")(y)
        y = nn.LayerNorm(dtype=self.cfg.dtype, param_dtype=jnp.float32)(x)
        if self.cfg.moe_experts > 0:
            return x + MoeMLP(self.cfg, name="moe_mlp")(y)
        return x + MLP(self.cfg, name="mlp")(y)


class TransformerLM(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed")(tokens)
        positions = jnp.arange(tokens.shape[1])
        if (cfg.attn_mode in SEQ_PARALLEL_MODES
                and not self.is_initializing()):
            # sequence-parallel: this shard holds a block of the global
            # sequence — positions are offset by the block index
            positions = positions + jax.lax.axis_index(
                cfg.seq_axis) * tokens.shape[1]
        pos = nn.Embed(cfg.max_seq_len, cfg.d_model, dtype=cfg.dtype,
                       param_dtype=jnp.float32, name="pos_embed")(positions)
        x = x + pos[None]
        for i in range(cfg.num_layers):
            x = Block(cfg, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32,
                         name="ln_f")(x)
        logits = nn.Dense(cfg.vocab_size, dtype=cfg.dtype,
                          param_dtype=jnp.float32, use_bias=False,
                          name="lm_head")(x)
        return logits.astype(jnp.float32)


def param_shardings(params, *, tp_axis: str = "tp"):
    """PartitionSpec pytree for Megatron-style tensor parallelism:
    column-parallel qkv/wi (split output dim over tp), row-parallel o/wo
    (split input dim), embeddings split over vocab/d_ff-free dims."""

    def spec_for(path, leaf):
        names = [getattr(k, "key", str(k)) for k in path]
        joined = "/".join(str(n) for n in names)
        nd = leaf.ndim
        if "attn" in joined and any(f"/{p}/" in joined + "/" for p in ("q", "k", "v")):
            # (d_model, heads, head_dim): split heads over tp
            return P(None, tp_axis, None) if nd == 3 else P(None, tp_axis)
        if "/o/" in joined + "/":
            # (heads, head_dim, d_model): split heads over tp
            return P(tp_axis, None, None) if nd == 3 else P(tp_axis, None)
        if joined.endswith("wi/kernel"):
            return P(None, tp_axis)
        if joined.endswith("wo/kernel"):
            return P(tp_axis, None)
        if joined.endswith("lm_head/kernel"):
            return P(None, tp_axis)
        if joined == "embed/embedding":  # vocab table only; pos_embed stays
            return P(tp_axis, None)      # replicated (seq rarely divides tp)
        return P(*([None] * nd))

    return jax.tree_util.tree_map_with_path(spec_for, params)
