"""VGG family in flax, TPU-first.

One of the reference's three published scaling-efficiency models
(``/root/reference/docs/benchmarks.rst:13-14``: VGG-16 at 68% on 512
GPUs — the hard case, its large dense layers stress allreduce
bandwidth, which is exactly why it belongs in the scaling harness).
NHWC layout, bfloat16 compute with float32 parameters.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# convs per stage; channels double per stage from 64 to 512
VGG16_STAGES = (2, 2, 3, 3, 3)
VGG19_STAGES = (2, 2, 4, 4, 4)


class VGG(nn.Module):
    stage_sizes: Sequence[int] = VGG16_STAGES
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    classifier_width: int = 4096

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, kernel_size=(3, 3), padding="SAME",
                       dtype=self.dtype, param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        for i, reps in enumerate(self.stage_sizes):
            ch = min(64 * 2 ** i, 512)
            for _ in range(reps):
                x = nn.relu(conv(ch)(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        dense = partial(nn.Dense, dtype=self.dtype, param_dtype=jnp.float32)
        x = nn.relu(dense(self.classifier_width)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(dense(self.classifier_width)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return dense(self.num_classes)(x).astype(jnp.float32)


VGG16 = partial(VGG, stage_sizes=VGG16_STAGES)
VGG19 = partial(VGG, stage_sizes=VGG19_STAGES)
