"""``hvdrun`` — the launcher CLI.

TPU-native rebuild of the reference's ``horovodrun``
(``/root/reference/horovod/runner/launch.py:242-775``): parse host/slot
topology, seed per-worker env (rank layout + rendezvous coordinates), spawn
one controller process per slot — locally or over ssh — and supervise the
job. The gloo/MPI controller split disappears: workers rendezvous through
``jax.distributed`` (coordinator = rank-0 host) plus the launcher's HTTP KV
store (results, elastic notifications).

Static path mirrors ``_run_static`` (``launch.py:530-620``); elastic path
mirrors ``_run_elastic`` (``launch.py:623-672``) and is implemented in
``horovod_tpu.elastic``.
"""

from __future__ import annotations

import argparse
import functools
import os
import shlex
import socket
import subprocess
import sys
import threading

from . import hosts as hosts_mod
from . import safe_exec
from .http_kv import KVServer, local_addresses, make_secret
from ..utils import envs
from ..version import __version__

SSH_OPTIONS = ["-o", "PasswordAuthentication=no",
               "-o", "StrictHostKeyChecking=no",
               "-o", "ConnectTimeout=10"]

# env vars forwarded from the launcher environment to every worker
# (reference forwards the full env over ssh via env exports,
# gloo_run.py:114-199)
_FORWARD_PREFIXES = ("HVD_", "HOROVOD_", "JAX_", "XLA_", "TPU_", "LIBTPU_",
                     "PYTHON", "PATH", "LD_", "VIRTUAL_ENV", "HOME", "USER",
                     "CUDA_", "TF_", "NCCL_")


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu distributed job.",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("-v", "--version", action="version",
                        version=f"hvdrun {__version__}")
    parser.add_argument("-np", "--num-proc", dest="np", type=int, default=None,
                        help="total number of worker processes")
    parser.add_argument("-H", "--hosts", default=None,
                        help='host list, e.g. "h1:2,h2:2" (slots default 1)')
    parser.add_argument("--hostfile", default=None,
                        help='hostfile with "hostname slots=N" lines')
    parser.add_argument("--slots-per-host", type=int, default=None,
                        help="override slot count for every host")
    parser.add_argument("--min-np", type=int, default=None,
                        help="elastic: minimum world size")
    parser.add_argument("--max-np", type=int, default=None,
                        help="elastic: maximum world size")
    parser.add_argument("--host-discovery-script", default=None,
                        help="elastic: executable printing one host:slots per line")
    parser.add_argument("--reset-limit", type=int, default=None,
                        help="elastic: stop after this many resets")
    parser.add_argument("--blacklist-cooldown-range", nargs=2, type=float,
                        default=None, metavar=("LO", "HI"),
                        help="elastic: blacklisted-host cooldown bounds (s)")
    parser.add_argument("--ssh-port", type=int, default=None)
    parser.add_argument("--ssh-identity-file", default=None)
    parser.add_argument("--start-timeout", type=float, default=600.0,
                        help="seconds to wait for the job to start")
    parser.add_argument("--output-filename", default=None,
                        help="redirect per-rank output to <dir>/rank.<N>/stdout|stderr")
    parser.add_argument("--coordinator-port", type=int, default=0,
                        help="port for jax.distributed coordinator (0 = auto)")
    parser.add_argument("--config-file", default=None,
                        help="YAML config file (CLI flags win)")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--disable-cache", action="store_true",
                        help="set HVD_CACHE_CAPACITY=0 in workers")
    parser.add_argument("--timeline-filename", default=None)
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="expose each worker's Prometheus /metrics "
                             "on this base port + its rank (seeds "
                             "HVD_METRICS_PORT; docs/metrics.md). The "
                             "launcher KV server always serves its own "
                             "/metrics route")
    parser.add_argument("--autotune", action="store_true")
    parser.add_argument("--env", action="append", default=[],
                        metavar="NAME=VALUE", help="extra env for workers")
    parser.add_argument("--loopback", action="store_true",
                        help="run all ranks as threads in ONE interpreter "
                             "over the in-process loopback engine "
                             "(hvd.loopback; docs/loopback.md) — the "
                             "world>1 stack without cross-process XLA, "
                             "so jax<0.5 CPU backends work")
    parser.add_argument("--launcher", choices=("auto", "local", "lsf"),
                        default="auto",
                        help="host-source escape hatch: 'auto' derives "
                             "hosts from a detected LSF allocation when no "
                             "-H/--hostfile is given, 'local' ignores "
                             "scheduler env, 'lsf' requires an LSF "
                             "allocation and fails loudly without one")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the training command")
    args = parser.parse_args(argv)

    if args.config_file:
        from . import config_parser
        cfg = config_parser.load_config(args.config_file)
        explicit = _explicit_dests(argv if argv is not None else sys.argv[1:], parser)
        config_parser.apply_config_to_args(cfg, args, explicit)
        args._config_env = config_parser.config_to_env(cfg)
    else:
        args._config_env = {}
    return args


def _explicit_dests(argv, parser) -> set:
    """Dest names of launcher options actually present on the command line.

    Scanning stops at ``--`` or at the first token that starts the training
    command, so flag lookalikes inside the command (e.g. the user's own
    ``--verbose``) are not misclassified as launcher options."""
    explicit = set()
    opt_actions = {}
    for action in parser._actions:
        for opt in action.option_strings:
            opt_actions[opt] = action
    i = 0
    while i < len(argv):
        tok = argv[i]
        if tok == "--":
            break
        if tok.startswith("-"):
            opt = tok.split("=", 1)[0]
            action = opt_actions.get(opt)
            if action is None and opt.startswith("--"):
                # argparse accepts unambiguous long-option abbreviations
                matches = {a for o, a in opt_actions.items()
                           if o.startswith(opt)}
                if len(matches) == 1:
                    action = next(iter(matches))
            if action is None:
                break  # unknown flag: the training command has started
            explicit.add(action.dest)
            if "=" in tok or isinstance(action, (
                    argparse._StoreTrueAction, argparse._StoreFalseAction,
                    argparse._CountAction, argparse._HelpAction,
                    argparse._VersionAction)):
                consumed = 0
            elif isinstance(action.nargs, int):
                consumed = action.nargs  # e.g. --blacklist-cooldown-range LO HI
            else:
                consumed = 1
            i += 1 + consumed
            continue
        break  # first positional token: the training command has started
    return explicit


def _resolve_hosts(args) -> list[hosts_mod.HostSpec]:
    from . import lsf

    if args.hosts and args.hostfile:
        raise ValueError("--hosts and --hostfile are mutually exclusive")
    launcher = getattr(args, "launcher", "auto")
    if launcher == "lsf" and not lsf.using_lsf():
        raise RuntimeError("--launcher lsf: no LSF allocation detected "
                           "(LSB_JOBID not set)")
    specs = None
    if args.hosts:
        specs = hosts_mod.parse_hosts(args.hosts)
    elif args.hostfile:
        specs = hosts_mod.parse_hostfile(args.hostfile)
    elif launcher != "local" and lsf.using_lsf():
        # hvdrun inside an LSF allocation: hosts come from the allocation
        # itself (reference launch.py does the same via LSFUtils)
        try:
            specs = lsf.lsf_host_specs()
        except RuntimeError:
            if launcher == "lsf":
                raise  # explicitly requested: fail loudly
            # auto: LSB_JOBID present but no usable host env — fall through
    if specs is None:
        specs = [hosts_mod.HostSpec("localhost", args.np or 1)]
    if args.slots_per_host:
        specs = [hosts_mod.HostSpec(h.hostname, args.slots_per_host)
                 for h in specs]
    return specs


_is_local_cache: dict[str, bool] = {}


def is_local_host(hostname: str) -> bool:
    if hostname in ("localhost", "127.0.0.1", socket.gethostname()):
        return True
    cached = _is_local_cache.get(hostname)
    if cached is not None:
        return cached
    try:
        result = socket.gethostbyname(hostname) in local_addresses()
    except OSError:
        return False  # transient resolver failure: do NOT memoize
    _is_local_cache[hostname] = result
    return result


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _forwarded_env() -> dict[str, str]:
    env = {}
    for k, v in os.environ.items():
        if k.startswith(_FORWARD_PREFIXES):
            env[k] = v
    # Make sure workers can import this package even when it is not
    # pip-installed and the worker script lives elsewhere (reference relies
    # on horovod being installed on every host; we forward the import root).
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    parts = os.environ.get("PYTHONPATH", "").split(os.pathsep)
    if pkg_root not in parts:
        parts.insert(0, pkg_root)
    env["PYTHONPATH"] = os.pathsep.join(p for p in parts if p)
    return env


def worker_env(slot: hosts_mod.SlotInfo, *, coordinator_addr: str,
               coordinator_port: int, kv_addr: str, kv_port: int,
               secret: str, extra: dict | None = None) -> dict[str, str]:
    """Seed one worker's env (reference seeds HOROVOD_RANK/... at
    ``gloo_run.py:65-101,201-226``)."""
    env = _forwarded_env()
    env.update({
        "HVD_RANK": str(slot.rank),
        "HVD_SIZE": str(slot.size),
        "HVD_LOCAL_RANK": str(slot.local_rank),
        "HVD_LOCAL_SIZE": str(slot.local_size),
        "HVD_CROSS_RANK": str(slot.cross_rank),
        "HVD_CROSS_SIZE": str(slot.cross_size),
        "HVD_PROCESS_ID": str(slot.rank),
        "HVD_NUM_PROCESSES": str(slot.size),
        "HVD_COORDINATOR_ADDR": coordinator_addr,
        "HVD_COORDINATOR_PORT": str(coordinator_port),
        "HVD_KV_ADDR": kv_addr,
        "HVD_KV_PORT": str(kv_port),
        "HVD_SECRET_KEY": secret,
        "HVD_HOSTNAME": slot.hostname,
    })
    if extra:
        env.update(extra)
    return env


# Env vars whose values must never appear in an ssh argv (visible to every
# local user via ps). The reference excludes the secret from ssh-exported env
# the same way (``runner/common/util/env.py:24`` IGNORE_REGEXES); we deliver
# it over the ssh channel's stdin instead.
_SECRET_ENV_VARS = ("HVD_SECRET_KEY",)


def _ssh_base_cmd(ssh_port: int | None, identity_file: str | None) -> list[str]:
    return (["ssh"] + SSH_OPTIONS
            + (["-p", str(ssh_port)] if ssh_port else [])
            + (["-i", identity_file] if identity_file else []))


def _ssh_command(hostname: str, command: list[str], env: dict[str, str],
                 ssh_port: int | None, identity_file: str | None) -> list[str]:
    public_env = {k: v for k, v in env.items() if k not in _SECRET_ENV_VARS}
    exports = " ".join(f"export {k}={shlex.quote(v)};"
                       for k, v in public_env.items())
    secret_reads = " ".join(f"IFS= read -r {k}; export {k};"
                            for k in _SECRET_ENV_VARS if k in env)
    remote = (f"cd {shlex.quote(os.getcwd())} 2>/dev/null; {secret_reads} "
              f"{exports} " + " ".join(shlex.quote(c) for c in command))
    return _ssh_base_cmd(ssh_port, identity_file) + [hostname, remote]


def spawn_worker(slot: hosts_mod.SlotInfo, command: list[str],
                 env: dict[str, str], args) -> safe_exec.ExecutedProcess:
    stdout = stderr = None
    owned = []
    if args.output_filename:
        d = os.path.join(args.output_filename, f"rank.{slot.rank}")
        os.makedirs(d, exist_ok=True)
        stdout = open(os.path.join(d, "stdout"), "w")
        stderr = open(os.path.join(d, "stderr"), "w")
        owned = [stdout, stderr]
    if is_local_host(slot.hostname):
        full_env = dict(os.environ)
        full_env.update(env)
        return safe_exec.execute(command, env=full_env, index=slot.rank,
                                 stdout=stdout, stderr=stderr, owned_files=owned)
    cmd = _ssh_command(slot.hostname, command, env,
                       args.ssh_port, args.ssh_identity_file)
    secret_lines = b"".join(env[k].encode() + b"\n"
                            for k in _SECRET_ENV_VARS if k in env)
    return safe_exec.execute(cmd, env=dict(os.environ), index=slot.rank,
                             stdout=stdout, stderr=stderr, shell=False,
                             stdin_data=secret_lines or None, owned_files=owned)


def probe_remote_free_port(hostname: str, ssh_port=None,
                           identity_file=None, timeout: float = 20) -> int:
    """Ask ``hostname``'s kernel for a free ephemeral port over ssh.

    Used for the remote jax.distributed coordinator endpoint: a
    kernel-assigned ephemeral port is vastly less collision-prone than a
    blind random pick (the kernel avoids ports in use and cycles the
    ephemeral range). Raises on ssh failure or unparsable output."""
    probe = ("python3 -c 'import socket; s=socket.socket(); "
             "s.bind((\"\", 0)); print(s.getsockname()[1])'")
    cmd = _ssh_base_cmd(ssh_port, identity_file) + [hostname, probe]
    out = subprocess.run(cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, timeout=timeout, env=dict(os.environ))
    if out.returncode != 0:
        raise RuntimeError(
            f"port probe on {hostname} failed: {out.stderr.strip()[:500]}")
    return int(out.stdout.strip().splitlines()[-1])


def check_hosts_ssh(hostnames: list[str], ssh_port=None,
                    identity_file=None) -> None:
    """Fail fast when a remote host is unreachable (reference
    ``_check_all_hosts_ssh_successful``, ``launch.py:58-108``)."""
    remote = [h for h in hostnames if not is_local_host(h)]
    failures = []

    def check(h):
        cmd = _ssh_base_cmd(ssh_port, identity_file) + [h, "true"]
        if safe_exec.run(cmd, env=dict(os.environ), prefix_output=False) != 0:
            failures.append(h)

    threads = [threading.Thread(target=check, args=(h,)) for h in set(remote)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if failures:
        raise RuntimeError(f"ssh connection failed for hosts: {sorted(failures)}")


class JobRendezvous:
    """Shared rendezvous state for one job: the launcher-side KV server and
    the coordinator address workers will dial."""

    def __init__(self, slots: list[hosts_mod.SlotInfo],
                 coordinator_port: int = 0):
        self.secret = make_secret()
        self.kv = KVServer(secret=self.secret)
        self.kv_port = self.kv.start()
        all_local = all(is_local_host(s.hostname) for s in slots)
        self.kv_addr = "127.0.0.1" if all_local else local_addresses()[0]
        # jax.distributed coordinator lives in rank 0's process on rank 0's
        # host, so that is the address every worker must dial.
        coord_host = slots[0].hostname
        self.coord_addr = "127.0.0.1" if all_local else (
            self.kv_addr if is_local_host(coord_host) else coord_host)
        self.coord_port = coordinator_port or _free_port()

    def worker_env(self, slot, extra=None) -> dict[str, str]:
        return worker_env(
            slot, coordinator_addr=self.coord_addr,
            coordinator_port=self.coord_port, kv_addr=self.kv_addr,
            kv_port=self.kv_port, secret=self.secret, extra=extra)

    def stop(self) -> None:
        self.kv.stop()


def run_static(args, command: list[str]) -> int:
    """Spawn all ranks, wait; first failure tears the job down
    (reference ``_run_static`` + ``launch_gloo``)."""
    specs = _resolve_hosts(args)
    np = args.np or hosts_mod.total_slots(specs)
    slots = hosts_mod.get_host_assignments(specs, np)
    check_hosts_ssh([s.hostname for s in slots],
                    args.ssh_port, args.ssh_identity_file)

    rdv = JobRendezvous(slots, args.coordinator_port)

    extra = dict(args._config_env)
    for assignment in args.env:
        k, _, v = assignment.partition("=")
        extra[k] = v
    if args.disable_cache:
        extra["HVD_CACHE_CAPACITY"] = "0"
    if args.timeline_filename:
        extra["HVD_TIMELINE"] = args.timeline_filename
    if args.metrics_port:
        extra["HVD_METRICS_PORT"] = str(args.metrics_port)
    if args.autotune:
        extra["HVD_AUTOTUNE"] = "1"

    procs = []
    try:
        for slot in slots:
            procs.append(spawn_worker(slot, command,
                                      rdv.worker_env(slot, extra), args))
        return _supervise(procs, slots, args)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        rdv.stop()


def _supervise(procs, slots, args) -> int:
    """Wait for all workers; kill the job on first failure (reference
    MULTI-process supervision in ``gloo_run.py:114-199``)."""
    exit_codes: dict[int, int] = {}
    lock = threading.Lock()
    failed = threading.Event()

    def waiter(i, p):
        code = p.wait()
        with lock:
            exit_codes[i] = code
        if code != 0:
            failed.set()

    threads = [threading.Thread(target=waiter, args=(i, p), daemon=True)
               for i, p in enumerate(procs)]
    for t in threads:
        t.start()
    while True:
        with lock:
            if len(exit_codes) == len(procs):
                break
        if failed.wait(timeout=0.2):
            break
    if failed.is_set():
        with lock:
            bad = {slots[i].rank: c for i, c in exit_codes.items() if c != 0}
        for p in procs:
            if p.poll() is None:
                p.terminate()
        print(f"hvdrun: worker failure, exit codes by rank: {bad}",
              file=sys.stderr)
        return next(iter(bad.values()), 1)
    for t in threads:
        t.join()
    return 0


def run_commandline(argv=None) -> int:
    args = parse_args(argv)
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("hvdrun: no command given", file=sys.stderr)
        return 2
    if args.verbose:
        envs.set_env(envs.LOG_LEVEL, "debug", only_if_unset=True)
    elastic = args.host_discovery_script or args.min_np or args.max_np
    if elastic:
        try:
            from ..elastic.launch import run_elastic
        except ImportError as e:
            print(f"hvdrun: elastic launch unavailable ({e})", file=sys.stderr)
            return 2
        return run_elastic(args, command)
    if args.loopback:
        from ..loopback.engine import run_command as run_loopback
        return run_loopback(args, command)
    return run_static(args, command)


def main() -> None:  # console entry point
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
