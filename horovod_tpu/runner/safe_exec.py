"""Process-tree-safe command execution.

TPU-native rebuild of the reference's ``safe_shell_exec``
(``/root/reference/horovod/runner/common/util/safe_shell_exec.py``): run a
worker command in its own session, stream its output with a per-rank prefix,
and guarantee the whole process tree dies with the launcher.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time

GRACEFUL_TERMINATION_TIME_S = 5


def _kill_tree(pid: int, sig: int) -> None:
    """Signal a process and all descendants (reference kills the process
    group + psutil children)."""
    try:
        import psutil
        try:
            root = psutil.Process(pid)
        except psutil.NoSuchProcess:
            return
        procs = [root] + root.children(recursive=True)
        for p in procs:
            try:
                p.send_signal(sig)
            except psutil.NoSuchProcess:
                pass
    except ImportError:  # pragma: no cover
        try:
            os.killpg(os.getpgid(pid), sig)
        except (ProcessLookupError, PermissionError):
            pass


def terminate_tree(pid: int) -> None:
    """SIGTERM the tree, escalate to SIGKILL after a grace period."""
    _kill_tree(pid, signal.SIGTERM)
    deadline = time.monotonic() + GRACEFUL_TERMINATION_TIME_S
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return
        time.sleep(0.1)
    _kill_tree(pid, signal.SIGKILL)


def _pump(stream, sink, prefix: str, index: int | None,
          prefix_output: bool) -> None:
    for raw in iter(stream.readline, b""):
        line = raw.decode(errors="replace")
        if prefix_output and index is not None:
            sink.write(f"[{index}]<{prefix}>:{line}")
        else:
            sink.write(line)
        sink.flush()
    stream.close()


class ExecutedProcess:
    """Handle to a spawned worker command."""

    def __init__(self, proc: subprocess.Popen, pumps: list[threading.Thread]):
        self.proc = proc
        self._pumps = pumps

    @property
    def pid(self) -> int:
        return self.proc.pid

    def wait(self, timeout: float | None = None) -> int:
        code = self.proc.wait(timeout)
        for t in self._pumps:
            t.join(timeout=1.0)
        return code

    def poll(self) -> int | None:
        return self.proc.poll()

    def terminate(self) -> None:
        terminate_tree(self.proc.pid)


def execute(command: str | list[str], env: dict | None = None,
            index: int | None = None, prefix_output: bool = True,
            stdout=None, stderr=None, shell: bool | None = None) -> ExecutedProcess:
    """Spawn ``command`` in a new session with piped, prefix-tagged output
    (reference ``safe_shell_exec.execute``)."""
    if shell is None:
        shell = isinstance(command, str)
    proc = subprocess.Popen(
        command, shell=shell, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        start_new_session=True)
    pumps = [
        threading.Thread(
            target=_pump,
            args=(proc.stdout, stdout or sys.stdout, "stdout", index, prefix_output),
            daemon=True),
        threading.Thread(
            target=_pump,
            args=(proc.stderr, stderr or sys.stderr, "stderr", index, prefix_output),
            daemon=True),
    ]
    for t in pumps:
        t.start()
    return ExecutedProcess(proc, pumps)


def run(command: str | list[str], env: dict | None = None,
        index: int | None = None, **kw) -> int:
    """Execute and wait; on KeyboardInterrupt tear down the tree."""
    p = execute(command, env=env, index=index, **kw)
    try:
        return p.wait()
    except KeyboardInterrupt:
        p.terminate()
        raise
