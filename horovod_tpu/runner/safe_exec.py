"""Process-tree-safe command execution.

TPU-native rebuild of the reference's ``safe_shell_exec``
(``/root/reference/horovod/runner/common/util/safe_shell_exec.py``): run a
worker command in its own session, stream its output with a per-rank prefix,
and guarantee the whole process tree dies with the launcher.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time

GRACEFUL_TERMINATION_TIME_S = 5


def _kill_tree(pid: int, sig: int) -> None:
    """Signal a process and all descendants (reference kills the process
    group + psutil children)."""
    try:
        import psutil
        try:
            root = psutil.Process(pid)
            # children() re-reads /proc; the root can exit between the
            # Process() lookup and here, raising NoSuchProcess from either
            # call — treat both as "tree already gone".
            procs = [root] + root.children(recursive=True)
        except psutil.NoSuchProcess:
            return
        for p in procs:
            try:
                p.send_signal(sig)
            except psutil.NoSuchProcess:
                pass
    except ImportError:  # pragma: no cover
        try:
            os.killpg(os.getpgid(pid), sig)
        except (ProcessLookupError, PermissionError):
            pass


def terminate_tree(pid: int) -> None:
    """SIGTERM the tree, escalate to SIGKILL after a grace period."""
    _kill_tree(pid, signal.SIGTERM)
    deadline = time.monotonic() + GRACEFUL_TERMINATION_TIME_S
    while time.monotonic() < deadline:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return
        # kill-escalation probe, not an RPC retry: there is no server to
        # back off from and the grace window is short and local
        time.sleep(0.1)  # hvdlint: disable=silent-except
    _kill_tree(pid, signal.SIGKILL)


def _pump(stream, sink, prefix: str, index: int | None,
          prefix_output: bool) -> None:
    try:
        for raw in iter(stream.readline, b""):
            line = raw.decode(errors="replace")
            if prefix_output and index is not None:
                sink.write(f"[{index}]<{prefix}>:{line}")
            else:
                sink.write(line)
            sink.flush()
    except ValueError:
        pass  # sink force-closed during teardown; drop the tail
    finally:
        stream.close()


class ExecutedProcess:
    """Handle to a spawned worker command."""

    def __init__(self, proc: subprocess.Popen, pumps: list[threading.Thread],
                 owned_files: list | None = None):
        self.proc = proc
        self._pumps = pumps
        self._owned_files = owned_files or []

    @property
    def pid(self) -> int:
        return self.proc.pid

    def _close_owned(self, blocking: bool = True) -> None:
        # Once the process is dead the pipes hit EOF and the pumps finish on
        # their own; the timeout is just a backstop. Only close the sink
        # files once every pump that writes to them has exited, so a slow
        # drain can't race a closed file.
        deadline = time.monotonic() + (30.0 if blocking else 0.0)
        for t in self._pumps:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        if any(t.is_alive() for t in self._pumps):
            return  # keep files open; retry on the next wait()/poll()
        for f in self._owned_files:
            try:
                f.close()
            except OSError:
                pass
        self._owned_files = []

    def wait(self, timeout: float | None = None) -> int:
        code = self.proc.wait(timeout)
        self._close_owned()
        return code

    def poll(self) -> int | None:
        # poll() is conventionally non-blocking and is looped over in
        # teardown paths (driver round transitions hold locks there) — never
        # wait on the pump threads here; wait()/terminate() do the blocking
        # join.
        code = self.proc.poll()
        if code is not None:
            self._close_owned(blocking=False)
        return code

    def terminate(self) -> None:
        terminate_tree(self.proc.pid)
        self._close_owned()


def execute(command: str | list[str], env: dict | None = None,
            index: int | None = None, prefix_output: bool = True,
            stdout=None, stderr=None, shell: bool | None = None,
            stdin_data: bytes | None = None,
            owned_files: list | None = None) -> ExecutedProcess:
    """Spawn ``command`` in a new session with piped, prefix-tagged output
    (reference ``safe_shell_exec.execute``). ``stdin_data`` is written to the
    child's stdin and then stdin is closed — used to hand secrets to remote
    workers without exposing them in argv."""
    if shell is None:
        shell = isinstance(command, str)
    proc = subprocess.Popen(
        command, shell=shell, env=env,
        stdin=subprocess.PIPE if stdin_data is not None else subprocess.DEVNULL,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        start_new_session=True)
    if stdin_data is not None:
        try:
            proc.stdin.write(stdin_data)
            proc.stdin.close()
        except (BrokenPipeError, OSError):
            pass  # child died before reading; its exit code tells the story
    pumps = [
        threading.Thread(
            target=_pump,
            args=(proc.stdout, stdout or sys.stdout, "stdout", index, prefix_output),
            daemon=True),
        threading.Thread(
            target=_pump,
            args=(proc.stderr, stderr or sys.stderr, "stderr", index, prefix_output),
            daemon=True),
    ]
    for t in pumps:
        t.start()
    return ExecutedProcess(proc, pumps, owned_files)


def run(command: str | list[str], env: dict | None = None,
        index: int | None = None, **kw) -> int:
    """Execute and wait; on KeyboardInterrupt tear down the tree."""
    p = execute(command, env=env, index=index, **kw)
    try:
        return p.wait()
    except KeyboardInterrupt:
        p.terminate()
        raise
