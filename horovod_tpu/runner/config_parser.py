"""YAML config file → CLI args / env knobs.

TPU-native rebuild of the reference's config parser
(``/root/reference/horovod/runner/common/util/config_parser.py``): a YAML
file can set every launcher argument and runtime knob; explicit CLI flags
win over the file.
"""

from __future__ import annotations

from ..utils import envs

# top-level scalar keys → argparse dest names
_ARG_KEYS = {
    "verbose": "verbose",
    "np": "np",
    "hosts": "hosts",
    "hostfile": "hostfile",
    "min-np": "min_np",
    "max-np": "max_np",
    "host-discovery-script": "host_discovery_script",
    "ssh-port": "ssh_port",
    "ssh-identity-file": "ssh_identity_file",
    "start-timeout": "start_timeout",
    "output-filename": "output_filename",
    "coordinator-port": "coordinator_port",
    "slots-per-host": "slots_per_host",
}

# params section → env knob names (values in natural units)
_PARAM_KEYS = {
    "fusion-threshold-mb": (envs.FUSION_THRESHOLD, lambda v: int(v) * 1024 * 1024),
    "cycle-time-ms": (envs.CYCLE_TIME, float),
    "cache-capacity": (envs.CACHE_CAPACITY, int),
    "hierarchical-allreduce": (envs.HIERARCHICAL_ALLREDUCE, lambda v: int(bool(v))),
    "hierarchical-allgather": (envs.HIERARCHICAL_ALLGATHER, lambda v: int(bool(v))),
}

_TIMELINE_KEYS = {
    "filename": (envs.TIMELINE, str),
    "mark-cycles": (envs.TIMELINE_MARK_CYCLES, lambda v: int(bool(v))),
}

_AUTOTUNE_KEYS = {
    "enabled": (envs.AUTOTUNE, lambda v: int(bool(v))),
    "log-file": (envs.AUTOTUNE_LOG, str),
    "warmup-samples": (envs.AUTOTUNE_WARMUP_SAMPLES, int),
    "steps-per-sample": (envs.AUTOTUNE_STEPS_PER_SAMPLE, int),
    "bayes-opt-max-samples": (envs.AUTOTUNE_BAYES_OPT_MAX_SAMPLES, int),
    "gaussian-process-noise": (envs.AUTOTUNE_GAUSSIAN_PROCESS_NOISE, float),
}

_STALL_KEYS = {
    "check-disable": (envs.STALL_CHECK_DISABLE, lambda v: int(bool(v))),
    "check-time-seconds": (envs.STALL_CHECK_TIME_SECONDS, float),
    "shutdown-time-seconds": (envs.STALL_SHUTDOWN_TIME_SECONDS, float),
}

_SECTIONS = {
    "params": _PARAM_KEYS,
    "timeline": _TIMELINE_KEYS,
    "autotune": _AUTOTUNE_KEYS,
    "stall-check": _STALL_KEYS,
}


def load_config(path: str) -> dict:
    import yaml
    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    if not isinstance(cfg, dict):
        raise ValueError(f"config file {path} must contain a mapping")
    return cfg


def apply_config_to_args(cfg: dict, args, explicit_dests: set) -> None:
    """Set argparse namespace fields from config unless given on the CLI
    (reference lets CLI override file, ``config_parser.py``)."""
    for key, dest in _ARG_KEYS.items():
        if key in cfg and dest not in explicit_dests:
            setattr(args, dest, cfg[key])


def config_to_env(cfg: dict) -> dict[str, str]:
    """Translate knob sections to HVD_* env assignments."""
    env: dict[str, str] = {}
    for section, keymap in _SECTIONS.items():
        body = cfg.get(section) or {}
        if not isinstance(body, dict):
            raise ValueError(f"config section {section!r} must be a mapping")
        for key, val in body.items():
            if key not in keymap:
                raise ValueError(f"unknown key {key!r} in section {section!r}")
            env_name, conv = keymap[key]
            env["HVD_" + env_name] = str(conv(val))
    return env
