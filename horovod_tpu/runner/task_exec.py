"""Worker-side entry for the programmatic ``run()`` API.

The launcher pickles the user function into its KV store; each worker
fetches it, executes, and puts the per-rank result back (the reference moves
results through the rendezvous KVStore the same way,
``/root/reference/horovod/runner/launch.py:598-616``).
"""

from __future__ import annotations

import sys
import traceback

import cloudpickle

from ..utils import envs
from .http_kv import KVClient


def main() -> int:
    rank = int(envs.require(envs.RANK))
    client = KVClient(envs.require(envs.KV_ADDR),
                      int(envs.require(envs.KV_PORT)),
                      secret=envs.get(envs.SECRET_KEY))
    startup_timeout = envs.get_float(envs.START_TIMEOUT, 600.0)
    fn, args, kwargs = cloudpickle.loads(
        client.wait("exec/fn", timeout=startup_timeout))
    try:
        result = fn(*args, **kwargs)
        payload = cloudpickle.dumps(("ok", result))
    except BaseException:
        payload = cloudpickle.dumps(("error", traceback.format_exc()))
        client.put(f"exec/result/{rank}", payload)
        return 1
    client.put(f"exec/result/{rank}", payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
