"""Worker-side entry for the programmatic ``run()`` API.

The launcher pickles the user function into its KV store; each worker
fetches it, executes, and puts the per-rank result back (the reference moves
results through the rendezvous KVStore the same way,
``/root/reference/horovod/runner/launch.py:598-616``).
"""

from __future__ import annotations

import os
import sys
import traceback

import cloudpickle

from .http_kv import KVClient


def main() -> int:
    rank = int(os.environ["HVD_RANK"])
    client = KVClient(os.environ["HVD_KV_ADDR"],
                      int(os.environ["HVD_KV_PORT"]),
                      secret=os.environ.get("HVD_SECRET_KEY"))
    startup_timeout = float(os.environ.get("HVD_START_TIMEOUT", "600"))
    fn, args, kwargs = cloudpickle.loads(
        client.wait("exec/fn", timeout=startup_timeout))
    try:
        result = fn(*args, **kwargs)
        payload = cloudpickle.dumps(("ok", result))
    except BaseException:
        payload = cloudpickle.dumps(("error", traceback.format_exc()))
        client.put(f"exec/result/{rank}", payload)
        return 1
    client.put(f"exec/result/{rank}", payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
