"""Threaded HTTP key-value store for rendezvous and result exchange.

TPU-native rebuild of the reference's rendezvous HTTP server
(``/root/reference/horovod/runner/http/http_server.py:152-230`` and the
client in ``http_client.py``): workers discover their placement and exchange
small payloads through scoped keys. Payloads are HMAC-signed with the
launcher's per-job secret, mirroring the reference's signed network messages
(``/root/reference/horovod/runner/common/util/network.py`` +
``secret.py``).
"""

from __future__ import annotations

import functools
import hashlib
import hmac
import http.client
import http.server
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

from .. import metrics as _metrics
from ..utils import faults as _faults
from ..utils import logging as hvd_logging
from ..utils import retry as _retry

SECRET_ENV = "HVD_SECRET_KEY"
_SIG_HEADER = "X-HVD-Signature"

# HTTP statuses worth retrying: server-side wait expiry / throttling /
# transient 5xx. 403 (bad signature) and 404 (missing key) are semantic.
_TRANSIENT_HTTP = (408, 425, 429, 500, 502, 503, 504)


def _transient_kv_error(exc: BaseException) -> bool:
    """The retry predicate for every KV seam: connection-level failures
    and transient HTTP statuses are retryable; semantic responses (404,
    signature rejection) and programming errors are not. Injected
    faults count as transient — the chaos contract is that KV flaps are
    absorbed by the retry ladder."""
    if isinstance(exc, _faults.FaultInjected):
        return True
    if isinstance(exc, urllib.error.HTTPError):
        return exc.code in _TRANSIENT_HTTP
    return isinstance(exc, (urllib.error.URLError, ConnectionError,
                            TimeoutError, socket.timeout,
                            http.client.HTTPException))


def make_secret() -> str:
    return os.urandom(16).hex()


def _sign(secret: str, method: str, path: str, payload: bytes) -> str:
    """Signature covers method + key path + payload, so a captured message
    can't be replayed against a different key or verb."""
    msg = method.encode() + b"\0" + path.encode() + b"\0" + payload
    return hmac.new(secret.encode(), msg, hashlib.sha256).hexdigest()


class KVHandler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence default stderr chatter
        pass

    def _key(self):
        return self.path.lstrip("/")

    def _verify(self, method: str, payload: bytes) -> bool:
        secret = self.server.secret  # type: ignore[attr-defined]
        if secret is None:
            return True
        sig = self.headers.get(_SIG_HEADER, "")
        return hmac.compare_digest(
            sig, _sign(secret, method, self.path, payload))

    def _reject(self):
        self.send_response(403)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        if self.path.rstrip("/") == "/metrics":
            # Prometheus exposition (docs/metrics.md): unsigned by
            # design — scrapers can't HMAC, and the payload is derived
            # telemetry (instrument samples), never KV values/secrets.
            # Serves THIS process's registry: for a loopback world every
            # rank's store (rank-labeled); for a real launcher the
            # driver-side view (workers serve their own on
            # HVD_METRICS_PORT).
            body = _metrics.prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if not self._verify("GET", b""):
            self._reject()
            return
        store = self.server.store  # type: ignore[attr-defined]
        key = self._key()
        if key.startswith("__gather__/"):
            self._gather(store, key)
            return
        with self.server.lock:  # type: ignore[attr-defined]
            if key.endswith("/") or key == "":  # scope listing
                scope = key.rstrip("/")
                prefix = scope + "/" if scope else ""
                keys = sorted(k for k in store if k.startswith(prefix))
                body = json.dumps(keys).encode()
            elif key in store:
                body = store[key]
            else:
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _gather(self, store, key):
        """Long-poll collect: ``__gather__/<scope>?count=N&timeout=S``
        blocks until N keys exist under scope, then returns them framed
        (sorted; u32 count, then per entry u32 klen + key + u32 vlen +
        value, then one f64 **server receipt time** per entry in the
        same order — a single clock for every member's PUT, which is
        what makes per-rank submit-lag attribution skew-free; old
        clients simply ignore the trailing section). Turns the engine
        transport's O(world) GET polls per cycle into one request per
        member (reference analog: the controller's single MPI_Gatherv,
        ``mpi_controller.cc:135-179``)."""
        import struct
        from urllib.parse import parse_qs, urlparse
        parsed = urlparse(key)
        scope = parsed.path[len("__gather__/"):].rstrip("/")
        q = parse_qs(parsed.query)
        count = int(q.get("count", ["1"])[0])
        timeout = min(float(q.get("timeout", ["30"])[0]), 60.0)
        prefix = scope + "/" if scope else ""
        # Blocked handler threads park on the store's condition variable and
        # are woken by do_PUT — no poll loop, no lock churn: one wakeup per
        # write instead of O(world) threads re-acquiring the lock ~500x/s.
        cond = self.server.lock  # type: ignore[attr-defined]
        times = self.server.times  # type: ignore[attr-defined]
        with cond:
            ready = cond.wait_for(
                lambda: sum(k.startswith(prefix) for k in store) >= count,
                timeout=timeout)
            if not ready:
                self.send_response(408)  # incomplete: client retries
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            keys = sorted(k for k in store if k.startswith(prefix))
            parts = [struct.pack("<I", len(keys))]
            for k in keys:
                kb = k.encode()
                v = store[k]
                parts.append(struct.pack("<I", len(kb)) + kb
                             + struct.pack("<I", len(v)) + v)
            parts.extend(struct.pack("<d", times.get(k, 0.0))
                         for k in keys)
            body = b"".join(parts)
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        payload = self.rfile.read(length)
        if not self._verify("PUT", payload):
            self._reject()
            return
        key = self._key()
        with self.server.lock:  # type: ignore[attr-defined]
            self.server.store[key] = payload  # type: ignore[attr-defined]
            # receipt time on the SERVER clock: one comparable clock for
            # every member's PUT (per-rank submit-lag attribution)
            self.server.times[key] = time.monotonic()  # type: ignore[attr-defined]
            self.server.lock.notify_all()  # wake parked gather handlers
        observer = getattr(self.server, "on_put", None)
        if observer is not None:
            try:
                observer(key, payload)
            except Exception:
                # Observer bugs must not break the store, but swallowing
                # them silently hid real protocol failures (a driver that
                # never learns a worker is ready looks like a hang).
                hvd_logging.exception(
                    "KV PUT observer failed for key %r", key)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):
        if not self._verify("DELETE", b""):
            self._reject()
            return
        key = self._key()
        with self.server.lock:  # type: ignore[attr-defined]
            store = self.server.store  # type: ignore[attr-defined]
            for k in [k for k in store
                      if k == key or k.startswith(key.rstrip("/") + "/")]:
                del store[k]
                self.server.times.pop(k, None)  # type: ignore[attr-defined]
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class _ThreadedHTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class KVServer:
    """In-memory scoped KV store served over HTTP (reference
    ``RendezvousServer``). Start on an ephemeral port; share
    ``addr``/``port``/``secret`` with workers via env."""

    def __init__(self, secret: str | None = None, on_put=None):
        self.secret = secret
        self.on_put = on_put  # callback(key, payload) for driver observers
        self._httpd: _ThreadedHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self, port: int = 0) -> int:
        self._httpd = _ThreadedHTTPServer(("0.0.0.0", port), KVHandler)
        self._httpd.store = {}  # type: ignore[attr-defined]
        self._httpd.times = {}  # type: ignore[attr-defined]  # key receipt times
        # Condition (not a bare Lock): gather long-polls park on it and
        # do_PUT wakes them, instead of each blocked handler polling.
        self._httpd.lock = threading.Condition()  # type: ignore[attr-defined]
        self._httpd.secret = self.secret  # type: ignore[attr-defined]
        self._httpd.on_put = self.on_put  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="hvd-kv-server")
        self._thread.start()
        return self.port

    @property
    def port(self) -> int:
        assert self._httpd is not None, "server not started"
        return self._httpd.server_address[1]

    def put(self, key: str, value: bytes) -> None:
        assert self._httpd is not None
        with self._httpd.lock:  # type: ignore[attr-defined]
            self._httpd.store[key] = value  # type: ignore[attr-defined]
            self._httpd.times[key] = time.monotonic()  # type: ignore[attr-defined]
            self._httpd.lock.notify_all()  # type: ignore[attr-defined]

    def get(self, key: str) -> bytes | None:
        assert self._httpd is not None
        with self._httpd.lock:  # type: ignore[attr-defined]
            return self._httpd.store.get(key)  # type: ignore[attr-defined]

    def keys(self, scope: str = "") -> list[str]:
        assert self._httpd is not None
        prefix = scope.rstrip("/") + "/" if scope else ""
        with self._httpd.lock:  # type: ignore[attr-defined]
            return sorted(k for k in self._httpd.store  # type: ignore[attr-defined]
                          if k.startswith(prefix))

    def delete(self, key: str) -> None:
        """Server-side mirror of ``do_DELETE``: drop ``key`` and every
        key under it as a scope (the driver's GC of per-round state —
        e.g. stale checkpoint shard hand-off keys at round publication)."""
        assert self._httpd is not None
        with self._httpd.lock:  # type: ignore[attr-defined]
            store = self._httpd.store  # type: ignore[attr-defined]
            for k in [k for k in store
                      if k == key or k.startswith(key.rstrip("/") + "/")]:
                del store[k]
                self._httpd.times.pop(k, None)  # type: ignore[attr-defined]

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class KVClient:
    """HTTP client for :class:`KVServer` (reference ``http_client.py``).

    The default per-request timeout honors ``HVD_GLOO_TIMEOUT_SECONDS``
    (the reference's transport-op timeout knob, ``common.h:120``): raise
    it on congested fabrics where a negotiation round can exceed 30 s.

    Every verb retries transient transport failures through the unified
    ``utils/retry.py`` ladder (``HVD_RETRY_*``; previously the first
    connection reset raised straight into the caller), and carries a
    fault-injection point named ``kv.<verb>`` — injected faults are
    retried exactly like real flaps (docs/robustness.md)."""

    def __init__(self, addr: str, port: int, secret: str | None = None,
                 timeout: float | None = None):
        from ..utils import envs
        self._base = f"http://{addr}:{port}"
        self._secret = secret
        self._timeout = timeout if timeout is not None else \
            envs.get_float(envs.GLOO_TIMEOUT_SECONDS, 30.0)

    def _request(self, method: str, path: str, payload: bytes = b"",
                 timeout: float | None = None):
        _faults.inject(f"kv.{method.lower()}")
        # One sample per server round trip (retries are separate trips);
        # divided by hvd_negotiation_rounds_total this is the protocol-
        # scalability "KV ops/round" curve (docs/metrics.md).
        _metrics.KV_OPS.inc(labels={
            "op": ("gather" if path.startswith("/__gather__/")
                   else method.lower())})
        req = urllib.request.Request(
            f"{self._base}{path}", data=payload if method == "PUT" else None,
            method=method)
        if self._secret is not None:
            req.add_header(_SIG_HEADER,
                           _sign(self._secret, method, path, payload))
        # Per-request timeout override, never instance mutation: one
        # client is shared between the engine cycle thread and the
        # health watchdog, so there is no safe place to write _timeout.
        return urllib.request.urlopen(
            req, timeout=self._timeout if timeout is None else timeout)

    def _read(self, method: str, path: str, payload: bytes = b"",
              timeout: float | None = None) -> bytes:
        # request AND body read inside one retry attempt: a connection
        # dying mid-read must retry the whole exchange, not surface a
        # short body
        with self._request(method, path, payload, timeout=timeout) as resp:
            return resp.read()

    def put(self, key: str, value: bytes) -> None:
        def attempt():
            with self._request("PUT", f"/{key}", value) as resp:
                if resp.status != 200:
                    raise RuntimeError(f"KV put {key}: HTTP {resp.status}")
        _retry.call(attempt, what="kv.put", retry_on=_transient_kv_error)

    def get(self, key: str) -> bytes | None:
        try:
            return _retry.call(lambda: self._read("GET", f"/{key}"),
                               what="kv.get", retry_on=_transient_kv_error)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def keys(self, scope: str = "") -> list[str]:
        return json.loads(_retry.call(
            lambda: self._read("GET", f"/{scope.rstrip('/')}/"),
            what="kv.keys", retry_on=_transient_kv_error))

    def delete(self, key: str) -> None:
        _retry.call(lambda: self._read("DELETE", f"/{key}"),
                    what="kv.delete", retry_on=_transient_kv_error)

    def wait(self, key: str, timeout: float = 60.0,
             poll_interval: float = 0.1) -> bytes:
        """Block until ``key`` appears (rendezvous barrier primitive).
        Paced by the retry helper's jittered backoff (base
        ``poll_interval`` growing toward 8x) instead of the old
        fixed-interval busy-poll — long rendezvous waits back off the
        server, and jitter decorrelates a fleet arriving at once."""
        val = self.get(key)
        if val is not None:
            return val
        for _ in _retry.poll_intervals("kv.wait", interval_s=poll_interval,
                                       deadline_s=timeout):
            val = self.get(key)
            if val is not None:
                return val
        raise TimeoutError(f"KV key {key!r} not set within {timeout}s")

    def gather(self, scope: str, count: int, timeout: float = 60.0,
               with_times: bool = False):
        """Collect ``count`` keys under ``scope`` in one server-side
        long-poll (server assembles; one HTTP round trip per call instead
        of one poll loop per key). Returns {key: value}; with
        ``with_times`` returns ``({key: value}, {key: server receipt
        seconds})`` — the server-clock PUT timestamps the negotiation
        transport turns into per-rank submit lags (older servers without
        the trailing section yield an empty times dict)."""
        import struct
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"KV gather {scope!r} did not reach {count} keys "
                    f"within {timeout}s")
            server_wait = max(min(remaining, 25.0), 0.05)
            path = (f"/__gather__/{scope.rstrip('/')}"
                    f"?count={count}&timeout={server_wait}")
            def attempt():
                return self._read("GET", path, timeout=server_wait + 10.0)

            try:
                # 408 is the long-poll's own "not yet" signal — the outer
                # loop re-issues it immediately; everything else transient
                # rides the backoff ladder within the remaining budget.
                data = _retry.call(
                    attempt, what="kv.gather",
                    retry_on=lambda e: (_transient_kv_error(e) and not (
                        isinstance(e, urllib.error.HTTPError)
                        and e.code == 408)),
                    deadline_s=remaining)
            except urllib.error.HTTPError as e:
                if e.code == 408:  # server-side wait expired; retry
                    continue
                raise
            out = {}
            pos = 4
            (n,) = struct.unpack_from("<I", data, 0)
            keys = []
            for _ in range(n):
                (klen,) = struct.unpack_from("<I", data, pos)
                pos += 4
                k = data[pos:pos + klen].decode()
                pos += klen
                (vlen,) = struct.unpack_from("<I", data, pos)
                pos += 4
                out[k] = data[pos:pos + vlen]
                pos += vlen
                keys.append(k)
            if not with_times:
                return out
            times = {}
            if len(data) - pos >= 8 * n:
                for k in keys:  # same order as the entry section
                    (t,) = struct.unpack_from("<d", data, pos)
                    pos += 8
                    times[k] = t
            return out, times


@functools.lru_cache(maxsize=1)
def _local_addresses_cached() -> tuple[str, ...]:
    """Routable addresses of this host (reference NIC discovery,
    ``driver_service.py:122-193``, radically simplified: on TPU pods the
    fabric is homogeneous so the default-route interface is correct)."""
    addrs = []
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))
            addrs.append(s.getsockname()[0])
        finally:
            s.close()
    except OSError:
        pass
    hostname_ip = None
    try:
        hostname_ip = socket.gethostbyname(socket.gethostname())
    except OSError:
        pass
    if hostname_ip and hostname_ip not in addrs:
        addrs.append(hostname_ip)
    if "127.0.0.1" not in addrs:
        addrs.append("127.0.0.1")
    return tuple(addrs)


def local_addresses() -> list[str]:
    return list(_local_addresses_cached())
