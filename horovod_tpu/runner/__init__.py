"""Launcher package: ``hvdrun`` CLI + programmatic ``run()``.

TPU-native rebuild of ``/root/reference/horovod/runner/`` (CLI at
``launch.py:739-775``, programmatic API at ``__init__.py:93-214``).
"""

from __future__ import annotations

import sys

from . import hosts as hosts_mod
from . import safe_exec
from .hosts import HostSpec, SlotInfo, get_host_assignments, parse_hosts, parse_hostfile
from .http_kv import KVClient, KVServer, local_addresses, make_secret
from .launch import (
    is_local_host,
    main,
    parse_args,
    run_commandline,
    run_static,
    spawn_worker,
    worker_env,
)


def run(fn, args=(), kwargs=None, np: int = 1, *, hosts: str | None = None,
        hostfile: str | None = None, env: dict | None = None,
        ssh_port: int | None = None, ssh_identity_file: str | None = None,
        verbose: bool = False, start_timeout: float = 600.0) -> list:
    """Run ``fn(*args, **kwargs)`` on ``np`` distributed workers and return
    the per-rank results, rank-ordered (reference ``horovod.run``,
    ``/root/reference/horovod/runner/__init__.py:93-214``)."""
    import cloudpickle

    from .launch import JobRendezvous, _resolve_hosts, _supervise

    kwargs = kwargs or {}
    ns = parse_args(["-np", str(np)] +
                    (["-H", hosts] if hosts else []) +
                    (["--hostfile", hostfile] if hostfile else []) +
                    (["--ssh-port", str(ssh_port)] if ssh_port else []) +
                    (["--ssh-identity-file", ssh_identity_file]
                     if ssh_identity_file else []) +
                    (["--verbose"] if verbose else []) +
                    ["--", "ignored"])
    specs = _resolve_hosts(ns)
    slots = get_host_assignments(specs, np)

    rdv = JobRendezvous(slots)
    rdv.kv.put("exec/fn", cloudpickle.dumps((fn, tuple(args), kwargs)))
    command = [sys.executable, "-m", "horovod_tpu.runner.task_exec"]

    procs = []
    try:
        for slot in slots:
            wenv = rdv.worker_env(
                slot, extra={**(env or {}),
                             "HVD_START_TIMEOUT": str(start_timeout)})
            procs.append(spawn_worker(slot, command, wenv, ns))
        # _supervise waits for all workers and tears the job down on the
        # first non-zero exit, so one dead rank can't hang the others
        # (start_timeout only bounds startup; healthy workers run unbounded).
        code = _supervise(procs, slots, ns)
        # Collect every rank's payload first: when _supervise tears the job
        # down on a mid-rank failure, earlier ranks may have no result — the
        # failing rank's stored traceback is the error worth surfacing.
        payloads = {slot.rank: rdv.kv.get(f"exec/result/{slot.rank}")
                    for slot in slots}
        decoded = {r: cloudpickle.loads(raw)
                   for r, raw in payloads.items() if raw is not None}
        for r in sorted(decoded):
            status, value = decoded[r]
            if status == "error":
                raise RuntimeError(f"rank {r} failed:\n{value}")
        missing = sorted(r for r, raw in payloads.items() if raw is None)
        if missing:
            raise RuntimeError(
                f"ranks {missing} produced no result (job exit code {code})")
        return [decoded[slot.rank][1] for slot in slots]
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        rdv.stop()


__all__ = [
    "HostSpec", "SlotInfo", "KVClient", "KVServer", "get_host_assignments",
    "hosts_mod", "is_local_host", "local_addresses", "main", "make_secret",
    "parse_args", "parse_hostfile", "parse_hosts", "run", "run_commandline",
    "run_static", "safe_exec", "spawn_worker", "worker_env",
]
