"""Launcher package: ``hvdrun`` CLI + programmatic ``run()``.

TPU-native rebuild of ``/root/reference/horovod/runner/`` (CLI at
``launch.py:739-775``, programmatic API at ``__init__.py:93-214``).
"""

from __future__ import annotations

import sys

import cloudpickle

from . import hosts as hosts_mod
from . import safe_exec
from .hosts import HostSpec, SlotInfo, get_host_assignments, parse_hosts, parse_hostfile
from .http_kv import KVClient, KVServer, local_addresses, make_secret
from .launch import (
    is_local_host,
    main,
    parse_args,
    run_commandline,
    run_static,
    spawn_worker,
    worker_env,
)


def run(fn, args=(), kwargs=None, np: int = 1, *, hosts: str | None = None,
        hostfile: str | None = None, env: dict | None = None,
        ssh_port: int | None = None, ssh_identity_file: str | None = None,
        verbose: bool = False, start_timeout: float = 600.0) -> list:
    """Run ``fn(*args, **kwargs)`` on ``np`` distributed workers and return
    the per-rank results, rank-ordered (reference ``horovod.run``,
    ``/root/reference/horovod/runner/__init__.py:93-214``)."""
    from .launch import _free_port, _resolve_hosts

    kwargs = kwargs or {}
    ns = parse_args(["-np", str(np)] +
                    (["-H", hosts] if hosts else []) +
                    (["--hostfile", hostfile] if hostfile else []) +
                    (["--ssh-port", str(ssh_port)] if ssh_port else []) +
                    (["--ssh-identity-file", ssh_identity_file]
                     if ssh_identity_file else []) +
                    (["--verbose"] if verbose else []) +
                    ["--", "ignored"])
    specs = _resolve_hosts(ns)
    slots = get_host_assignments(specs, np)

    secret = make_secret()
    kv = KVServer(secret=secret)
    kv_port = kv.start()
    kv.put("exec/fn", cloudpickle.dumps((fn, tuple(args), kwargs)))

    all_local = all(is_local_host(s.hostname) for s in slots)
    my_addr = "127.0.0.1" if all_local else local_addresses()[0]
    # jax.distributed coordinator binds inside rank 0's process, so it must
    # be addressed by rank 0's host (mirrors run_static).
    coord_host = slots[0].hostname
    coord_addr = "127.0.0.1" if all_local else (
        my_addr if is_local_host(coord_host) else coord_host)
    coord_port = _free_port()
    command = [sys.executable, "-m", "horovod_tpu.runner.task_exec"]

    procs = []
    try:
        for slot in slots:
            wenv = worker_env(
                slot, coordinator_addr=coord_addr, coordinator_port=coord_port,
                kv_addr=my_addr, kv_port=kv_port, secret=secret,
                extra={**(env or {}),
                       "HVD_START_TIMEOUT": str(start_timeout)})
            procs.append(spawn_worker(slot, command, wenv, ns))
        # start_timeout bounds job startup only; a healthy worker may run
        # indefinitely, so the overall wait is unbounded.
        codes = [p.wait() for p in procs]
        results = []
        for slot in slots:
            raw = kv.get(f"exec/result/{slot.rank}")
            if raw is None:
                raise RuntimeError(
                    f"rank {slot.rank} produced no result "
                    f"(exit code {codes[slot.rank]})")
            status, value = cloudpickle.loads(raw)
            if status == "error":
                raise RuntimeError(f"rank {slot.rank} failed:\n{value}")
            results.append(value)
        return results
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        kv.stop()


__all__ = [
    "HostSpec", "SlotInfo", "KVClient", "KVServer", "get_host_assignments",
    "hosts_mod", "is_local_host", "local_addresses", "main", "make_secret",
    "parse_args", "parse_hostfile", "parse_hosts", "run", "run_commandline",
    "run_static", "safe_exec", "spawn_worker", "worker_env",
]
