"""Host / slot parsing and rank assignment.

TPU-native rebuild of the reference's host machinery
(``/root/reference/horovod/runner/common/util/hosts.py`` and the host parsing
in ``/root/reference/horovod/runner/launch.py:242-528``): ``-H h1:4,h2:4``
style host lists, hostfiles, and the host-major contiguous rank layout that
the rest of the stack (local_rank / cross_rank) is derived from.

On TPU a "slot" is a controller process (one per host by default — a single
jax process drives every chip of its host), so typical TPU hostfiles use
``slots=1`` per host, but the assignment math supports any slot count.
"""

from __future__ import annotations

import dataclasses
import re


class HostParseError(ValueError):
    pass


@dataclasses.dataclass
class HostSpec:
    hostname: str
    slots: int

    def __post_init__(self):
        if self.slots < 1:
            raise HostParseError(
                f"host {self.hostname!r} has invalid slot count {self.slots}")


@dataclasses.dataclass
class SlotInfo:
    """One rank's placement (reference ``hosts.py`` SlotInfo): global rank,
    position within its host (local) and across hosts (cross)."""
    hostname: str
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int

    def to_response_string(self) -> str:
        return (f"{self.hostname},{self.rank},{self.size},{self.local_rank},"
                f"{self.local_size},{self.cross_rank},{self.cross_size}")


_HOST_RE = re.compile(r"^(?P<host>\[[^\]]+\]|[^:\s]+)(:(?P<slots>\d+))?$")


def parse_hosts(hosts_string: str) -> list[HostSpec]:
    """Parse ``"h1:4,h2:4"`` (reference ``parse_hosts``; slots default 1)."""
    specs = []
    for part in hosts_string.split(","):
        part = part.strip()
        if not part:
            continue
        m = _HOST_RE.match(part)
        if not m:
            raise HostParseError(f"invalid host specification: {part!r}")
        specs.append(HostSpec(m.group("host"),
                              int(m.group("slots") or 1)))
    if not specs:
        raise HostParseError(f"no hosts found in {hosts_string!r}")
    return specs


def parse_hostfile(path: str) -> list[HostSpec]:
    """Parse a hostfile with ``hostname slots=N`` lines (reference
    ``parse_host_files``, ``launch.py``). ``#`` comments allowed."""
    specs = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            fields = line.split()
            hostname = fields[0]
            slots = 1
            for field in fields[1:]:
                if field.startswith("slots="):
                    slots = int(field[len("slots="):])
                else:
                    raise HostParseError(
                        f"{path}:{lineno}: unrecognized field {field!r}")
            specs.append(HostSpec(hostname, slots))
    if not specs:
        raise HostParseError(f"hostfile {path} is empty")
    return specs


def total_slots(hosts: list[HostSpec]) -> int:
    return sum(h.slots for h in hosts)


def elastic_host_assignments(hosts: list[HostSpec], min_np: int,
                             max_np: int | None) -> list[SlotInfo]:
    """Elastic assignment (reference ``get_host_assignments(host_list,
    min_np, max_np)``): use every available slot up to ``max_np``; raise when
    fewer than ``min_np`` slots exist."""
    capacity = total_slots(hosts)
    if capacity < min_np:
        raise ValueError(
            f"only {capacity} slots available across {len(hosts)} hosts, "
            f"fewer than the required minimum {min_np}")
    np = capacity if max_np is None else min(capacity, max_np)
    return get_host_assignments(hosts, np)


def get_host_assignments(hosts: list[HostSpec], np: int) -> list[SlotInfo]:
    """Assign ``np`` ranks to hosts, host-major and contiguous (reference
    ``get_host_assignments``, ``hosts.py``): rank r lands on the first host
    with a free slot; local_rank counts within the host; cross_rank indexes
    the host among hosts that own a slot at the same local_rank.
    """
    if np < 1:
        raise ValueError(f"np must be positive, got {np}")
    capacity = total_slots(hosts)
    if np > capacity:
        raise ValueError(
            f"requested np={np} exceeds total available slots {capacity} "
            f"across {len(hosts)} hosts")

    # slots actually used per host, host-major fill
    used: list[int] = []
    remaining = np
    for h in hosts:
        take = min(h.slots, remaining)
        used.append(take)
        remaining -= take
    hosts_used = [(h, u) for h, u in zip(hosts, used) if u > 0]

    assignments: list[SlotInfo] = []
    rank = 0
    for host_idx, (h, u) in enumerate(hosts_used):
        for local_rank in range(u):
            cross_rank = sum(1 for _, u2 in hosts_used[:host_idx]
                             if u2 > local_rank)
            cross_size = sum(1 for _, u2 in hosts_used if u2 > local_rank)
            assignments.append(SlotInfo(
                hostname=h.hostname, rank=rank, size=np,
                local_rank=local_rank, local_size=u,
                cross_rank=cross_rank, cross_size=cross_size))
            rank += 1
    return assignments


def slots_from_ips(ips: list) -> list[SlotInfo]:
    """Rank assignment from an already-placed worker list (one IP per
    rank, rank = list position): local ranks/sizes derive from colocation,
    cross ranks from host order of first appearance. Shared by the Ray and
    Spark integrations, where the cluster scheduler (not the launcher)
    decided placement."""
    n = len(ips)
    host_order: list = []
    local_counts: dict = {}
    for ip in ips:
        if ip not in local_counts:
            local_counts[ip] = 0
            host_order.append(ip)
        local_counts[ip] += 1
    seen: dict = {ip: 0 for ip in local_counts}
    slots = []
    for rank, ip in enumerate(ips):
        slots.append(SlotInfo(
            hostname=ip, rank=rank, size=n,
            local_rank=seen[ip], local_size=local_counts[ip],
            cross_rank=host_order.index(ip), cross_size=len(host_order)))
        seen[ip] += 1
    return slots
