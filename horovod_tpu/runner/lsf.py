"""LSF / jsrun allocation detection for ``hvdrun``.

TPU-native parity with the reference's LSF integration
(``/root/reference/horovod/runner/util/lsf.py:1-103`` and
``/root/reference/horovod/runner/js_run.py:1-151``): when ``hvdrun`` runs
inside an LSF allocation without explicit ``-H``/``--hostfile``, the host
list comes from the allocation's own environment. The reference queries
IBM CSM binaries for the node list; those are machine-local daemons with
no TPU-pod analog, so here the (documented, portable) LSF env surface is
the source of truth:

* ``LSB_DJOB_RANKFILE`` — file with one hostname per allocated slot
  (repeats = slots per host), written by LSF for every distributed job;
* ``LSB_MCPU_HOSTS`` — ``"host1 n1 host2 n2 ..."`` pairs, the fallback;
* ``LSB_HOSTS`` — ``"host1 host1 host2 ..."`` one name per slot, last
  resort.

In-task rank detection for ``jsrun``-launched processes (JSM sets
``JSM_NAMESPACE_RANK``/``JSM_NAMESPACE_SIZE``) lives in
``horovod_tpu.runtime._CLUSTER_ENV_PAIRS``.
"""

from __future__ import annotations

import collections
import os

from . import hosts as hosts_mod
from ..utils import logging as hvd_logging


def using_lsf() -> bool:
    """True when the current process runs inside an LSF job (the
    reference's ``LSFUtils.using_lsf``: ``LSB_JOBID`` present)."""
    return "LSB_JOBID" in os.environ


def _drop_launch_nodes(names: list[str]) -> list[str]:
    """Summit-style LSF allocations list the *launch* (batch) node ahead of
    the compute nodes in LSB_DJOB_RANKFILE / LSB_MCPU_HOSTS; jsrun never
    places a rank there, and the reference avoids it by asking CSM for
    ``compute_nodes`` only. CSM has no analog here, so filter by the
    documented naming convention (``batch*``/``login*``) — only when
    compute hosts remain, so single-host jobs keep working. Escape hatch:
    pass ``-H``/``--hostfile`` explicitly."""
    kept = [n for n in names
            if not n.lower().startswith(("batch", "login"))]
    if kept and len(kept) < len(names):
        # a cluster naming real compute hosts batch*/login* would be
        # silently shrunk here — say exactly what was filtered so a
        # mis-filtered allocation is visible (escape hatch: -H/--hostfile)
        dropped = sorted({n for n in names if n not in set(kept)})
        hvd_logging.info(
            "LSF allocation: dropping launch node(s) %s (batch*/login* "
            "prefix); %d compute host(s) remain. Pass -H/--hostfile if "
            "these are real compute hosts.", ", ".join(dropped),
            len(set(kept)))
    return kept if kept else names


def _specs_from_slot_hostnames(names: list[str]) -> list[hosts_mod.HostSpec]:
    """One hostname per slot, repeats meaning multiple slots; order of
    first appearance is preserved so rank 0 lands on the first host."""
    names = _drop_launch_nodes(names)
    counts = collections.Counter(names)
    seen: list[str] = []
    for n in names:
        if n not in seen:
            seen.append(n)
    return [hosts_mod.HostSpec(h, counts[h]) for h in seen]


def lsf_host_specs() -> list[hosts_mod.HostSpec]:
    """Host/slot specs for the current LSF allocation.

    Raises ``RuntimeError`` when no usable LSF host information is
    present (caller decides whether that is fatal: it is under
    ``--launcher lsf``, not under ``--launcher auto``).
    """
    rankfile = os.environ.get("LSB_DJOB_RANKFILE")
    if rankfile and os.path.exists(rankfile):
        with open(rankfile) as f:
            names = [line.strip() for line in f if line.strip()]
        if names:
            return _specs_from_slot_hostnames(names)
    mcpu = os.environ.get("LSB_MCPU_HOSTS")
    if mcpu:
        toks = mcpu.split()
        if len(toks) % 2 == 0 and toks:
            try:
                specs = [hosts_mod.HostSpec(toks[i], int(toks[i + 1]))
                         for i in range(0, len(toks), 2)]
                kept = set(_drop_launch_nodes([s.hostname for s in specs]))
                return [s for s in specs if s.hostname in kept]
            except ValueError:
                pass
    hosts = os.environ.get("LSB_HOSTS")
    if hosts and hosts.split():
        return _specs_from_slot_hostnames(hosts.split())
    raise RuntimeError(
        "LSF job detected (LSB_JOBID set) but none of LSB_DJOB_RANKFILE / "
        "LSB_MCPU_HOSTS / LSB_HOSTS yields a host list; pass -H/--hostfile "
        "explicitly")
