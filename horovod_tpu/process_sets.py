"""Process sets: collectives over subsets of ranks.

TPU-native equivalent of the reference's ``ProcessSet``/``ProcessSetTable``
(``/root/reference/horovod/common/process_set.h:26-171``) and the Python API
(``/root/reference/horovod/common/process_sets.py``). A process set maps to

* a **sub-mesh** over its member chips (eager path — XLA emits ICI-local
  collectives for the subset), and
* an ``axis_index_groups`` partition of the global mesh axis (traced path —
  every chip participates in the SPMD program; non-members reduce within
  singleton groups, mirroring how non-member ranks simply don't contribute).

Dynamic registration/removal mirrors ``process_set.h:89-171`` (ids with a
free-list; gated on ``HVD_DYNAMIC_PROCESS_SETS`` like the reference gates on
``HOROVOD_DYNAMIC_PROCESS_SETS``, ``operations.cc:606-607``).
"""

from __future__ import annotations

import threading
from typing import Sequence

import numpy as np
from jax.sharding import Mesh

from . import runtime


class ProcessSet:
    """A subset of global ranks over which collectives run.

    Mirrors ``horovod.ProcessSet`` (``process_sets.py:20-80``): created with
    a rank list, bound to an id once registered.
    """

    def __init__(self, ranks: Sequence[int] | None = None):
        self.process_set_id: int | None = None
        self._ranks: list[int] | None = sorted(ranks) if ranks is not None else None
        self._mesh: Mesh | None = None
        self._mesh_generation: int = -1

    # -- identity ----------------------------------------------------------
    @property
    def ranks(self) -> list[int]:
        if self._ranks is None:
            return list(range(runtime.size()))
        return list(self._ranks)

    def size(self) -> int:
        return len(self.ranks)

    def included(self, global_rank: int | None = None) -> bool:
        """Whether ``global_rank`` (default: this process's representative
        rank) belongs to the set (reference ``process_set.included()``)."""
        r = runtime.rank() if global_rank is None else global_rank
        return r in set(self.ranks)

    def rank(self, global_rank: int | None = None) -> int:
        """Rank *within* the set of a global rank (−1 if not included)."""
        r = runtime.rank() if global_rank is None else global_rank
        try:
            return self.ranks.index(r)
        except ValueError:
            return -1

    # -- mesh machinery ----------------------------------------------------
    @property
    def is_global(self) -> bool:
        return self.size() == runtime.size() and self.ranks == list(range(runtime.size()))

    def mesh(self) -> Mesh:
        """Sub-mesh over member chips, axis name == global axis name.
        Cached per runtime generation so a set held across
        shutdown()/init() never runs over stale device objects."""
        if self.is_global:
            return runtime.mesh()
        gen = runtime.generation()
        if self._mesh is None or self._mesh_generation != gen:
            devs = runtime.devices()
            members = [devs[r] for r in self.ranks]
            self._mesh = Mesh(np.array(members), (runtime.axis_name(),))
            self._mesh_generation = gen
        return self._mesh

    def dispatch_key(self):
        """Stable hashable identity for dispatch-plan cache keys: the
        registered id (unique while registered — removal flushes the plan
        cache, so a free-listed id can never serve a stale plan), the rank
        tuple for unregistered subsets, or "g" for an unregistered
        global-view set."""
        if self.process_set_id is not None:
            return self.process_set_id
        return tuple(self._ranks) if self._ranks is not None else "g"

    def axis_index_groups(self) -> list[list[int]] | None:
        """Partition of the global axis for traced-mode collectives.

        Members form one group; every non-member is a singleton group (the
        partition must cover the axis). ``None`` for the global set (lets
        XLA use the plain collective).
        """
        if self.is_global:
            return None
        member = set(self.ranks)
        groups = [list(self.ranks)]
        groups.extend([r] for r in range(runtime.size()) if r not in member)
        return groups

    def __repr__(self) -> str:
        return f"ProcessSet(id={self.process_set_id}, ranks={self.ranks})"


class ProcessSetTable:
    """Id-keyed registry with a free-list, mirroring
    ``ProcessSetTable`` (``process_set.h:89-171``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._table: dict[int, ProcessSet] = {}
        self._next_id = 0
        self._free_ids: list[int] = []
        self.dynamic_enabled = False

    def initialize_global(self, world_size: int) -> ProcessSet:
        ps = ProcessSet(list(range(world_size)))
        ps.process_set_id = 0
        with self._lock:
            self._table[0] = ps
            self._next_id = 1
        return ps

    def add(self, ranks: Sequence[int], force: bool = False) -> ProcessSet:
        if not force and not self.dynamic_enabled:
            raise RuntimeError(
                "Dynamic process sets are disabled; set HVD_DYNAMIC_PROCESS_SETS=1 "
                "or pass process_sets to hvd.init() (reference gates identically, "
                "operations.cc:606-607).")
        ranks = sorted(set(ranks))
        world = runtime.size()
        for r in ranks:
            if not 0 <= r < world:
                raise ValueError(f"rank {r} out of range [0, {world})")
        with self._lock:
            for ps in self._table.values():
                if ps.ranks == list(ranks):
                    return ps  # reference dedups identical sets
            ps = ProcessSet(ranks)
            if self._free_ids:
                ps.process_set_id = self._free_ids.pop(0)
            else:
                ps.process_set_id = self._next_id
                self._next_id += 1
            self._table[ps.process_set_id] = ps
            return ps

    def remove(self, ps: ProcessSet) -> None:
        if ps.process_set_id in (None, 0):
            raise ValueError("cannot remove the global process set (id 0)")
        with self._lock:
            if ps.process_set_id in self._table:
                del self._table[ps.process_set_id]
                self._free_ids.append(ps.process_set_id)
                self._free_ids.sort()
            ps.process_set_id = None

    def get(self, ps_id: int) -> ProcessSet:
        with self._lock:
            return self._table[ps_id]

    def ids(self) -> list[int]:
        with self._lock:
            return sorted(self._table)


# --- module-level parity API (process_sets.py in the reference) -----------

#: The always-present set of all ranks (id 0).
global_process_set = ProcessSet()
global_process_set.process_set_id = 0


def _resolve(process_set: ProcessSet | None) -> ProcessSet:
    return global_process_set if process_set is None else process_set


def add_process_set(process_set: ProcessSet | Sequence[int]) -> ProcessSet:
    """Register a new process set (reference ``add_process_set``,
    ``process_sets.py:95-130``)."""
    if not isinstance(process_set, ProcessSet):
        process_set = ProcessSet(list(process_set))
    registered = runtime.process_set_table().add(process_set.ranks)
    process_set.process_set_id = registered.process_set_id
    return registered


def remove_process_set(process_set: ProcessSet) -> None:
    runtime.process_set_table().remove(process_set)
    # The freed id may be reissued to a different rank list; drop every
    # dispatch plan rather than risk one keyed on the stale id serving.
    from .ops import dispatch_cache
    dispatch_cache.invalidate("process set removed")
