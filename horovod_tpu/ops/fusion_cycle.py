"""Cycle-driven cross-call fusion scheduler for eager async collectives.

The TPU-native rebuild of the reference's headline performance mechanism:
not the collective itself but the background cycle that coalesces
independently-submitted small tensors into large fusion buffers
(``operations.cc:385-806``: the coordinator negotiates readiness, fuses
ready tensors into buffers bounded by ``HOROVOD_FUSION_THRESHOLD``, and
flushes every ``HOROVOD_CYCLE_TIME``). Before this module, every
``*_async`` call dispatched its own collective synchronously — a
per-parameter eager loop over 100 small gradients paid 100 negotiations
and 100 wire launches.

Here, ``allreduce_async`` / ``broadcast_async`` / ``allgather_async`` /
``grouped_allreduce_async`` / ``sparse_allreduce_async`` enqueue into
**per-signature pending queues** instead of dispatching immediately. A
queue is keyed like the dispatch plan cache: op kind / process set /
reduce op / pre+post scales / hierarchical flag / wire dtype (the
compression class), so everything in one queue is legal to fuse into one
grouped dispatch. A flush fires when

* pending bytes in a queue reach ``HVD_FUSION_THRESHOLD`` (trigger
  ``threshold``),
* ``HVD_CYCLE_TIME`` elapses on the queue's oldest entry — or
  ``HVD_PENDING_CYCLE_TIME`` while work is in flight (trigger ``cycle``;
  a dispatch keeps the scheduler "in flight" for one cycle window),
* total pending bytes across all queues exceed ``HVD_FUSION_MAX_PENDING``
  (backpressure; trigger ``backpressure``),
* the user observes a handle: ``Handle.poll()`` / ``Handle.synchronize()``
  (triggers ``poll`` / ``synchronize``), or
* a synchronization point drains everything: ``hvd.barrier()`` (trigger
  ``barrier``) or ``hvd.shutdown()`` (trigger ``shutdown``).

A flush coalesces the queue into ONE grouped dispatch through the
existing dispatch plan cache (``ops/dispatch_cache.py``) — steady-state
training loops therefore pay one plan hit per flush instead of one full
dispatch per parameter.

Determinism contract (the reference coordinator's role): flush
*composition* must be identical on every rank. Composition derives from
submission order and deterministic negotiation names only — never from
wall-clock:

* **Single-controller jobs** (one process drives every chip — the normal
  SPMD deployment): the one process's queue IS the global view, so any
  flush trigger yields a rank-consistent composition by construction.
* **Multi-process jobs** (a negotiation service is running): each entry
  is assigned a deterministic negotiation name at *submission* time
  (per-set counters, identical across processes running the same
  program). A flush batches the drained entries' negotiations into one
  ``negotiate_many`` round (one KV cycle for the whole flush — the
  queue's multi-process win) but keeps each entry's *program composition*
  exactly as submitted: singles stay single programs, grouped entries
  stay their group. That mirrors the active-path programs a joined rank
  reconstructs from response metadata (``_execute_joined_zeros``), so
  composition can never diverge across processes — timer jitter on one
  process only changes *when* entries negotiate, never *what* program
  runs.

**Pipelined flush executor** (``HVD_MAX_INFLIGHT_FLUSHES``, default 2):
flush triggers only *drain* a queue and hand the entry batch to a
dedicated dispatch thread with a bounded in-flight window, so flush k+1's
host-side fuse (and, in multi-process jobs, its ``negotiate_many`` round,
submitted at the trigger point via the split
:meth:`~horovod_tpu.engine_service.DynamicService.negotiate_many_submit`)
overlaps flush k's in-flight device collective instead of serializing
against the triggering thread's enqueues. The executor is deliberately a
SINGLE thread consuming a FIFO queue: slot admission order derives from
submission order only (never completion timing), which preserves
per-signature FIFO result order, the PR-2 rank-deterministic composition
contract, and — critically — a serial collective program issue order
(two threads interleaving the per-device enqueues of two collectives
deadlock the backend rendezvous; see ``ops/program_issue.py``). The
slots bound how many dispatched flushes may be device-incomplete at
once: admitting a batch past the window first blocks on the oldest
in-flight flush (GIL released — producers keep enqueueing).
``HVD_MAX_INFLIGHT_FLUSHES=0/1`` restores the synchronous
execute-on-the-triggering-thread behavior byte-for-byte. Fused wire
buffers past ``HVD_PIPELINE_THRESHOLD`` additionally dispatch as
``HVD_PIPELINE_CHUNKS`` chunk programs (``collectives._chunk_layout``,
docs/pipeline.md).

**Multi-tenant QoS** (``HVD_QOS=1``; ``horovod_tpu/qos.py``,
docs/qos.md): batches route through a strict-priority + deficit-round-
robin admission gate in front of the executor FIFO instead of being
appended directly — per-process-set tenants get priority tiers, byte-
weighted fair shares of the executor slots, and pending-bytes quotas
(``block`` backpressure at enqueue / ``shed`` with a typed
``QosAdmissionError`` on the handle). Grant order stays a pure function
of submission order + static QoS config (window pumps and handle-
observation releases at rank-deterministic program points; executor-
demand grants for single-controller batches only), so the composition
contract above survives tenancy. ``HVD_QOS=0`` (default) keeps this
whole path byte-for-byte.

Statistics surface through :func:`stats` (exported as
``hvd.fusion_stats()``; the ``pipeline`` block carries slot occupancy and
overlap ratio); the timeline gains ``QUEUE_ENQUEUE``, ``CYCLE_FLUSH``,
and ``INFLIGHT_DEPTH`` instant events plus ``PIPELINE_*`` stage spans.
The scheduler's off switch is ``HVD_CYCLE_TIME=0`` (immediate dispatch,
the pre-queue behavior).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

import jax.numpy as jnp
import numpy as np

from .. import autotune as _autotune
from .. import conformance as _conformance
from .. import metrics as _metrics
from .. import qos as _qos
from .. import timeline as _timeline
from ..utils import envs
from ..utils import faults as _faults
from ..utils import invariants as _inv
from ..utils import logging as hvd_logging
from . import dispatch_cache as _dispatch_cache
from . import step_capture as _step_capture

FLUSH_TRIGGERS = ("threshold", "cycle", "synchronize", "poll", "barrier",
                  "join", "shutdown", "backpressure", "name-reuse",
                  "bucket")

# In-flight window multiplier: after a dispatch the scheduler flushes at
# the PENDING_CYCLE_TIME pace for one cycle window (see _age_limit_s).
_INFLIGHT_WINDOW_CYCLES = 1.0


# Bound registry series for the enqueue/flush hot paths: label
# resolution paid once per (tenant, trigger), after which a sample is a
# dict update under the registry's leaf lock (docs/metrics.md overhead
# contract; benign rebind race under the GIL).
_PENDING_BYTES_G = _metrics.FUSION_PENDING_BYTES.bind()
_INFLIGHT_DEPTH_G = _metrics.PIPELINE_INFLIGHT_DEPTH.bind()
_tenant_series: dict = {}


def _tenant_metrics(tenant: str) -> dict:
    t = _tenant_series.get(tenant)
    if t is None:
        t = _tenant_series[tenant] = {
            "enqueued": _metrics.FUSION_ENQUEUED_TENSORS.bind(
                {"process_set": tenant}),
            "tensors": _metrics.FUSION_FLUSHED_TENSORS.bind(
                {"process_set": tenant}),
            "bytes": _metrics.FUSION_FLUSHED_BYTES.bind(
                {"process_set": tenant}),
            "flushes": {},  # trigger -> bound counter
        }
    return t


def _flush_counter(tm: dict, tenant: str, trigger: str):
    c = tm["flushes"].get(trigger)
    if c is None:
        c = tm["flushes"][trigger] = _metrics.FUSION_FLUSHES.bind(
            {"process_set": tenant, "trigger": trigger})
    return c


def _pset_label(pset) -> str:
    """Tenant label for the registry's per-process-set fusion counters
    AND the QoS class registry: the one derivation lives in
    ``qos.tenant_label`` (``engine_service._set_key`` with the global
    set spelled ``"global"``), so fusion counters, negotiation
    instruments, and QoS classes can never drift apart on a tenant's
    identity."""
    return _qos.tenant_label(pset)


def _qos_tenant_counter(tenant: str, kind: str):
    """Bound per-tenant QoS counter (``shed`` / ``blocks``), cached in
    the same per-tenant series map as the fusion counters."""
    tm = _tenant_metrics(tenant)
    c = tm.get("qos_" + kind)
    if c is None:
        inst = (_metrics.QOS_SHED if kind == "shed"
                else _metrics.QOS_QUOTA_BLOCKS)
        c = tm["qos_" + kind] = inst.bind({"process_set": tenant})
    return c


def enabled() -> bool:
    """The scheduler queues async ops whenever ``HVD_CYCLE_TIME`` > 0.
    ``HVD_CYCLE_TIME=0`` restores immediate per-call dispatch (the
    reference's cycle likewise stops coalescing at a zero cycle time)."""
    return envs.cycle_time_ms() > 0.0


def max_pending_bytes() -> int:
    """Backpressure cap on total queued bytes across all queues
    (``HVD_FUSION_MAX_PENDING``; default 4x the fusion threshold)."""
    return envs.get_int(envs.FUSION_MAX_PENDING,
                        4 * envs.fusion_threshold_bytes())


def pending_cycle_time_ms() -> float:
    """Flush pace while work is in flight (``HVD_PENDING_CYCLE_TIME``;
    default: half the cycle time, capped at 2 ms like the engine
    service's transport floor)."""
    cycle = envs.cycle_time_ms()
    return envs.get_float(envs.PENDING_CYCLE_TIME, min(cycle / 2.0, 2.0))


class _QueueSpec:
    """Immutable per-queue dispatch parameters, captured at first
    enqueue. ``kind`` is one of allreduce/broadcast/allgather/sparse."""

    __slots__ = ("kind", "pset", "axis", "op", "pre", "post", "root_rank",
                 "compression", "svc")

    def __init__(self, kind, pset, axis, op=None, pre=1.0, post=1.0,
                 root_rank=-1, compression=None, svc=None):
        self.kind = kind
        self.pset = pset
        self.axis = axis
        self.op = op
        self.pre = pre
        self.post = post
        self.root_rank = root_rank
        self.compression = compression
        self.svc = svc


class _Entry:
    """One queued ``*_async`` submission: a single tensor or an atomic
    group (grouped entries never split across flushes). ``requests`` are
    the pre-built negotiation dicts (multi-process jobs only; names
    assigned at submission time so every process generates the same
    sequence). ``run`` is the opaque executor for sparse entries."""

    __slots__ = ("tensors", "count", "grouped", "nbytes", "names",
                 "requests", "run", "queue_key", "label", "event",
                 "results", "error", "sigs", "captured", "qos_tenant",
                 "qos_acked", "qos_inflight", "qos_epoch")

    def __init__(self, tensors, grouped, nbytes, names, requests=(),
                 run=None, label=""):
        self.tensors = tensors
        self.count = len(tensors)
        self.grouped = grouped
        self.nbytes = nbytes
        self.names = tuple(names)
        self.requests = tuple(requests)
        self.run = run
        self.queue_key = None
        self.label = label or (names[0] if names else "queued")
        self.event = _inv.make_event("fusion_cycle.entry")
        self.results = None
        self.error = None
        # normalized per-tensor plan signatures (step capture templates);
        # None = unplannable entry (opaque/sparse), never capturable
        self.sigs = None
        self.captured = False  # held by a step-capture replay
        # multi-tenant QoS accounting (docs/qos.md): the entry's tenant
        # label, whether its unacked bytes were released (synchronize
        # return), whether it currently charges granted-but-unsettled
        # bytes (set at executor admission, cleared at settle), and the
        # scheduler quota epoch it was charged under — abort() bumps
        # the epoch when it zeroes the accounting, so a stale ack or
        # settle from a pre-abort entry can never deflate charges made
        # by post-abort submissions
        self.qos_tenant = None
        self.qos_acked = False
        self.qos_inflight = False
        self.qos_epoch = 0

    @property
    def done(self) -> bool:
        return self.event.is_set()


class _Queue:
    __slots__ = ("spec", "entries", "nbytes", "oldest_t", "names")

    def __init__(self, spec):
        self.spec = spec
        self.entries: list[_Entry] = []
        self.nbytes = 0
        self.oldest_t = 0.0
        self.names: set = set()  # pending negotiation names (O(1) clash check)


class _Batch:
    """One drained flush handed to the pipelined executor: the queue's
    spec, its entries in submission order, the trigger that drained it,
    and — for multi-process queues — the negotiation ticket submitted at
    the (rank-deterministic) trigger point so the KV round overlaps
    earlier in-flight flushes."""

    __slots__ = ("spec", "entries", "trigger", "ticket")

    def __init__(self, spec, entries, trigger, ticket=None):
        self.spec = spec
        self.entries = entries
        self.trigger = trigger
        self.ticket = ticket


class FusionScheduler:
    """Owns the pending queues, the cycle timer thread, and the flush
    statistics. Normally a process-wide singleton (:func:`scheduler`);
    tests instantiate fresh ones to check composition determinism."""

    def __init__(self):
        self._mu = _inv.make_lock("fusion_cycle.scheduler.mu")
        self._queues: "OrderedDict[tuple, _Queue]" = OrderedDict()
        self._pending_tensors = 0
        self._pending_bytes = 0
        self._wake = _inv.make_event("fusion_cycle.scheduler.wake")
        self._stop = _inv.make_event("fusion_cycle.scheduler.stop")
        self._thread: threading.Thread | None = None
        self._inflight_until = 0.0
        self._stats = {
            "enqueued_tensors": 0,
            "enqueued_bytes": 0,
            "flushed_tensors": 0,
            "flushed_bytes": 0,
            "dispatches": 0,
            "wire_programs": 0,
            "flushes": {t: 0 for t in FLUSH_TRIGGERS},
        }
        # (trigger, queue key, entry names) per flush — the composition
        # record the determinism tests compare across schedulers.
        self.flush_history: deque = deque(maxlen=64)
        # -- pipelined flush executor state (see _exec_loop) --
        self._exec_cv = _inv.make_condition("fusion_cycle.scheduler.exec_cv")
        self._exec_q: "deque[_Batch]" = deque()
        self._exec_busy = False
        self._exec_stop = False
        self._exec_thread: threading.Thread | None = None
        self._exec_inflight: deque = deque()  # result leaves per batch
        self._exec_names: set = set()  # svc names submitted, not yet done
        self._pstats = {
            "submitted": 0, "executed": 0, "overlapped": 0,
            "depth_sum": 0, "inflight_peak": 0, "slot_waits": 0,
            "device_wait_ms": 0.0,
        }
        # -- multi-tenant QoS state (qos.py; all guarded by _exec_cv) --
        # admission gate (lazy: created at the first submission with
        # HVD_QOS=1), per-tenant unacknowledged bytes (enqueue ->
        # synchronize return; the rank-deterministic shed measure) and
        # granted-but-unsettled bytes (executor admission -> settle;
        # the block-policy backpressure measure)
        self._qos_gate = None
        self._qos_unacked: dict[str, float] = {}
        self._qos_inflight: dict[str, float] = {}
        self._qos_epoch = 0  # bumped by abort(); guards stale releases
        self._qos_stats = {"shed": {}, "quota_blocks": 0}
        # step capture-and-replay controller (HVD_STEP_CAPTURE;
        # ops/step_capture.py): records the marked step's flush stream,
        # then replays the whole step as one cached program
        self.capture = _step_capture.CaptureState(self)

    # -- enqueue -----------------------------------------------------------

    def enqueue(self, key: tuple, spec: _QueueSpec, entry: _Entry) -> None:
        # A flush execution must never re-enter the scheduler: on the
        # synchronous path it would self-deadlock on _mu, on the pipelined
        # path it would corrupt flush composition mid-drain.
        _inv.assert_outside("fusion-cycle-flush", "FusionScheduler.enqueue")
        entry.queue_key = key
        # Step replay intake: a submission matching the armed captured
        # stream is HELD for the whole-step program instead of queued;
        # a mismatch falls back to eager transparently (offer returns
        # False and the entry takes the normal path below).
        if self.capture.offer(key, spec, entry):
            return
        if _qos.enabled():
            tenant = _pset_label(spec.pset)
            entry.qos_tenant = tenant
            cls = _qos.get_class(tenant)
            if not self._qos_admit(entry, tenant, cls):
                return  # shed: the handle raises QosAdmissionError
        if entry.requests:
            # Multi-process entries negotiate the whole flush in ONE
            # negotiate_many batch, whose duplicate-name guard only spans
            # batches — a user-named submission repeating a name already
            # pending in the same queue would silently orphan the first
            # request and stall the flush. Flush the queue first so the
            # two negotiations stay sequential, like immediate dispatch.
            # With the pipelined executor the earlier submission's
            # negotiation may also still be in flight downstream of its
            # flush — quiesce the pipeline before reusing the name.
            with self._mu:
                q = self._queues.get(key)
                clash = q is not None and not q.names.isdisjoint(entry.names)
            with self._exec_cv:
                exec_clash = not self._exec_names.isdisjoint(entry.names)
            if clash:
                self.flush_queue(key, "name-reuse")
            if clash or exec_clash:
                # A clashing batch may be parked in the QoS admission
                # gate (names register at drain, before the grant):
                # force-grant it, or the wait below parks forever.
                gate = self._qos_gate
                if gate is not None:
                    gate.release_names(entry.names)
                # Wait for the clashing names specifically (not just an
                # executor quiesce): the earlier flush may still be
                # between its _mu-side name registration and its batch
                # submission, where the executor queue looks idle.
                self._wait_names_clear(entry.names)
        with self._mu:
            _inv.assert_holding(self._mu, "pending-queue mutation (enqueue)")
            q = self._queues.get(key)
            if q is None:
                q = _Queue(spec)
                q.oldest_t = _inv.monotonic()
                self._queues[key] = q
            q.entries.append(entry)
            q.names.update(entry.names)
            q.nbytes += entry.nbytes
            self._pending_tensors += entry.count
            self._pending_bytes += entry.nbytes
            self._stats["enqueued_tensors"] += entry.count
            self._stats["enqueued_bytes"] += entry.nbytes
            pending_bytes = self._pending_bytes
            over_threshold = q.nbytes >= envs.fusion_threshold_bytes()
            over_pending = self._pending_bytes >= max_pending_bytes()
            self._ensure_thread_locked()
        _tenant_metrics(_pset_label(spec.pset))["enqueued"].inc(entry.count)
        _PENDING_BYTES_G.set(pending_bytes)
        for name in entry.names:
            _timeline.record_queue_enqueue(name or entry.label)
        self._wake.set()
        if over_pending:
            if _qos.enabled() and entry.qos_tenant is not None and \
                    _qos.get_class(entry.qos_tenant).quota > 0:
                # QoS backpressure for a QUOTA'D tenant: drain back
                # under the cap (LOWEST tier first — the bulk backlog
                # is what moves out; latency tenants' queues drain at
                # their own synchronize) WITHOUT the flush_all
                # gate-release + quiesce — quiescing would block THIS
                # producer (possibly a latency tenant) on the whole
                # bulk backlog's execution, the exact inversion QoS
                # exists to prevent. The producer's memory stays
                # bounded by its OWN quota instead of by the stall. A
                # tenant with quota=0 (unlimited) has opted out of
                # that bound, so it keeps the legacy producer-stalling
                # flush_all below — otherwise nothing would bound it
                # at all (docs/qos.md "Interactions").
                self._drain_queues("backpressure",
                                   until_under=max_pending_bytes() // 2)
            else:
                # Backpressure: drain everything oldest-first so memory
                # held by pending wire payloads stays bounded.
                self.flush_all("backpressure")
        elif over_threshold:
            self.flush_queue(key, "threshold")

    # -- QoS admission control (docs/qos.md) -------------------------------

    def _qos_admit(self, entry: _Entry, tenant: str, cls) -> bool:
        """Per-tenant pending-bytes quota at enqueue. ``shed`` consults
        the unacknowledged-bytes measure — enqueue minus synchronize
        returns, both rank-deterministic stream points, so every member
        rank sheds the identical submissions — and fails the handle
        with :class:`QosAdmissionError`. ``block`` waits for
        granted-but-unsettled bytes to drop: work the executor WILL
        settle without any action from this (blocked) producer, and the
        wait never mutates the admission gate (a completion-timed grant
        would desynchronize the cross-rank grant order — the
        determinism contract's one forbidden move, and the planted
        priority-inversion shape hvdsched's qos-inversion-demo finds).
        Admission CHARGES the tenant's unacked bytes in the same
        critical section as the shed check — a separate check-then-
        reserve would let two same-tenant producer threads both pass
        against the same pending value and jointly overshoot the quota.
        Returns False when the entry was shed."""
        from ..exceptions import QosAdmissionError
        if cls.policy == "shed" and cls.quota > 0:
            with self._exec_cv:
                pending = self._qos_unacked.get(tenant, 0.0)
                if pending + entry.nbytes <= cls.quota:
                    self._qos_unacked[tenant] = pending + entry.nbytes
                    entry.qos_epoch = self._qos_epoch
                    return True
                shed = self._qos_stats["shed"]
                shed[tenant] = shed.get(tenant, 0) + 1
            # never charged: a synchronize() on the shed handle must not
            # deflate the unacked measure (the quota would leak headroom
            # equal to every shed-then-observed submission's size)
            entry.qos_acked = True
            entry.error = QosAdmissionError(tenant, entry.nbytes,
                                            int(pending), cls.quota)
            entry.tensors = ()
            entry.run = None
            entry.event.set()
            _qos_tenant_counter(tenant, "shed").inc()
            _timeline.record_qos("SHED", tenant)
            return False
        blocked = False
        with self._exec_cv:
            if cls.policy == "block" and cls.quota > 0:
                while True:
                    # granted-but-unsettled bytes PLUS parked single-
                    # controller bytes: both drain via the executor
                    # (settles and demand pulls) with no action from
                    # this blocked producer, so the wait cannot
                    # deadlock — while without the parked component a
                    # single-controller flood's backlog would sit in
                    # the gate unbounded, never engaging the quota.
                    # Parked NEGOTIATED bytes stay excluded (window-
                    # bounded; grantable only at deterministic points a
                    # blocked producer never reaches).
                    pending = self._qos_inflight.get(tenant, 0.0)
                    if self._qos_gate is not None:
                        pending += self._qos_gate.sc_parked_bytes_locked(
                            tenant)
                    # an entry larger than the quota admits once the
                    # tenant is fully drained — blocking would wait
                    # forever
                    if (pending <= 0.0
                            or pending + entry.nbytes <= cls.quota):
                        break
                    if not blocked:
                        blocked = True
                        self._qos_stats["quota_blocks"] += 1
                    # plain wait: grants (_emit_batch_locked),
                    # _qos_settle, and abort() all notify _exec_cv
                    self._exec_cv.wait()
            self._qos_unacked[tenant] = (
                self._qos_unacked.get(tenant, 0.0) + entry.nbytes)
            entry.qos_epoch = self._qos_epoch
        if blocked:
            _qos_tenant_counter(tenant, "blocks").inc()
            _timeline.record_qos("BLOCK", tenant)
        return True

    def _qos_ack(self, entry: _Entry) -> None:
        """Release the entry's unacknowledged bytes at a synchronize
        return (idempotent) — the deterministic retirement point of the
        shed measure. The acked test-and-set sits under ``_exec_cv``:
        two threads synchronizing one handle concurrently must not
        double-release the bytes (the per-op clamp would hide the
        tenant total undercounting, permanently leaking quota
        headroom)."""
        if entry.qos_tenant is None:
            return
        with self._exec_cv:
            if entry.qos_acked:
                return
            entry.qos_acked = True
            if entry.qos_epoch != self._qos_epoch:
                return  # charged under a world abort() already zeroed
            t = entry.qos_tenant
            self._qos_unacked[t] = max(
                0.0, self._qos_unacked.get(t, 0.0) - entry.nbytes)

    def _qos_settle(self, entries) -> None:
        """Release granted-but-unsettled bytes once entries settle (the
        block-policy quota's wait condition)."""
        charged = [e for e in entries if e.qos_inflight]
        if not charged:
            return
        with self._exec_cv:
            for e in charged:
                e.qos_inflight = False
                if e.qos_epoch != self._qos_epoch:
                    continue  # abort() already zeroed this charge
                t = e.qos_tenant
                self._qos_inflight[t] = max(
                    0.0, self._qos_inflight.get(t, 0.0) - e.nbytes)
            self._exec_cv.notify_all()

    # -- flushing ----------------------------------------------------------

    def flush_queue(self, key: tuple, trigger: str) -> None:
        """Flush one queue (no-op when it is already drained/being
        flushed by another thread — the entry events carry completion).

        With the pipelined executor on, this only DRAINS the queue,
        records the flush composition, and submits the batch — execution
        happens on the executor thread, so the triggering thread (a
        producer hitting the threshold, the cycle timer, a synchronize)
        returns immediately and flush k+1's enqueues overlap flush k's
        fuse/negotiate/collective. ``HVD_MAX_INFLIGHT_FLUSHES<=1``
        executes inline, the pre-pipeline behavior."""
        pipelined = envs.pipeline_enabled()
        with self._mu:
            _inv.assert_holding(self._mu, "pending-queue mutation (drain)")
            q = self._queues.pop(key, None)
            if q is None or not q.entries:
                return
            entries = q.entries
            self._pending_tensors -= sum(e.count for e in entries)
            self._pending_bytes -= q.nbytes
            self._stats["flushes"][trigger] += 1
            self._stats["flushed_tensors"] += sum(e.count for e in entries)
            self._stats["flushed_bytes"] += q.nbytes
            names = tuple(n for e in entries for n in e.names)
            self.flush_history.append((trigger, key, names))
            # Lockstep decision point (docs/conformance.md): the flush
            # composition every rank must derive identically. The
            # trigger is deliberately NOT hashed — WHEN a queue drains
            # may vary across ranks (timer jitter); WHAT drains may not.
            _conformance.record(
                "ops/fusion_cycle.py::FusionScheduler.flush_queue",
                "flush", (q.spec.kind, names))
            self._inflight_until = _inv.monotonic() + (
                _INFLIGHT_WINDOW_CYCLES * envs.cycle_time_ms() / 1e3)
            if pipelined:
                # Register svc names with the executor's guard set in the
                # SAME critical section that removes them from q.names —
                # a producer reusing a name can then never observe the
                # window between the drain and the batch submission
                # (enqueue's clash check reads both sets). _mu -> _exec_cv
                # nesting is one-way; no path nests them in reverse.
                svc_names = {n for e in entries if e.requests
                             for n in e.names}
                if svc_names:
                    with self._exec_cv:
                        self._exec_names.update(svc_names)
            pending_bytes = self._pending_bytes
        tenant = _pset_label(q.spec.pset)
        tm = _tenant_metrics(tenant)
        _flush_counter(tm, tenant, trigger).inc()
        tm["tensors"].inc(sum(e.count for e in entries))
        tm["bytes"].inc(q.nbytes)
        _PENDING_BYTES_G.set(pending_bytes)
        _timeline.record_cycle_flush(trigger)
        # Step capture recording: composition noted at the drain point
        # (submission order), while the entries still hold their tensors.
        self.capture.note_flush(q.spec, entries, trigger)
        if not pipelined:
            self._execute(q.spec, entries)
            return
        ticket = None
        if (q.spec.svc is not None and q.spec.kind in ("allreduce",
                                                       "broadcast")):
            # Overlapped negotiation: submit the whole flush's requests
            # NOW, at the rank-deterministic trigger point (preserving
            # the PR-2 negotiation-order contract), and let the executor
            # wait for the responses only when it reaches this batch —
            # the KV round trip then runs under flush k's collective.
            reqs = [r for e in entries for r in e.requests]
            if reqs:
                try:
                    # Statically reachable from the cycle timer, but the
                    # timer never flushes svc queues (_loop skips them);
                    # only rank-deterministic user-thread triggers reach
                    # this negotiation submit.
                    ticket = q.spec.svc.negotiate_many_submit(reqs)  # hvdlint: disable=timer-purity
                except BaseException as exc:
                    with self._exec_cv:  # batch never reaches the
                        # executor; release its guard names
                        self._exec_names.difference_update(
                            n for e in entries for n in e.names)
                        self._exec_cv.notify_all()
                    self._fail_entries(entries, exc)
                    hvd_logging.error(
                        "fusion cycle negotiation submit failed: %s", exc)
                    if not isinstance(exc, Exception):
                        raise
                    return
        self._submit(_Batch(q.spec, entries, trigger, ticket))

    def flush_entry(self, entry: _Entry, trigger: str) -> None:
        if entry.done or entry.queue_key is None:
            return
        # A capture-held entry dispatches with the whole-step program
        # (or falls back eagerly right here when the trigger blocks
        # before the stream completed) — never through its queue.
        if self.capture.intercept_flush(entry, trigger):
            return
        self.flush_queue(entry.queue_key, trigger)
        # Handle observation is a rank-deterministic program point: if
        # the entry's batch is parked in the QoS admission gate, grant
        # it now (every rank's gate jumps at the same stream point, so
        # the cross-rank grant order stays identical — docs/qos.md).
        gate = self._qos_gate
        if gate is not None:
            gate.release_entry(entry)

    def _drain_queues(self, trigger: str, until_under: int | None = None
                      ) -> None:
        """Drain pending queues — first-enqueue order, or highest QoS
        tier first with HVD_QOS=1 (high-priority work negotiates and
        parks ahead of bulk backlogs; deterministic: the pending set at
        a drain point is a pure function of the submission stream).
        ``until_under`` stops once total pending bytes fall to/below it
        (the QoS backpressure path: drain the MINIMUM that restores the
        cap, instead of chasing an always-refilling backlog on whatever
        producer thread — possibly a latency tenant's — happened to
        cross it); a bounded drain evicts the LOWEST tier first — the
        bulk backlog is what backpressure exists to move out, and a
        latency tenant's queue is about to drain at its own synchronize
        anyway."""
        qos_on = _qos.enabled()
        bounded = until_under is not None
        while True:
            with self._mu:
                if bounded and self._pending_bytes <= until_under:
                    return
                key = None
                if qos_on:
                    best = None
                    for i, (k, q) in enumerate(self._queues.items()):
                        tier = _qos.get_class(
                            _pset_label(q.spec.pset)).priority
                        rank_key = (tier if bounded else -tier, i)
                        if best is None or rank_key < best:
                            best, key = rank_key, k
                else:
                    key = next(iter(self._queues), None)
            if key is None:
                return
            self.flush_queue(key, trigger)

    def flush_all(self, trigger: str) -> None:
        """Drain every queue (:meth:`_drain_queues`), then release the
        QoS admission gate and quiesce the pipelined executor (barrier /
        shutdown / backpressure): callers of flush_all need everything
        *dispatched* on return — a barrier psum issued before a
        still-queued flush's programs would break the cross-process
        program issue order."""
        self._drain_queues(trigger)
        # a replay caught mid-stream must dispatch its held prefix too
        self.capture.flush_pending(trigger)
        gate = self._qos_gate
        if gate is not None:
            gate.release_all()
        self.quiesce()

    def wait_result(self, entry: _Entry):
        """Synchronize path: flush the entry's queue if still pending,
        wait for its dispatch, re-raise any flush failure."""
        self.flush_entry(entry, "synchronize")
        entry.event.wait()
        self._qos_ack(entry)
        if entry.error is not None:
            raise entry.error
        return entry.results

    def poll_entry(self, entry: _Entry) -> bool:
        """Poll path: an unflushed entry must first trigger its own flush
        (otherwise ``poll()`` on a queued handle would spin forever), then
        report whether the dispatch has landed."""
        self.flush_entry(entry, "poll")
        return entry.done

    # -- pipelined flush executor ------------------------------------------

    def _submit(self, batch: _Batch) -> None:
        # svc entry names were already registered in _exec_names by
        # flush_queue, inside the same _mu section that drained them from
        # q.names — THAT registration is the load-bearing one (no window
        # for a reused name to slip through); this method only queues.
        # With HVD_QOS=1 the batch routes through the admission gate
        # instead: it parks per tenant and the arbiter grants it into
        # the executor FIFO (window pump here, demand pull in
        # _exec_loop, forced release at handle observation).
        if _qos.enabled():
            with self._exec_cv:
                if self._qos_gate is None:
                    self._qos_gate = _qos.QosGate(
                        self._exec_cv, self._emit_batch_locked,
                        on_park=self._ensure_exec_thread_locked)
                gate = self._qos_gate
            tenant = _pset_label(batch.spec.pset)
            gate.submit(batch, tenant, _qos.get_class(tenant))
            return
        with self._exec_cv:
            self._emit_batch_locked(batch)

    def _ensure_exec_thread_locked(self) -> None:
        """Spawn the executor thread if needed (callers hold
        ``_exec_cv``). Also the QoS gate's ``on_park`` hook: a parked
        single-controller batch grants ONLY on executor demand, so the
        executor must exist the moment the gate holds work."""
        if self._exec_thread is None or not self._exec_thread.is_alive():
            self._exec_stop = False
            self._exec_thread = _inv.spawn_thread(
                self._exec_loop, name="hvd-flush-pipeline")

    def _emit_batch_locked(self, batch: _Batch) -> None:
        """Append one batch to the executor FIFO (callers hold
        ``_exec_cv``) — the executor admission point, where QoS
        granted-but-unsettled bytes are charged."""
        for e in batch.entries:
            if e.qos_tenant is not None:
                e.qos_inflight = True
                self._qos_inflight[e.qos_tenant] = (
                    self._qos_inflight.get(e.qos_tenant, 0.0) + e.nbytes)
        self._exec_q.append(batch)
        self._pstats["submitted"] += 1
        self._ensure_exec_thread_locked()
        self._exec_cv.notify_all()

    def _exec_loop(self) -> None:
        """The dedicated dispatch thread: one batch at a time, in strict
        submission (FIFO) order — slot admission order derives from
        submission order only, never from completion timing, so the flush
        composition AND the collective program issue order are identical
        for identical call streams (and concurrent collective launches,
        which deadlock the backend rendezvous, cannot happen between two
        queued flushes by construction)."""
        while True:
            with self._exec_cv:
                while not self._exec_q:
                    if self._exec_stop:
                        return
                    # QoS demand pull: a dry FIFO grants the fair-order
                    # pick among parked SINGLE-CONTROLLER batches
                    # (work-conserving priority scheduling; negotiated
                    # batches only grant at rank-deterministic points —
                    # docs/qos.md determinism contract)
                    if (self._qos_gate is not None
                            and self._qos_gate.demand_pull_locked()):
                        continue
                    # plain wait, no poll timeout: every producer path
                    # (submit, abort, stop) notifies under _exec_cv, so an
                    # idle pipeline sleeps instead of waking twice a second
                    self._exec_cv.wait()
                batch = self._exec_q.popleft()
                self._exec_busy = True
            try:
                try:
                    self._admit_slot()
                except BaseException:
                    # a failed earlier flush raises at block_until_ready;
                    # its entries already carry results — the error
                    # surfaces at THEIR synchronize, not this batch's
                    self._exec_inflight.clear()
                try:
                    self._execute(batch.spec, batch.entries, batch.ticket)
                except BaseException:
                    # entries were already marked failed by _execute; a
                    # KeyboardInterrupt on the daemon executor is spurious
                    # and must not kill the pipeline
                    hvd_logging.exception("pipelined flush failed")
                try:
                    self._track_inflight(batch.entries)
                except BaseException:  # accounting must never stall the
                    hvd_logging.exception("in-flight tracking failed")
            finally:
                with self._exec_cv:
                    self._exec_busy = False
                    self._pstats["executed"] += 1
                    for e in batch.entries:
                        if e.requests:
                            self._exec_names.difference_update(e.names)
                    self._exec_cv.notify_all()

    def _admit_slot(self) -> None:
        """Bound the in-flight window: at most ``HVD_MAX_INFLIGHT_FLUSHES``
        dispatched-but-device-incomplete flushes. Admission past the
        window blocks on the OLDEST in-flight flush (FIFO retirement —
        completion timing never reorders anything).

        Overlap metrics are sampled *before* eager retirement — the
        pre-ISSUE-6 accounting retired completed flushes first, so it
        read depth 0 whenever device completion beat the next admission,
        under-reporting any overlap that did happen. Two samples with
        distinct meanings: ``inflight_peak`` is the ADMISSION-time depth
        (pipeline pressure as the batch arrives at the window), while
        ``overlapped`` uses the POST-BLOCKING depth — a flush that had
        to wait out every predecessor before dispatching (slots=1, the
        documented synchronous mode) did not overlap anything and must
        not count. Slot-blocking time accumulates into
        ``device_wait_ms`` so a pipeline stalled on device completion is
        visible in ``fusion_stats()["pipeline"]`` instead of hiding
        inside dispatch wall time."""
        import jax
        # The in-flight window deque is executor-private state: only the
        # single dispatch thread may touch it (stop() clears it after the
        # thread is joined).
        _inv.assert_thread(self._exec_thread,
                           "in-flight window admission (_admit_slot)")
        slots = max(envs.max_inflight_flushes(), 1)

        def _done(leaves) -> bool:
            return all(getattr(l, "is_ready", lambda: True)()
                       for l in leaves)

        # admission-time sample, pre-retirement: earlier flushes still in
        # flight on device as this batch arrives at the window (pipeline
        # pressure — with 2 slots a saturated stream reads 2 here)
        depth = sum(1 for leaves in self._exec_inflight
                    if not _done(leaves))
        while self._exec_inflight and _done(self._exec_inflight[0]):
            self._exec_inflight.popleft()  # retire completed without blocking
        waited = False
        wait_s = 0.0
        while len(self._exec_inflight) >= slots:
            leaves = self._exec_inflight.popleft()
            waited = True
            t0 = _inv.monotonic()
            with _timeline.pipeline_stage("SLOT_WAIT"):
                jax.block_until_ready(leaves)  # GIL released: producers run on
            wait_s += _inv.monotonic() - t0
        # overlap sample, post-blocking: a flush only counts as
        # OVERLAPPED if an earlier flush is still device-incomplete when
        # it actually dispatches — i.e. after slot admission released it.
        # Counting the pre-block depth would report overlap_ratio ~1.0
        # for a slots=1 saturated stream, whose every dispatch waited out
        # its predecessor (the documented synchronous mode).
        live = sum(1 for leaves in self._exec_inflight
                   if not _done(leaves))
        # window depth after retirement/blocking: what actually remains
        # in the slot window alongside the admitted batch (occupancy)
        window_depth = len(self._exec_inflight)
        with self._exec_cv:
            self._pstats["depth_sum"] += window_depth
            if live > 0:
                self._pstats["overlapped"] += 1
            if depth > self._pstats["inflight_peak"]:
                self._pstats["inflight_peak"] = depth
            if waited:
                self._pstats["slot_waits"] += 1
                self._pstats["device_wait_ms"] += wait_s * 1e3
        _INFLIGHT_DEPTH_G.set(depth)
        _timeline.record_inflight_depth(depth)

    def _track_inflight(self, entries: list[_Entry]) -> None:
        import jax
        _inv.assert_thread(self._exec_thread,
                           "in-flight window tracking (_track_inflight)")
        leaves = []
        for e in entries:
            for r in (e.results or ()):
                arr = getattr(r, "array", r)  # PerRank carries .array
                leaves.extend(x for x in jax.tree.leaves(arr)
                              if hasattr(x, "is_ready"))
        if leaves:
            # a batch with no readiness-bearing leaves (results already
            # materialized, or a failed dispatch) never occupies a slot
            self._exec_inflight.append(leaves)

    def quiesce(self) -> None:
        """Block until every submitted batch has been dispatched (entry
        events set; device completion is the slots'/handles' business).
        Safe to call with nothing pending; no-op from the executor thread
        itself (an executor-side dispatch can never wait on itself)."""
        if threading.current_thread() is self._exec_thread:
            return
        with self._exec_cv:
            while self._exec_q or self._exec_busy:
                # plain wait: _submit and the executor's batch-complete
                # finally block both notify under _exec_cv
                self._exec_cv.wait()

    def _wait_names_clear(self, names) -> None:
        """Block until none of ``names`` is tracked as an in-flight svc
        negotiation (name-reuse guard): covers the whole span from the
        drain-side registration through batch execution — including the
        submission window where the executor queue itself looks idle.
        With QoS on, every wakeup re-attempts the gate release: the
        clashing batch can PARK only after this waiter's enqueue-side
        release attempt (names register at drain, before the
        negotiate-submit round trip that precedes the park), and a
        parked batch under the arbitration window would otherwise never
        grant while its only observer sits here."""
        if threading.current_thread() is self._exec_thread:
            return
        names = set(names)
        with self._exec_cv:
            while not self._exec_names.isdisjoint(names):
                if self._qos_gate is not None:
                    self._qos_gate.release_names_locked(names)
                    if self._exec_names.isdisjoint(names):
                        break
                # plain wait: every path that removes names (batch
                # completion, abort, submit failure) notifies under
                # _exec_cv — and gate.submit notifies on every park
                self._exec_cv.wait()

    # -- execution ---------------------------------------------------------

    def _fail_entries(self, entries: list[_Entry], exc) -> None:
        """Mark every undelivered entry so waiters unblock (the error
        re-raises at synchronize())."""
        failed = []
        for e in entries:
            if not e.done:
                e.error = exc
                e.tensors = ()
                e.run = None
                e.event.set()
                failed.append(e)
        self._qos_settle(failed)

    def _execute(self, spec: _QueueSpec, entries: list[_Entry],
                 ticket=None) -> None:
        with _inv.section("fusion-cycle-flush"), \
                _dispatch_cache.dispatch_source("flush"):
            self._execute_inner(spec, entries, ticket)

    def _execute_inner(self, spec: _QueueSpec, entries: list[_Entry],
                       ticket=None) -> None:
        try:
            # Chaos seam for the flush pipeline: an injected error here
            # exercises the _fail_entries path (entries marked failed,
            # waiters unblocked, handles raise at synchronize) exactly
            # like a real dispatch failure. No-op with HVD_FAULT_SPEC
            # unset (cached-bool fast path in utils/faults.py).
            _faults.inject("exec.dispatch")
            if spec.kind == "sparse":
                units = [[e] for e in entries]
                self._dispatch_units(units, self._run_opaque_unit)
            elif spec.kind == "allgather":
                units = [[e] for e in entries]
                self._dispatch_units(
                    units, lambda unit: self._run_allgather_unit(spec, unit))
            elif spec.svc is None:
                # Single-controller flush: ONE grouped dispatch for the
                # whole queue, through the dispatch plan cache — repeated
                # flush signatures go straight to the compiled programs.
                self._dispatch_units(
                    [entries], lambda unit: self._run_fused_unit(spec, unit))
            else:
                self._execute_negotiated(spec, entries, ticket)
        except BaseException as exc:
            self._fail_entries(entries, exc)
            hvd_logging.error("fusion cycle flush failed: %s", exc)
            if not isinstance(exc, Exception):
                # KeyboardInterrupt/SystemExit must interrupt the caller
                # (user-thread flushes run inside enqueue/synchronize);
                # the timer and executor loops catch it separately and
                # survive.
                raise

    def _dispatch_units(self, units, run_unit) -> None:
        """THE shared dispatch helper: a flush is a list of wire dispatch
        *units* (each a list of entries whose tensors travel together in
        one wire batch). Single-controller flushes are one unit; the
        multi-process allreduce path is one unit per entry (submission-
        time composition, matching the joined-rank reconstruction);
        allgather/sparse are per-entry by nature. Dispatch accounting is
        therefore uniform across modes: ``dispatches`` counts FLUSH-level
        dispatch rounds (so the coalesce ratio means the same thing in
        single-controller and multi-process jobs) and ``wire_programs``
        counts the actual program batches issued."""
        settled = []
        try:
            for unit in units:
                outs = run_unit(unit)
                i = 0
                for e in unit:
                    e.results = list(outs[i:i + e.count])
                    i += e.count
                    e.tensors = ()  # release inputs: handles keep results
                    e.run = None
                    settled.append(e)
        except BaseException:
            # a later unit failing must not poison earlier units whose
            # wire programs already ran (peers counted them as done):
            # settle the completed entries with their results before the
            # error reaches _fail_entries (which skips done entries)
            for e in settled:
                e.event.set()
            self._qos_settle(settled)
            raise
        with self._mu:
            self._stats["dispatches"] += 1
            self._stats["wire_programs"] += len(units)
        # Events last, results and stats first: the moment ANY waiter
        # wakes, the whole flush's accounting is final (a synchronize on
        # one entry of a batch used to race the remaining event sets and
        # the stats bump — observable as a peer entry briefly "not done"
        # after its batch already executed).
        for e in settled:
            e.event.set()
        self._qos_settle(settled)

    def _run_fused_unit(self, spec: _QueueSpec, unit: list[_Entry]) -> list:
        from . import collectives as _coll
        tensors = [t for e in unit for t in e.tensors]
        if spec.kind == "allreduce":
            return _coll.grouped_allreduce(
                tensors, op=spec.op, process_set=spec.pset,
                prescale_factor=spec.pre, postscale_factor=spec.post,
                axis_name=spec.axis, compression=spec.compression)
        return _coll.grouped_broadcast(
            tensors, spec.root_rank, process_set=spec.pset,
            axis_name=spec.axis)

    def _run_allgather_unit(self, spec: _QueueSpec,
                            unit: list[_Entry]) -> list:
        """Allgather entries dispatch per-entry in submission order (the
        engine's recv_splits can resize the program per call, so there is
        no fused multi-tensor gather program to coalesce into); the queue
        still defers them to the cycle so they overlap submission-side
        Python with in-flight device work."""
        from . import collectives as _coll
        e, = unit
        return [_coll.allgather(e.tensors[0], process_set=spec.pset,
                                axis_name=spec.axis, name=e.names[0])]

    def _run_opaque_unit(self, unit: list[_Entry]) -> list:
        e, = unit
        return [e.run()]

    def _execute_negotiated(self, spec: _QueueSpec, entries: list[_Entry],
                            ticket=None) -> None:
        """Multi-process flush: batch ALL drained negotiations into one
        ``negotiate_many`` round (one KV cycle per flush instead of one
        per call — submitted early by the pipelined flush trigger, waited
        here), then execute each entry with its submission-time program
        composition — identical to what a joined rank rebuilds from
        response metadata, so programs match across processes no matter
        when each process's cycle fired."""
        from . import collectives as _coll
        # Both negotiation calls are timer-unreachable at runtime: _loop
        # skips svc queues, so only user-thread triggers (rank-
        # deterministic program points) drain negotiated flushes.
        if ticket is not None:
            spec.svc.negotiate_many_wait(ticket)  # hvdlint: disable=timer-purity
        else:
            spec.svc.negotiate_many(  # hvdlint: disable=timer-purity
                [r for e in entries for r in e.requests])
        if spec.kind == "broadcast":
            # Broadcast is illegal while any rank is joined (reference
            # JoinOp covers allreduce/allgather/barrier only), so there is
            # no joined-rank program reconstruction to match — the whole
            # flushed queue fuses into one dispatch, like single-
            # controller mode (flush points are rank-deterministic, so
            # every process fuses the identical set).
            def run_bcast(unit):
                tensors = [t for e in unit for t in e.tensors]
                return _coll._run_queued_broadcast(
                    tensors, spec.pset, spec.axis, spec.root_rank,
                    unit[0].label)
            self._dispatch_units([entries], run_bcast)
            return

        def run_entry(unit):
            e, = unit
            return _coll._run_queued_allreduce(
                e.tensors, spec.pset, spec.axis, spec.op, spec.pre,
                spec.post, spec.compression, e.label)
        self._dispatch_units([[e] for e in entries], run_entry)

    # -- cycle timer -------------------------------------------------------

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop = _inv.make_event("fusion_cycle.scheduler.stop")
            self._thread = _inv.spawn_thread(
                self._loop, name="hvd-fusion-cycle")

    def _age_limit_s(self) -> float:
        """Queue age that triggers a cycle flush: CYCLE_TIME idle,
        PENDING_CYCLE_TIME while work is in flight (a dispatch happened
        within the last cycle window)."""
        cycle = envs.cycle_time_ms() / 1e3
        if _inv.monotonic() < self._inflight_until:
            return min(cycle, pending_cycle_time_ms() / 1e3)
        return cycle

    def _loop(self) -> None:
        stop = self._stop
        while not stop.is_set():
            self._wake.clear()
            now = _inv.monotonic()
            due: list[tuple] = []
            next_deadline = None
            with self._mu:
                limit = self._age_limit_s()
                for key, q in self._queues.items():
                    if q.spec.svc is not None:
                        # Multi-process queues NEVER flush from the timer:
                        # XLA programs must be issued in the identical
                        # order on every process, and only user-thread
                        # triggers (threshold at enqueue, synchronize,
                        # poll, barrier, shutdown) happen at rank-
                        # deterministic program points. Timer jitter on
                        # one process must not reorder dispatches.
                        continue
                    deadline = q.oldest_t + limit
                    if deadline <= now:
                        due.append(key)
                    elif next_deadline is None or deadline < next_deadline:
                        next_deadline = deadline
            for key in due:
                if stop.is_set():
                    return
                try:
                    self.flush_queue(key, "cycle")
                except BaseException:  # entries already marked failed; a
                    # KeyboardInterrupt on the daemon timer is spurious
                    # and must not kill the cycle loop
                    hvd_logging.exception("cycle flush failed on timer")
            if due:
                continue
            timeout = (None if next_deadline is None
                       else max(next_deadline - _inv.monotonic(), 0.0))
            self._wake.wait(timeout)

    # -- lifecycle / stats -------------------------------------------------

    def drain(self) -> None:
        """Execute everything still pending (clean shutdown: results of
        never-synchronized handles are materialized, not dropped)."""
        self.flush_all("shutdown")

    def abort(self, reason: str) -> int:
        """Fail everything still pending without executing (engine
        service reset / elastic world teardown — the world the entries
        were negotiated against no longer exists): the pending queues AND
        the batches sitting in the pipelined executor's submission queue
        (their negotiation tickets are cancelled so the names become
        reusable). The batch the executor is currently dispatching runs
        to completion or error on its own — its entries' events are set
        either way, so no waiter can deadlock on an abort mid-pipeline.
        Returns the number of entries aborted; their handles raise at
        synchronize()."""
        with self._mu:
            queues = list(self._queues.values())
            self._queues.clear()
            self._pending_tensors = 0
            self._pending_bytes = 0
        with self._exec_cv:
            batches = list(self._exec_q)
            self._exec_q.clear()
            if self._qos_gate is not None:
                # parked batches die with the world too (their
                # negotiation tickets cancel below, like queued ones)
                batches.extend(self._qos_gate.drain_locked())
            for b in batches:
                for e in b.entries:
                    if e.requests:
                        self._exec_names.difference_update(e.names)
                    e.qos_inflight = False
            # quota accounting dies with the world: zero it and bump
            # the epoch in the same critical section. EVERY pre-abort
            # entry — queued, parked, executor-queued, or already
            # executed but not yet synchronized — carries the old
            # epoch, so its late ack/settle is a no-op instead of
            # deflating charges made by post-abort submissions (the
            # shed quota would otherwise leak headroom equal to the
            # pre-abort pending). Then wake any quota-blocked
            # producers (their entries are failing below).
            self._qos_epoch += 1
            self._qos_unacked.clear()
            self._qos_inflight.clear()
            self._exec_cv.notify_all()
        n = 0
        err = lambda e: RuntimeError(
            f"queued collective {e.label!r} aborted: {reason}")
        for q in queues:
            for e in q.entries:
                e.error = err(e)
                e.tensors = ()
                e.run = None
                e.event.set()
                n += 1
        for b in batches:
            if b.ticket is not None:
                try:
                    b.spec.svc.negotiate_many_cancel(b.ticket)
                except Exception:  # hvdlint: disable=silent-except
                    pass  # service may already be gone
            for e in b.entries:
                if not e.done:
                    e.error = err(e)
                    e.tensors = ()
                    e.run = None
                    e.event.set()
                    n += 1
        # capture-held entries + the recorded/armed plan die with the
        # world they were recorded against (elastic re-form, service
        # reset, PeerFailureError teardown)
        n += self.capture.abort(reason)
        return n

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            _inv.join_thread(t, timeout=5)
        self._thread = None
        with self._exec_cv:
            self._exec_stop = True
            self._exec_cv.notify_all()
        t = self._exec_thread
        if t is not None and t is not threading.current_thread():
            _inv.join_thread(t, timeout=5)
        self._exec_thread = None
        self._exec_inflight.clear()

    def stats(self) -> dict:
        slots = max(envs.max_inflight_flushes(), 1)
        capture = self.capture.stats()
        with self._exec_cv:
            executed = self._pstats["executed"]
            qos = {"enabled": _qos.enabled(),
                   "shed": dict(self._qos_stats["shed"]),
                   "quota_blocks": self._qos_stats["quota_blocks"],
                   "unacked_bytes": dict(self._qos_unacked),
                   "inflight_bytes": dict(self._qos_inflight)}
            if self._qos_gate is not None:
                qos.update(self._qos_gate.stats_locked())
            pipeline = {
                "enabled": envs.pipeline_enabled(),
                "max_inflight": envs.max_inflight_flushes(),
                "chunking": envs.pipeline_chunking_enabled(),
                "pipeline_threshold_bytes": envs.pipeline_threshold_bytes(),
                "pipeline_chunks": envs.pipeline_chunks(),
                "submitted": self._pstats["submitted"],
                "executed": executed,
                "queue_depth": len(self._exec_q),
                "inflight_peak": self._pstats["inflight_peak"],
                "slot_waits": self._pstats["slot_waits"],
                # total ms the executor spent blocked on device
                # completion at slot admission (window full) — a
                # device-bound pipeline shows here, not in dispatch time
                "device_wait_ms": self._pstats["device_wait_ms"],
                # fraction of flushes dispatched while >=1 earlier flush
                # was still in flight on device — the overlap the
                # executor exists to create. Sampled BEFORE eager
                # retirement but AFTER slot blocking, so a slots=1
                # stream honestly reads 0.0 (docs/pipeline.md "Overlap
                # semantics").
                "overlap_ratio": (self._pstats["overlapped"] / executed
                                  if executed else 0.0),
                # mean fraction of the slot window occupied at admission
                # (the admitted batch itself counts as one slot;
                # post-retirement window depth)
                "slot_occupancy": (
                    (self._pstats["depth_sum"] / executed + 1.0) / slots
                    if executed else 0.0),
            }
        with self._mu:
            flushes = dict(self._stats["flushes"])
            dispatches = self._stats["dispatches"]
            flushed = self._stats["flushed_tensors"]
            total_flushes = sum(flushes.values())
            return {
                "enabled": enabled(),
                "cycle_time_ms": envs.cycle_time_ms(),
                "pending_cycle_time_ms": pending_cycle_time_ms(),
                "fusion_threshold_bytes": envs.fusion_threshold_bytes(),
                "max_pending_bytes": max_pending_bytes(),
                "enqueued_tensors": self._stats["enqueued_tensors"],
                "enqueued_bytes": self._stats["enqueued_bytes"],
                "pending_tensors": self._pending_tensors,
                "pending_bytes": self._pending_bytes,
                "flushes": {**flushes, "total": total_flushes},
                "flushed_tensors": flushed,
                "flushed_bytes": self._stats["flushed_bytes"],
                "dispatches": dispatches,
                "wire_programs": self._stats["wire_programs"],
                "tensors_per_flush": (flushed / total_flushes
                                      if total_flushes else 0.0),
                "bytes_per_flush": (self._stats["flushed_bytes"]
                                    / total_flushes if total_flushes
                                    else 0.0),
                # tensors coalesced per flush-level dispatch round — the
                # headline number: N small async calls -> N/coalesce
                # dispatches. Uniform across modes: a multi-process flush
                # is ONE dispatch round (one negotiate_many batch) even
                # though its submission-time composition issues one wire
                # program per entry (see wire_programs).
                "coalesce_ratio": (flushed / dispatches if dispatches
                                   else 0.0),
                "pipeline": pipeline,
                # multi-tenant QoS admission counters (docs/qos.md):
                # per-tenant grants/shares from the gate plus the
                # scheduler-side shed/quota accounting
                "qos": qos,
                # step capture-and-replay lifecycle counters
                # (docs/step_capture.md). Replayed entries never appear
                # in dispatches/wire_programs — the per-source plan-hit
                # split lives in dispatch_cache_stats()["hits_by_source"]
                "capture": capture,
            }

    def reset_stats(self) -> None:
        with self._mu:
            self._stats = {
                "enqueued_tensors": 0, "enqueued_bytes": 0,
                "flushed_tensors": 0, "flushed_bytes": 0, "dispatches": 0,
                "wire_programs": 0,
                "flushes": {t: 0 for t in FLUSH_TRIGGERS},
            }
            self.flush_history.clear()
        with self._exec_cv:
            self._pstats = {
                "submitted": 0, "executed": 0, "overlapped": 0,
                "depth_sum": 0, "inflight_peak": 0, "slot_waits": 0,
                "device_wait_ms": 0.0,
            }
            self._qos_stats = {"shed": {}, "quota_blocks": 0}
        self.capture.reset_stats()


# ---------------------------------------------------------------------------
# process-wide scheduler + the enqueue front door the async ops call
# ---------------------------------------------------------------------------

_scheduler: FusionScheduler | None = None
_scheduler_lock = threading.Lock()


def scheduler() -> FusionScheduler:
    from ..loopback import context as _lbctx
    ctx = _lbctx.current()
    if ctx is not None:
        # One scheduler per loopback rank: each rank's flush composition
        # and pipelined executor are its own, like one per process.
        if ctx.scheduler is None:
            with _scheduler_lock:
                if ctx.scheduler is None:
                    ctx.scheduler = FusionScheduler()
        return ctx.scheduler
    global _scheduler
    if _scheduler is None:
        with _scheduler_lock:
            if _scheduler is None:
                _scheduler = FusionScheduler()
    return _scheduler


def _current_scheduler() -> FusionScheduler | None:
    """The already-created scheduler for this thread's world (loopback
    rank or process-wide), without creating one."""
    from ..loopback import context as _lbctx
    ctx = _lbctx.current()
    if ctx is not None:
        return ctx.scheduler
    return _scheduler


def _plan_sigs(tensors):
    """Per-tensor dispatch signatures, or None when any tensor cannot be
    planned (python scalars, lists, ragged bundles keep the immediate
    generic path). Computed ONCE per submission — the enqueue hot path is
    exactly the per-call Python overhead this module exists to shrink."""
    from . import collectives as _coll
    sigs = [_coll._plan_sig(t) for t in tensors]
    return sigs if all(s is not None for s in sigs) else None


def _per_shapes(sigs):
    """Per-rank shapes from signatures (bundles drop the rank axis)."""
    return [s[1][1:] if s[0] == "b" else s[1] for s in sigs]


def _entry_nbytes(shapes, wire_dts) -> int:
    """Per-rank wire payload of one entry (what lands in a fusion
    buffer), in the wire dtype when compression is routed."""
    return sum(int(np.prod(shp) or 1) * dt.itemsize
               for shp, dt in zip(shapes, wire_dts))


def _negotiation_requests(request_type, names, shapes, wire_dts,
                          group_id=-1, **meta) -> list[dict]:
    """Pre-built negotiation payloads (multi-process jobs): metadata is
    frozen at submission time so every process emits the identical
    request sequence regardless of when its cycle fires. Built through
    ``collectives._request_dict``, the wire format's single owner."""
    from . import collectives as _coll
    return [_coll._request_dict(name, request_type, shape, dt,
                                group_id=group_id, **meta)
            for name, shape, dt in zip(names, shapes, wire_dts)]


def queue_allreduce(tensors, *, grouped: bool, op=None, process_set=None,
                    prescale_factor=1.0, postscale_factor=1.0, name=None,
                    axis_name=None, compression=None):
    """Enqueue an async (grouped) allreduce; returns a queued Handle, or
    None when the submission must take the immediate path (scheduler off,
    traced context, unplannable input, adasum, custom compressor)."""
    from ..process_sets import _resolve
    from ..utils import compat as _compat
    from . import collectives as _coll
    from .reduce_ops import ReduceOp, handle_average

    if op is None:
        op = ReduceOp.AVERAGE  # the allreduce()/reference default
    if not tensors or not enabled() or not _compat.trace_state_clean():
        return None
    if op == ReduceOp.ADASUM:
        return None
    sigs = _plan_sigs(tensors)
    if sigs is None:
        return None
    if _coll._is_custom_compressor(compression):
        # custom (non-cast) compressor: only its own compress/decompress
        # pair defines the wire format — take the immediate path, which
        # wraps the call with it
        return None
    if getattr(compression, "wire_dtype", None) is None:
        compression = None  # none-compression == no compression: one queue
    pset = _resolve(process_set)
    axis = _coll._resolve_axis(axis_name)
    for t in tensors:
        _coll._check_op_dtype(
            op, jnp.result_type(t.array if isinstance(t, _coll.PerRank)
                                else t))
    from .. import engine_service
    from . import hierarchical
    svc = engine_service.get_service(pset)
    # Key the queue by the WIRE mapping itself, not the compressor's
    # class name — a compressor instance (or two classes sharing a name)
    # must never share a queue with a different wire format.
    wire = getattr(compression, "wire_dtype", None)
    comp_key = jnp.dtype(wire).name if wire is not None else None
    shapes = _per_shapes(sigs)
    wire_dts = [_coll._wire_dtype_of(t, compression) for t in tensors]
    key = ("allreduce", pset.dispatch_key(), axis, int(op),
           float(prescale_factor), float(postscale_factor),
           hierarchical.hierarchical_enabled_for(pset), comp_key)
    requests: list[dict] = []
    if grouped:
        base = name or _coll._auto_name("q_grouped_allreduce", pset)
        names = [f"{base}.{i}" for i in range(len(tensors))]
    elif name is not None:
        names = [name]
    else:
        names = [_coll._auto_name("q_allreduce", pset)]
    if svc is not None:
        from ..dynamic import REQ_ALLREDUCE
        lowered_op, post = handle_average(op, pset.size(), postscale_factor)
        gid = -1
        if grouped:
            import zlib
            gid = zlib.crc32(names[0].rsplit(".", 1)[0].encode()) & 0x7FFFFFFF
        requests = _negotiation_requests(
            REQ_ALLREDUCE, names, shapes, wire_dts,
            group_id=gid, reduce_op=int(lowered_op),
            prescale=float(prescale_factor), postscale=float(post))
    spec = _QueueSpec("allreduce", pset, axis, op=op,
                      pre=float(prescale_factor),
                      post=float(postscale_factor),
                      compression=compression, svc=svc)
    entry = _Entry(list(tensors), grouped,
                   _entry_nbytes(shapes, wire_dts), names, requests,
                   label=names[0])
    entry.sigs = tuple(sigs)
    scheduler().enqueue(key, spec, entry)
    return _coll._QueuedHandle(entry)


def queue_broadcast(tensor, root_rank: int, *, process_set=None, name=None,
                    axis_name=None):
    from ..process_sets import _resolve
    from ..utils import compat as _compat
    from . import collectives as _coll

    if not enabled() or not _compat.trace_state_clean():
        return None
    sigs = _plan_sigs([tensor])
    if sigs is None:
        return None
    pset = _resolve(process_set)
    if root_rank not in pset.ranks:
        raise ValueError(
            f"root_rank {root_rank} not in process set {pset.ranks}")
    axis = _coll._resolve_axis(axis_name)
    from .. import engine_service
    svc = engine_service.get_service(pset)
    key = ("broadcast", pset.dispatch_key(), axis, int(root_rank))
    names = [name or _coll._auto_name("q_broadcast", pset)]
    shapes = _per_shapes(sigs)
    wire_dts = [jnp.dtype(sigs[0][2])]
    requests: list[dict] = []
    if svc is not None:
        from ..dynamic import REQ_BROADCAST
        requests = _negotiation_requests(
            REQ_BROADCAST, names, shapes, wire_dts,
            root_rank=int(root_rank))
    spec = _QueueSpec("broadcast", pset, axis, root_rank=int(root_rank),
                      svc=svc)
    entry = _Entry([tensor], False, _entry_nbytes(shapes, wire_dts), names,
                   requests, label=names[0])
    entry.sigs = tuple(sigs)
    scheduler().enqueue(key, spec, entry)
    return _coll._QueuedHandle(entry)


def queue_allgather(tensor, *, process_set=None, name=None, axis_name=None):
    from ..process_sets import _resolve
    from ..utils import compat as _compat
    from . import collectives as _coll

    if not enabled() or not _compat.trace_state_clean():
        return None
    sigs = _plan_sigs([tensor])
    if sigs is None:
        return None
    pset = _resolve(process_set)
    axis = _coll._resolve_axis(axis_name)
    from .. import engine_service
    svc = engine_service.get_service(pset)
    key = ("allgather", pset.dispatch_key(), axis)
    # Negotiation happens inside allgather() at flush time (its program
    # shape depends on the negotiated recv_splits), but in multi-process
    # jobs the NAME is drawn from the shared allgather counter NOW, at the
    # submission point — drawing it at flush time would interleave
    # nondeterministically with sync allgather calls and desynchronize
    # names across processes. Single-controller jobs keep name=None so
    # repeated flushes share one dispatch plan.
    auto = _coll._auto_name("allgather", pset) if svc is not None else None
    names = [name if name is not None else auto]
    spec = _QueueSpec("allgather", pset, axis, svc=svc)
    entry = _Entry([tensor], False,
                   _entry_nbytes(_per_shapes(sigs),
                                 [jnp.dtype(sigs[0][2])]),
                   names, label=names[0] or "allgather")
    scheduler().enqueue(key, spec, entry)
    return _coll._QueuedHandle(entry)


def queue_opaque(kind: str, run, *, process_set=None, nbytes: int = 0,
                 label: str = "", extra_key=()):
    """Deferred-execution entry with its own executor (sparse async): no
    cross-entry fusion, but submissions still ride the cycle so a burst
    of sparse ops drains in one flush."""
    from ..process_sets import _resolve
    from ..utils import compat as _compat
    from . import collectives as _coll

    if not enabled() or not _compat.trace_state_clean():
        return None
    pset = _resolve(process_set)
    from .. import engine_service
    key = (kind, pset.dispatch_key()) + tuple(extra_key)
    # svc pins the timer restriction: opaque executors negotiate inside
    # their run() (e.g. sparse -> allgather), so multi-process entries
    # must flush from user-thread triggers only, like every other kind.
    spec = _QueueSpec("sparse", pset, None,
                      svc=engine_service.get_service(pset))
    entry = _Entry([None], False, int(nbytes),
                   [label or _coll._auto_name("q_" + kind, pset)], run=run,
                   label=label)
    scheduler().enqueue(key, spec, entry)
    return _coll._QueuedHandle(entry)


# -- module-level conveniences (mirror dispatch_cache's surface) ------------

def flush_all(trigger: str = "barrier") -> None:
    sched = _current_scheduler()
    if sched is not None:
        sched.flush_all(trigger)


def fusion_flush() -> None:
    """User-visible flush point (exported as ``hvd.fusion_flush()``):
    drain every pending queue into the pipelined executor and wait until
    all of it is *dispatched*. Weaker than ``hvd.barrier()`` — no
    cross-rank rendezvous and no device-completion wait (synchronize a
    handle for that) — and useful before timing boundaries or memory
    checkpoints where queued-but-undispatched work would skew the
    measurement."""
    flush_all("barrier")


def drain() -> None:
    """Clean-shutdown hook (``hvd.shutdown()``): execute everything still
    queued so no submitted collective is silently dropped."""
    sched = _current_scheduler()
    if sched is not None:
        sched.drain()
        sched.stop()


def abort(reason: str) -> int:
    """Service-reset hook (elastic teardown): fail pending entries."""
    sched = _current_scheduler()
    if sched is not None:
        return sched.abort(reason)
    return 0


def stats() -> dict:
    """Scheduler counters (the ``hvd.fusion_stats()`` API)."""
    return scheduler().stats()


def reset() -> None:
    """Tests / teardown: drop queues (aborting pending entries), stop the
    timer, and zero the counters."""
    global _scheduler
    from ..loopback import context as _lbctx
    ctx = _lbctx.current()
    with _scheduler_lock:
        if ctx is not None:
            sched, ctx.scheduler = ctx.scheduler, None
        else:
            sched, _scheduler = _scheduler, None
    if sched is not None:
        sched.abort("fusion scheduler reset")
        sched.stop()
