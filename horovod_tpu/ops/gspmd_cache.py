"""GSPMD cached-program fast path: stable step-signature caching for
jit/pjit train steps.

MULTICHIP_r05 clocked the GSPMD transformer train step at 8.8 s where
the shard_map path took 0.3 s. The gap is not execution — it is
*retracing*: ``jax.jit``'s internal cache keys on the **Python identity**
of the wrapped function, so the ubiquitous training-loop pattern of
re-creating the step closure (rebuilding a model wrapper, re-entering a
train function, re-forming after an elastic resize) pays the full
trace+lower+compile on every "first" call even though the program is
byte-identical. Every cache built since PR 1 (the dispatch plan cache,
PR-8 step capture) is eager-side only and never sees a GSPMD step.

:func:`cached_step` closes the gap by giving jit/pjit steps the same
"trace once, replay forever" contract the eager path already has:

* a stable **step signature** — pytree structure + leaf avals
  (shape/dtype/weak-type) + shardings + mesh identity + a content
  fingerprint of the step function (code object + primitive closure
  cells, never ``id()`` or weak function hashes) — keys a
  lowered+compiled executable in the dispatch plan cache
  (``ops/dispatch_cache.py``) under ``("gspmd", ..., sig)``, so every
  existing invalidation path (knob-override epoch, runtime generation,
  process-set removal, service reset, LRU pressure) applies unchanged;
* **donation** of parameter/optimizer buffers: ``donate_argnums`` is
  derived from the step's pytree layout (an argument donates when its
  leaf avals round-trip into the outputs — the params/opt-state carry),
  guarded by the PR-1 alias rules (an array object passed in two
  argument positions disqualifies both) and gated off on backends where
  donation is a no-op (``envs.donation_effective``);
* a capture-style **divergence contract**: shape/dtype/sharding drift
  simply produces a different signature (the cache holds several
  signatures, so train/eval shapes coexist); an executable that rejects
  its inputs *despite* a signature hit is dropped (:func:`~.dispatch_cache.drop`)
  and the call falls back to a plain traced ``jax.jit`` call — correct
  results, no hang, no stale-program reuse — then the next call
  re-records, mirroring ``ops/step_capture.py`` semantics.

GSPMD and eager DP converge on ONE cached-program architecture: the
dispatch plan cache is the shared store, :func:`~.dispatch_cache.fold_knobs`
the shared store-key canonicalizer, ``hits_by_source`` (now with a
``"gspmd"`` source) the shared hit accounting, and
:func:`~.step_capture._lifecycle_note` (with the capture phase
vocabulary) the shared metrics mirror. Loopback rank threads get
per-rank plan isolation for free through the dispatch cache's
per-context stores.

Contract (docs/gspmd.md): the step function must be *closure-light* —
anything that changes the compiled program must be visible in the
argument avals/shardings or captured as a primitive (str/int/float/
bool) closure cell. Capturing a mutable object whose state silently
changes the traced program (without changing any argument aval) is
outside the contract, exactly as it is for ``jax.jit`` itself when the
wrapper is reused.

Knobs: ``HVD_GSPMD_CACHE`` (default on; 0 restores plain per-call jit),
``HVD_GSPMD_CACHE_DONATE`` (auto|1|0; auto follows
``envs.donation_effective``). ``HVD_CACHE_CAPACITY=0`` disables this
cache along with every other dispatch plan.
"""

from __future__ import annotations

import collections

import jax

from .. import metrics as _metrics
from .. import timeline as _timeline
from ..utils import envs
from ..utils import logging as hvd_logging
from . import dispatch_cache as _dispatch
from . import step_capture as _capture
from .program_issue import issue_serialized as _issue_serialized


# ---------------------------------------------------------------------------
# step-signature canonicalizer
# ---------------------------------------------------------------------------

def _mesh_fingerprint(mesh) -> tuple:
    """Stable identity of a device mesh: axis names, logical shape, and
    the physical device ids in mesh order. Two ``Mesh`` objects built
    over the same devices compare equal here even when the Python
    objects differ (the re-created-closure case); an elastic re-form
    that changes membership changes the id tuple and therefore the
    signature."""
    devices = getattr(mesh, "devices", None)
    if devices is not None:
        ids = tuple(int(d.id) for d in devices.flat)
        shape = tuple(devices.shape)
    else:  # AbstractMesh: no physical devices, shape is the identity
        ids = ()
        shape = tuple(getattr(mesh, "axis_sizes", ()) or ())
    return (tuple(getattr(mesh, "axis_names", ())), shape, ids)


def _sharding_fingerprint(leaf) -> tuple | None:
    """Canonical sharding component of a leaf signature. NamedShardings
    reduce to (mesh fingerprint, spec); anything else (single-device,
    GSPMD/positional shardings) keys on its repr, which jax keeps
    stable and content-descriptive. Uncommitted host values (numpy,
    scalars) carry no sharding."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None:
        return None
    mesh = getattr(sharding, "mesh", None)
    spec = getattr(sharding, "spec", None)
    if mesh is not None and spec is not None:
        # PartitionSpecs are rank-extended with trailing Nones; XLA strips
        # them on outputs (P('tp', None) comes back as P('tp')). Both mean
        # the same placement, so canonicalize by dropping the trailing
        # Nones — otherwise feeding step N's outputs into step N+1 would
        # spuriously miss.
        entries = list(tuple(spec))
        while entries and entries[-1] is None:
            entries.pop()
        return ("named", _mesh_fingerprint(mesh),
                tuple(str(p) for p in entries))
    return ("other", repr(sharding))


def leaf_signature(leaf) -> tuple:
    """(shape, dtype, weak_type, sharding) of one pytree leaf — THE
    shared per-leaf canonicalizer of the cached-program architecture:
    the step-capture templates canonicalize collective *stream* entries
    the same way (shape/dtype content, never object identity), and this
    is its aval-level twin for whole-step program arguments."""
    aval = jax.api_util.shaped_abstractify(leaf)
    return (tuple(aval.shape), str(aval.dtype),
            bool(getattr(aval, "weak_type", False)),
            _sharding_fingerprint(leaf))


def tree_signature(args: tuple) -> tuple:
    """Signature of an argument pytree: (treedef, per-leaf signatures).
    Treedefs hash structurally, so two structurally-identical pytrees
    built from different Python objects produce equal signatures."""
    flat, treedef = jax.tree.flatten(args)
    return (treedef, tuple(leaf_signature(leaf) for leaf in flat))


def _code_fingerprint(fn) -> tuple:
    """Content identity of the step function, stable across closure
    re-creation: module + qualname + the code object (CPython hashes
    code objects structurally, and a nested ``def`` re-executed by its
    builder reuses ONE code constant) + primitive closure cells. A
    non-primitive captured object contributes only its type, which is
    the documented closure-light contract: its *state* must show up in
    the argument avals, not in the trace."""
    code = getattr(fn, "__code__", None)
    cells = []
    for cell in (getattr(fn, "__closure__", None) or ()):
        contents = cell.cell_contents
        if isinstance(contents, (str, bytes, int, float, bool, type(None))):
            cells.append(("lit", contents))
        else:
            cells.append(("obj", type(contents).__module__,
                          type(contents).__qualname__))
    return (getattr(fn, "__module__", ""), getattr(fn, "__qualname__", ""),
            code, tuple(cells))


# ---------------------------------------------------------------------------
# donation derivation (the PR-1 alias-guard rules at step scope)
# ---------------------------------------------------------------------------

def _aliased_positions(args: tuple) -> set:
    """Argument positions sharing a leaf array *object* with another
    position: donating either would hand the executable a buffer the
    other position still reads (XLA rejects the call: ``f(donate(a),
    a)``). Both positions are excluded — the alias guard the per-flush
    dispatch plans apply to wire buffers, applied to step arguments."""
    by_id: dict = {}
    for i, arg in enumerate(args):
        for leaf in jax.tree.leaves(arg):
            if isinstance(leaf, jax.Array):
                by_id.setdefault(id(leaf), set()).add(i)
    return {i for positions in by_id.values() if len(positions) > 1
            for i in positions}


def _derive_donate_argnums(args: tuple, out_tree) -> tuple:
    """Donate the argument positions whose leaf avals round-trip into
    the outputs — the params/opt-state carry pattern: every donated
    buffer is replaced by a same-shaped output, so HBM is recycled
    instead of doubled. Output avals are *consumed* as arguments claim
    them, so two same-shaped arguments can never donate against one
    output slot; batch inputs (avals absent from the outputs) never
    donate."""
    out_counter = collections.Counter(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree.leaves(out_tree))
    aliased = _aliased_positions(args)
    donate = []
    for i, arg in enumerate(args):
        leaves = jax.tree.leaves(arg)
        if not leaves or i in aliased:
            continue
        if not all(isinstance(leaf, jax.Array) for leaf in leaves):
            continue
        claimed = collections.Counter(
            (tuple(leaf.shape), str(leaf.dtype)) for leaf in leaves)
        if all(out_counter[sig] >= n for sig, n in claimed.items()):
            out_counter -= claimed
            donate.append(i)
    return tuple(donate)


# ---------------------------------------------------------------------------
# the compiled-step constructor (hvdlint pass-5 donation seam)
# ---------------------------------------------------------------------------

def _gspmd_step_program(fn, args: tuple, donate=()):
    """Lower and compile ``fn`` for ``args``' exact signature, donating
    the ``donate`` positions, and wrap the executable in the program-
    issue lock (a replayed GSPMD step is a multi-device program enqueue
    like any eager collective). Registered in hvdlint pass 5
    (``donate-kwarg``): a local array passed in a donated position of
    the RESULT and read after the call is a read-after-donate finding —
    params/opt-state handed to a cached step belong to the step."""
    return _issue_serialized(
        jax.jit(fn, donate_argnums=tuple(donate)).lower(*args).compile())


class GspmdPlan(_dispatch.DispatchPlan):
    """A compiled GSPMD step in the dispatch plan cache. ``execute``
    holds the lock-wrapped executable; ``run`` replays it under the
    step's timeline lane. No ``negotiate`` stage and no payload
    accounting: the partitioner already owns cross-device movement, so
    the base class's negotiation-skip/autotune bookkeeping would count
    fictional work. Never shelved across elastic re-forms — the
    executable bakes the old world's device assignment
    (``dispatch_cache._restorable``)."""

    __slots__ = ("key", "donate_argnums")

    def __init__(self, key: tuple, execute, donate_argnums: tuple):
        super().__init__("gspmd", "GSPMD_STEP", None, None, execute,
                         variant="gspmd")
        self.key = key
        self.donate_argnums = donate_argnums

    def run(self, args: tuple):
        with _timeline.op_range(self.label, self.activity):
            return self.execute(*args)


def _note_gspmd(event: str | None = None, state: str | None = None) -> None:
    """Registry mirror of the gspmd-cache lifecycle — the shared
    capture/gspmd instrument pattern (``step_capture._lifecycle_note``,
    same phase vocabulary)."""
    _capture._lifecycle_note(_metrics.GSPMD_CACHE_STEPS,
                             _metrics.GSPMD_CACHE_PHASE, event, state)


# ---------------------------------------------------------------------------
# the cached step
# ---------------------------------------------------------------------------

class CachedStep:
    """Callable wrapper around one step function (see
    :func:`cached_step`). Holds no compiled state itself — executables
    live in the dispatch plan cache, so two ``CachedStep`` objects over
    the same function (the re-created-closure pattern) serve each
    other's programs, and every cache-wide invalidation path applies."""

    def __init__(self, fn, donate="auto"):
        self._fn = fn
        self._donate = donate
        self._fingerprint = _code_fingerprint(fn)
        self._traces = 0
        self._counted = self._make_counted(fn)
        self._fallback = None

    @property
    def traces(self) -> int:
        """Times the step function has been traced through this wrapper
        (lowering, donation-shape probes, and plain-jit fallbacks all
        count) — the dryrun's regression evidence: a warm steady state
        replays with this number frozen."""
        return self._traces

    def _make_counted(self, fn):
        def _step(*args):
            self._traces += 1
            return fn(*args)
        return _step

    def _donate_tag(self) -> int:
        """Raw donation decision folded into the store key (the
        ``_store_key`` discipline: override-driven knob changes already
        invalidate via the cache epoch, but a raw env change does not
        bump the epoch — folding the resolved value means a program
        compiled under the other donation mode can never replay)."""
        if self._donate == "auto":
            return int(envs.gspmd_donate_enabled(jax.default_backend()))
        return 2  # explicit per-wrapper mask: keyed apart from both autos

    def _store_key(self, args: tuple) -> tuple:
        return _dispatch.fold_knobs(
            "gspmd", (self._fingerprint,) + tree_signature(args),
            self._donate_tag())

    def _resolve_donate(self, args: tuple) -> tuple:
        if self._donate == "auto":
            if not envs.gspmd_donate_enabled(jax.default_backend()):
                return ()
            return _derive_donate_argnums(
                args, jax.eval_shape(self._counted, *args))
        return tuple(self._donate or ())

    def _plain(self, args: tuple):
        """The divergence fallback: a plain traced call through one
        stable jit wrapper (jax's own cache keys on it, so repeated
        fallbacks of one signature retrace once). Mirrors the capture
        contract — correct results, no hang, no stale-program reuse."""
        if self._fallback is None:
            self._fallback = _issue_serialized(jax.jit(self._counted))
        return self._fallback(*args)

    def _build(self, args: tuple, key: tuple):
        donate = self._resolve_donate(args)
        try:
            program = _gspmd_step_program(self._counted, args,
                                          donate=donate)
        except (TypeError, ValueError) as exc:
            # Unlowerable under AOT (e.g. a signature the donation mask
            # mis-fits). Cache the negative decision so repeated calls
            # skip the rebuild attempt, then serve eagerly.
            hvd_logging.warning(
                "gspmd_cache: step is not AOT-compilable (%s); serving "
                "plain traced calls for this signature", exc)
            _dispatch.store(key, _dispatch.UNPLANNABLE)
            return None
        return GspmdPlan(key, program, donate)

    def __call__(self, *args):
        if not envs.gspmd_cache_enabled():
            _note_gspmd("bypass", state="bypass")
            return self._plain(args)
        key = self._store_key(args)
        # record_stats=False: like the capture controller, a hit counts
        # only when the replay actually SERVES (note_gspmd_hit below) —
        # an executable that rejects its inputs never counts.
        plan = _dispatch.lookup(key, record_stats=False)
        if plan is _dispatch.UNPLANNABLE:
            return self._plain(args)
        if plan is not None:
            try:
                out = plan.run(args)
            except TypeError as exc:
                # Signature hit but the executable rejected the
                # arguments (aval/layout drift the signature cannot
                # see). Rejection happens before execution, so no
                # buffer was donated: drop the plan, serve this call
                # plainly, and let the next call re-record.
                hvd_logging.warning(
                    "gspmd_cache: cached executable rejected its inputs "
                    "(%s); invalidating and falling back to a traced "
                    "call", exc)
                _dispatch.drop(key)
                _note_gspmd("invalidated")
                _note_gspmd("fallback", state="bypass")
                return self._plain(args)
            _dispatch.note_gspmd_hit()
            _note_gspmd("replayed", state="replayed")
            return out
        _note_gspmd(state="record")
        plan = self._build(args, key)
        if plan is None:
            _note_gspmd("fallback", state="bypass")
            return self._plain(args)
        _dispatch.store(key, plan)
        _note_gspmd("recorded")
        return plan.run(args)


def cached_step(fn, donate="auto") -> CachedStep:
    """Wrap a jit/pjit-style train step in the GSPMD cached-program
    fast path (docs/gspmd.md).

    ``cached = hvd.cached_step(train_step)`` then ``cached(params,
    opt_state, batch)``: the first call with a given signature lowers
    and compiles once; every later call with the same signature — from
    this wrapper or ANY other ``cached_step`` over the same function,
    including a re-created closure — replays the compiled executable
    with zero retrace. ``donate`` is ``"auto"`` (derive the
    params/opt-state donation mask per signature, off where donation is
    a backend no-op), an explicit tuple of argument positions, or
    ``()``/``None`` to disable donation."""
    return CachedStep(fn, donate=donate)


# ---------------------------------------------------------------------------
# DistributedOptimizer integration + stats
# ---------------------------------------------------------------------------

def note_passthrough() -> None:
    """Called by ``optim._allreduce_tree``'s GSPMD passthrough branch at
    trace time: counts gradient syncs routed through the partitioner
    (once per *trace*, not per step — a warm cached step holds this
    counter frozen, which is exactly the no-retrace evidence)."""
    _metrics.GSPMD_PASSTHROUGH_SYNCS.inc()


def stats() -> dict:
    """GSPMD cached-program counters (the ``hvd.gspmd_cache_stats()``
    API): a view over the shared registry instruments, shaped like the
    ``dispatch_cache_stats()``/capture blocks."""
    events = {}
    for labelitems, v in _metrics.GSPMD_CACHE_STEPS.series().items():
        events[dict(labelitems).get("event", "")] = int(v)
    cache = _dispatch.stats()
    return {
        "enabled": envs.gspmd_cache_enabled(),
        "hits": cache["hits_by_source"].get("gspmd", 0),
        "builds": cache["gspmd_builds"],
        "events": events,
        "passthrough_syncs": int(_metrics.GSPMD_PASSTHROUGH_SYNCS.value()),
    }


def reset_stats() -> None:
    for inst in (_metrics.GSPMD_CACHE_STEPS, _metrics.GSPMD_CACHE_PHASE,
                 _metrics.GSPMD_PASSTHROUGH_SYNCS):
        inst.reset()
