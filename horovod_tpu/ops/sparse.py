"""Sparse (indexed-rows) gradient reduction.

TPU-native rebuild of the reference's sparse gradient path: TF
``IndexedSlices`` gradients are synchronized by allgathering values+indices
instead of allreducing a huge mostly-zero dense tensor
(``/root/reference/horovod/tensorflow/__init__.py:95-112``), and torch has
``sparse_allreduce_async`` (``/root/reference/horovod/torch/mpi_ops.py:556``).
The ``HOROVOD_SPARSE_AS_DENSE`` escape hatch (estimator param
``sparse_as_dense``) converts to dense before reducing; here that is the
``HVD_SPARSE_AS_DENSE`` knob.

JAX has no IndexedSlices: embedding gradients materialize dense. The TPU
design therefore has two halves:

* :func:`rows_from_dense` — bound-size row extraction. Inside jit shapes
  are static, so the caller names ``max_rows`` (e.g. tokens-per-batch) and
  the hottest ``max_rows`` rows are selected with ``top_k`` — for embedding
  grads at most tokens-per-batch rows are nonzero, so selection is exact.
* :func:`sparse_allreduce` — synchronizes ``SparseRows`` by allgathering
  values and indices over the mesh axis (wire traffic ∝ touched rows, not
  vocabulary size), exactly the reference's IndexedSlices→allgather shape.

``DistributedOptimizer(sparse_gradient_paths=[...])`` routes matching
gradient leaves through this path (the analog of the reference wiring
sparse grads inside ``DistributedOptimizer``/``DistributedGradientTape``).
"""

from __future__ import annotations

import typing

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils import envs
from .reduce_ops import ReduceOp

SPARSE_AS_DENSE = envs.SPARSE_AS_DENSE  # backcompat alias; HVD_SPARSE_AS_DENSE


class SparseRows(typing.NamedTuple):
    """A bounded indexed-rows gradient: ``values[i]`` is the gradient of
    row ``indices[i]`` of a ``(num_rows, dim)`` parameter. Duplicate
    indices mean implicit summation (IndexedSlices semantics)."""

    values: jax.Array   # (k, dim)
    indices: jax.Array  # (k,) int32
    num_rows: int       # static: first dimension of the dense parameter


def rows_from_dense(grad, max_rows: int) -> SparseRows:
    """Extract the ``max_rows`` highest-activity rows of a dense
    ``(num_rows, dim)`` gradient (exact when at most ``max_rows`` rows are
    nonzero, which holds for embedding grads with ``max_rows`` >=
    tokens-per-step). Static output shapes — jit/SPMD safe."""
    if grad.ndim != 2:
        raise ValueError(f"rows_from_dense expects a 2-D gradient, got "
                         f"shape {grad.shape}")
    num_rows = grad.shape[0]
    k = min(int(max_rows), num_rows)
    activity = jnp.sum(jnp.abs(grad), axis=1)
    _, idx = lax.top_k(activity, k)
    idx = idx.astype(jnp.int32)
    return SparseRows(values=grad[idx], indices=idx, num_rows=num_rows)


def rows_to_dense(rows: SparseRows):
    """Scatter-add ``SparseRows`` back to a dense ``(num_rows, dim)``
    array (duplicate indices sum — IndexedSlices semantics)."""
    dense = jnp.zeros((rows.num_rows,) + rows.values.shape[1:],
                      rows.values.dtype)
    return dense.at[rows.indices].add(rows.values)


def _resolve_sparse(process_set, axis_name):
    from ..process_sets import _resolve
    from .collectives import _resolve_axis
    return _resolve(process_set), _resolve_axis(axis_name)


def sparse_allreduce(rows: SparseRows, *, op: ReduceOp = ReduceOp.AVERAGE,
                     process_set=None, name: str | None = None,
                     axis_name=None) -> SparseRows:
    """Synchronize an indexed-rows gradient across ranks by allgathering
    values and indices (the reference's IndexedSlices allreduce,
    ``tensorflow/__init__.py:95-112``). AVERAGE pre-divides values by the
    process-set size — summing the returned rows then equals the dense
    average.

    Traced mode (inside ``shard_map``): per-rank ``rows`` with uniform
    ``k``; returns gathered rows of size ``world*k``. Eager mode: pass
    per-rank bundles via :class:`~horovod_tpu.ops.collectives.PerRank`
    values/indices of uniform ``k``. (The ``HVD_SPARSE_AS_DENSE`` escape
    hatch lives in :func:`sparse_allreduce_to_dense`, where dense-in
    dense-out makes its semantics exact.)
    """
    if op not in (ReduceOp.AVERAGE, ReduceOp.SUM):
        raise ValueError(
            f"sparse_allreduce supports AVERAGE/SUM, got {op.name} "
            "(matches the reference, which only averages/sums IndexedSlices)")
    from . import collectives
    pset, axis = _resolve_sparse(process_set, axis_name)

    n = pset.size()
    values = rows.values
    if op == ReduceOp.AVERAGE:
        if jnp.issubdtype(jnp.result_type(values), jnp.integer):
            raise TypeError("AVERAGE needs floating-point values; use SUM")
        values = values / jnp.asarray(n, jnp.result_type(values))

    if collectives._axis_is_bound(axis):
        groups = pset.axis_index_groups()
        g_values = lax.all_gather(values, axis, axis_index_groups=groups,
                                  tiled=True)
        g_indices = lax.all_gather(rows.indices, axis,
                                   axis_index_groups=groups, tiled=True)
        return SparseRows(g_values, g_indices, rows.num_rows)

    g_values = collectives.allgather(values, process_set=pset,
                                     axis_name=axis,
                                     name=None if name is None else name + ".values")
    g_indices = collectives.allgather(rows.indices, process_set=pset,
                                      axis_name=axis,
                                      name=None if name is None else name + ".indices")
    return SparseRows(g_values, g_indices, rows.num_rows)


def sparse_allreduce_to_dense(grad, max_rows: int, *,
                              op: ReduceOp = ReduceOp.AVERAGE,
                              process_set=None, name: str | None = None,
                              axis_name=None):
    """Dense-in dense-out convenience: extract rows, sync them with wire
    traffic ∝ ``world * max_rows * dim``, scatter back to dense. The drop-in
    replacement for a dense allreduce of an embedding gradient. With
    ``HVD_SPARSE_AS_DENSE`` set, skips row extraction and runs a regular
    dense allreduce (the reference's ``sparse_as_dense`` escape hatch)."""
    if envs.get_bool(envs.SPARSE_AS_DENSE):
        from . import collectives
        return collectives.allreduce(grad, op=op, process_set=process_set,
                                     axis_name=axis_name, name=name)
    rows = rows_from_dense(grad, max_rows)
    reduced = sparse_allreduce(rows, op=op, process_set=process_set,
                               name=name, axis_name=axis_name)
    return rows_to_dense(reduced).astype(grad.dtype)


def sparse_allreduce_async(rows, *, op: ReduceOp = ReduceOp.AVERAGE,
                           process_set=None, name: str | None = None,
                           axis_name=None):
    """Completion handle over :func:`sparse_allreduce` (reference
    ``sparse_allreduce_async``, ``torch/mpi_ops.py:556-579`` — allgather
    of indices+values wrapped in a synthesized handle). Rides the fusion
    cycle: the submission is queued and dispatches at the next flush
    (deferred execution; sparse entries keep their own composition —
    values+indices allgathers — rather than fusing across entries).
    The negotiation name is fixed at submission time so multi-process
    flush timing cannot desynchronize the auto-name counters."""
    from . import collectives, fusion_cycle
    from ..process_sets import _resolve
    pset = _resolve(process_set)
    fixed_name = name
    if fixed_name is None and not collectives._axis_is_bound(
            collectives._resolve_axis(axis_name)):
        from .. import engine_service
        if engine_service.get_service(pset) is not None:
            fixed_name = collectives._auto_name("sparse_allreduce", pset)
    nbytes = 0
    values = getattr(rows, "values", None)
    if values is not None and hasattr(values, "nbytes"):
        nbytes = int(values.nbytes)

    def run():
        return sparse_allreduce(rows, op=op, process_set=pset,
                                name=fixed_name, axis_name=axis_name)

    h = fusion_cycle.queue_opaque(
        "sparse_allreduce", run, process_set=pset, nbytes=nbytes,
        label=fixed_name or "sparse_allreduce", extra_key=(int(op),))
    if h is not None:
        return h
    return collectives.Handle(run())
