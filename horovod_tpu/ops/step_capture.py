"""Step capture-and-replay: compile the whole step's collective stream
into one cached program.

MULTICHIP_r05 put the GSPMD transformer train step at 8.8 s against
0.3 s for the shard_map path — eager per-flush dispatch (and, for GSPMD,
retracing) leaves a large factor on the table even after the dispatch
plan cache, the fusion cycle, and the pipelined executor shaved the
per-call and per-flush costs. The PR-2/3 determinism contract makes the
remaining overhead *removable*: flush composition is a pure function of
submission order plus enqueue-time negotiation names, so the per-step
collective stream is rank-deterministic and therefore **recordable**.

This module records the flush stream of one *marked* step — signatures,
bucket layouts, wire dtypes, negotiation names — as it flows through
``ops/fusion_cycle.py``, then lowers the entire step's collective work
(per-dtype fuse, grouped collectives, split, wire-buffer donation) into
ONE jitted program pair built by :func:`_plan_step_programs`, cached in
``ops/dispatch_cache.py`` under a step-signature key, and replayed on
subsequent steps with zero per-flush Python/dispatch overhead. The
Horovod API stays eager on the surface: handles, ``synchronize()``,
``result()`` behave identically; only the dispatch under them changes.

Lifecycle (``HVD_STEP_CAPTURE=1``; see docs/step_capture.md):

* ``hvd.step_marker()`` marks a step boundary (bare call per loop
  iteration, or ``with hvd.step_marker():`` around the step body). The
  bucketed ``optim.DistributedOptimizer`` gradient sync opens a region
  automatically when the knob is on and no user region is active.
* The first marked step RECORDS: every flush that drains during the
  region appends a :class:`_FlushRecord` (queue key, per-entry
  signatures, grouping, trigger) while executing eagerly as usual.
* The boundary SEALS the recording into a :class:`StepPlan` keyed by
  the stream's content signature (never by auto-generated negotiation
  names, so two schedulers fed the same stream produce byte-identical
  keys) and arms REPLAY.
* During replay, submissions are matched against the recorded stream
  and *held*; when the last recorded submission arrives, the whole
  step's collective work issues as one ``fuse``/``wire`` program pair
  (both under ``program_issue.issue_serialized``; the wire stage takes
  the fused buffers donated, exactly like the per-flush plans).
* Any divergence — shape/dtype drift, a new tensor, a different
  composition, a blocking ``synchronize`` before the stream completed,
  a barrier drain, an elastic re-form or ``abort()`` mid-step, a knob
  override epoch (the dispatch-cache epoch flush drops the plan) —
  INVALIDATES the capture: held entries execute eagerly with their
  recorded composition (correct results, no hang, no stale-plan reuse)
  and the next marked step re-records.

Multi-process (negotiation-service) streams replay with their
submission-time per-entry program composition (the joined-rank contract
forbids re-fusing them) but batch every flush's negotiation of the step
into ONE ``DynamicService.negotiate_step`` round — one KV cycle per
step instead of one per flush.

Statistics surface as the ``capture`` block of ``hvd.fusion_stats()``;
``hvd.dispatch_cache_stats()["hits_by_source"]`` separates step-plan
hits from per-flush and per-call hits so coalesce/overlap ratios stay
honest when capture is on.
"""

from __future__ import annotations

import zlib as _zlib

import jax
import jax.numpy as jnp
import numpy as np

from .. import autotune as _autotune
from .. import conformance as _conformance
from .. import metrics as _metrics
from .. import timeline as _timeline
from ..utils import envs
from ..utils import invariants as _inv
from ..utils import logging as hvd_logging
from . import dispatch_cache as _dispatch
from .program_issue import issue_serialized as _issue_serialized

# Flush triggers that mean the caller will BLOCK on the entry: a held
# entry observed through one of these before the recorded stream
# completed is a divergence (the recording predicted more submissions
# first) and must fall back eagerly so the caller can never hang.
_BLOCKING_TRIGGERS = ("synchronize", "poll")


def _wire_dt(src_dt, compression):
    """Wire dtype from a *signature* dtype (the tensor itself is gone by
    plan-build time) — the metadata twin of ``collectives._wire_dtype_of``:
    floating tensors travel in the compressor's wire dtype, everything
    else in its own."""
    wire = getattr(compression, "wire_dtype", None)
    if wire is not None and jnp.issubdtype(jnp.dtype(src_dt), jnp.floating):
        return jnp.dtype(wire)
    return jnp.dtype(src_dt)


class _EntryTemplate:
    """The replay-matchable shape of one recorded submission: queue key,
    grouping, tensor count, and the normalized per-tensor plan signatures
    (``collectives._plan_sig`` tuples). Negotiation names are
    deliberately NOT part of the template *signature* — auto names
    advance global counters, so keying on them would make the capture
    key depend on unrelated traffic instead of the stream's content —
    but they are retained for the seal-time duplicate check (a
    user-specified name repeated within one step needs the eager path's
    name-reuse serialization, which replay's single batched negotiation
    round cannot provide)."""

    __slots__ = ("key", "grouped", "count", "sigs", "names")

    def __init__(self, key, grouped, count, sigs, names=()):
        self.key = key
        self.grouped = grouped
        self.count = count
        self.sigs = sigs
        self.names = names

    def matches(self, entry) -> bool:
        return (entry.grouped == self.grouped
                and entry.count == self.count
                and getattr(entry, "sigs", None) == self.sigs)

    def signature(self) -> tuple:
        return (self.grouped, self.count, self.sigs)


class _FlushRecord:
    """One recorded flush: the queue spec it drained with, its entry
    templates in submission order, and the trigger that drained it."""

    __slots__ = ("spec", "templates", "trigger")

    def __init__(self, spec, templates, trigger):
        self.spec = spec
        self.templates = templates
        self.trigger = trigger

    def signature(self) -> tuple:
        return (self.templates[0].key if self.templates else (),
                tuple(t.signature() for t in self.templates))


class StepPlan(_dispatch.DispatchPlan):
    """A sealed capture: the recorded stream plus the whole-step
    executor. ``execute`` takes the held entries grouped per record (in
    template order) and returns per-record flat result lists. Stored in
    the dispatch plan cache under ``("step",) + key`` so every existing
    invalidation path (knob-override epoch, process-set removal, service
    reset, shutdown, LRU pressure) drops it like any other plan."""

    __slots__ = ("key", "records", "entries_total", "rebindable")

    def __init__(self, key, records, run_step, nbytes, pieces,
                 rebindable: bool = False):
        super().__init__("step", "STEP_REPLAY", nbytes, None, run_step,
                         variant="step", pieces=pieces)
        self.key = key
        self.records = records
        self.entries_total = sum(len(r.templates) for r in records)
        # Whether the executor survives an elastic re-form to the same
        # process-set shape (docs/elastic.md): negotiated streams over
        # the GLOBAL set resolve their service and mesh lazily per
        # replay, so their whole-step executor can be warm-grafted
        # across worlds. Single-controller streams bake mesh-bound jits,
        # and registered non-global sets bake old-world membership (the
        # numeric id may alias a different rank list after the resize) —
        # both stay world-local.
        self.rebindable = rebindable


# ---------------------------------------------------------------------------
# whole-step program construction (single-controller streams)
# ---------------------------------------------------------------------------

def _group_part(spec, sigs):
    """Per-group compile ingredients for the whole-step program: the
    fuse/wire closures (traced inside the step jits), the input
    canonicalizer, and the donation mask — the step-scope mirror of
    ``collectives._build_grouped_allreduce_plan``'s bookkeeping. A
    *group* is every recorded flush sharing one queue key (same
    op/process-set/scales/compression/root), so the whole step's
    same-signature flushes re-fuse into ONE per-dtype wire buffer set —
    the reduction is elementwise per tensor, so cross-flush fusion only
    changes wire packaging, never numerics (the PR-2 coalescing
    argument applied at step scope)."""
    from . import collectives as _coll
    from . import hierarchical
    from .reduce_ops import ReduceOp, handle_average

    count = len(sigs)
    n = spec.pset.size()
    bundled = any(s[0] == "b" for s in sigs)
    shapes = [tuple(s[1][1:]) if s[0] == "b" else tuple(s[1]) for s in sigs]
    src_dts = [jnp.dtype(s[2]) for s in sigs]
    if spec.kind == "allreduce":
        wire_dts = [_wire_dt(dt, spec.compression) for dt in src_dts]
    else:
        wire_dts = list(src_dts)
    metas = _coll._fusion_metas(shapes, src_dts, wire_dts)
    layout = None
    if spec.kind == "allreduce":
        lowered_op, post = handle_average(spec.op, n, spec.post)
        pre, post = float(spec.pre), float(post)
        hier = (lowered_op == ReduceOp.SUM
                and hierarchical.hierarchical_enabled_for(spec.pset))
        if hier:
            smap = hierarchical._hier_grouped_allreduce_smap(
                hierarchical.hierarchical_mesh(), lowered_op, pre, post,
                len(metas), bundled)
        else:
            smap = _coll._grouped_allreduce_smap(
                spec.pset.mesh(), spec.axis, lowered_op, pre, post,
                len(metas), bundled)
            # The recorded chunking decision carries into the captured
            # program: wire buckets past HVD_PIPELINE_THRESHOLD reduce
            # as HVD_PIPELINE_CHUNKS piece collectives INSIDE the step
            # program — a monolithic multi-MiB reduction measured far
            # slower than its chunked pieces (the PR-3 finding), and
            # step fusion across flushes makes buckets BIGGER, not
            # smaller.
            layout = _coll._chunk_layout(metas)
        row0 = bundled
        if layout is not None:
            piece_smap = _coll._grouped_allreduce_smap(
                spec.pset.mesh(), spec.axis, lowered_op, pre, post, 1,
                bundled)
    else:  # broadcast
        root_pos = spec.pset.ranks.index(spec.root_rank)
        smap = _coll._grouped_broadcast_smap(
            spec.pset.mesh(), spec.axis, root_pos, len(metas), bundled)
        row0 = False
    # the fuse body, donate mask, and canonicalizer are THE shared
    # builders the per-flush plans compile from — numerics and donation
    # safety cannot drift between the eager and replay paths
    donate = _coll._sig_donate_mask(metas, sigs, bundled)
    fuse = _coll._fuse_closure(metas, n, bundled)
    canon = _coll._canon_closure(shapes, n, bundled)

    if layout is None:
        def wire(fused):
            outs = list(smap(*fused))
            if row0:
                outs = [o[0] for o in outs]
            return _coll._split_fused(outs, metas, count)
    else:
        def wire(fused):
            pieces: list = [[] for _ in metas]
            for bi, a, b in layout:
                part = fused[bi][:, a:b] if bundled else fused[bi][a:b]
                out, = piece_smap(part)
                pieces[bi].append(out)
            outs = [ps[0] if len(ps) == 1
                    else jnp.concatenate(ps, axis=1 if bundled else 0)
                    for ps in pieces]
            if row0:
                outs = [o[0] for o in outs]
            return _coll._split_fused(outs, metas, count)

    nbytes = sum(int(np.prod(shp) or 1) * dt.itemsize
                 for shp, dt in zip(shapes, wire_dts))
    return {"fuse": fuse, "wire": wire, "canon": canon, "donate": donate,
            "count": count, "n_inputs": count, "n_bufs": len(metas),
            "nbytes": nbytes}


def _plan_step_programs(parts):
    """The captured step's two compiled stages — the step-scope twin of
    ``collectives._plan_fused_programs``. Stage 1 (``fuse``) packs EVERY
    record's user tensors into their per-dtype wire buffers in one
    program. Stage 2 (``wire``) runs every record's shard-mapped
    collective AND its wire-buffer split in one program, with the fused
    buffers donated — they are stage-1 outputs, so donation can only
    recycle dispatcher-owned memory (the per-record donate masks exclude
    buffers a backend's input-output forwarding could alias to a user
    array, exactly like the per-flush plans)."""
    in_slices, buf_slices = [], []
    donate: list = []
    ip = bp = 0
    for p in parts:
        in_slices.append((ip, ip + p["n_inputs"]))
        ip += p["n_inputs"]
        buf_slices.append((bp, bp + p["n_bufs"]))
        bp += p["n_bufs"]
        donate.extend(p["donate"])

    def fuse(*flat_inputs):
        bufs = []
        for p, (lo, hi) in zip(parts, in_slices):
            bufs.extend(p["fuse"](list(flat_inputs[lo:hi])))
        return tuple(bufs)

    def wire(*flat_fused):
        outs = []
        for p, (lo, hi) in zip(parts, buf_slices):
            outs.extend(p["wire"](list(flat_fused[lo:hi])))
        return tuple(outs)

    fuse_fn = _issue_serialized(jax.jit(fuse))
    wire_fn = _issue_serialized(jax.jit(
        wire, donate_argnums=tuple(i for i, d in enumerate(donate) if d)))
    return fuse_fn, wire_fn


def _fuse_groups(records):
    """Group the recorded flushes by queue key (stream order preserved
    within each group). Each group fuses into one per-dtype wire buffer
    set — a steady-state step's N bucket flushes become ONE collective
    set instead of N."""
    groups: dict = {}
    order: list = []
    for ri, rec in enumerate(records):
        key = rec.templates[0].key if rec.templates else ()
        g = groups.get(key)
        if g is None:
            g = {"spec": rec.spec, "sigs": [], "records": []}
            groups[key] = g
            order.append(g)
        sigs = [s for t in rec.templates for s in t.sigs]
        lo = len(g["sigs"])
        g["sigs"].extend(sigs)
        g["records"].append((ri, lo, lo + len(sigs)))
    return order


def _make_jit_execute(records):
    """Whole-step executor for single-controller streams: one
    fuse+wire program pair covering every recorded flush, with
    same-signature flushes re-fused across the step."""
    groups = _fuse_groups(records)
    parts = [_group_part(g["spec"], g["sigs"]) for g in groups]
    fuse_fn, wire_fn = _plan_step_programs(parts)

    def execute(entries_per_record):
        flat = []
        for g, p in zip(groups, parts):
            ts = []
            for ri, _lo, _hi in g["records"]:
                ts.extend(t for e in entries_per_record[ri]
                          for t in e.tensors)
            flat.extend(p["canon"](ts))
        outs = list(wire_fn(*fuse_fn(*flat)))
        result: list = [None] * len(records)
        pos = 0
        for g, p in zip(groups, parts):
            group_outs = outs[pos:pos + p["count"]]
            pos += p["count"]
            for ri, lo, hi in g["records"]:
                result[ri] = group_outs[lo:hi]
        return result

    return execute, sum(p["nbytes"] for p in parts)


def _make_svc_execute(records):
    """Whole-step executor for negotiated (multi-process) streams: ONE
    batched negotiation round for every flush of the step, then each
    entry's submission-time program composition — identical to what a
    joined rank reconstructs from response metadata, so active and
    joined processes keep lowering the same programs."""
    pset = records[0].spec.pset
    build_svc = records[0].spec.svc

    def execute(entries_per_record):
        from .. import engine_service
        from . import collectives as _coll
        reqs = [r for entries in entries_per_record
                for e in entries for r in e.requests]
        if reqs:
            # Resolve the service per replay, not at build: an elastic
            # re-form back to this shape rebuilds services, and lazy
            # resolution is what lets a warm-restored step plan
            # (docs/elastic.md) negotiate against the NEW world.
            svc = engine_service.get_service(pset) or build_svc
            svc.negotiate_step(reqs)
        out = []
        for rec, entries in zip(records, entries_per_record):
            spec = rec.spec
            if spec.kind == "broadcast":
                tensors = [t for e in entries for t in e.tensors]
                out.append(_coll._run_queued_broadcast(
                    tensors, spec.pset, spec.axis, spec.root_rank,
                    entries[0].label))
            else:
                outs: list = []
                for e in entries:
                    outs.extend(_coll._run_queued_allreduce(
                        e.tensors, spec.pset, spec.axis, spec.op,
                        spec.pre, spec.post, spec.compression, e.label))
                out.append(outs)
        return out

    return execute, None


# ---------------------------------------------------------------------------
# the per-scheduler capture controller
# ---------------------------------------------------------------------------

def _store_key(key: tuple) -> tuple:
    """Dispatch-cache key for a sealed capture: the stream's content
    signature PLUS the raw knob values the compiled programs bake in
    (fusion threshold -> bucket metas; pipeline threshold/chunks ->
    in-program chunk layout), canonicalized through the shared
    :func:`~.dispatch_cache.fold_knobs` discipline the GSPMD program
    cache (``ops/gspmd_cache.py``) also uses. Override-driven knob
    changes already invalidate via the cache epoch, but a raw
    os.environ change does not bump the epoch — folding the values into
    the key means a stale layout can never replay (the eager plan keys
    do the same). The composed-mesh axis carve (``HVD_MESH_AXES``) is
    folded too: a captured composed step's ICI+DCN collective stream is
    layout-specific, and a carve change must re-record rather than
    replay the old axis split."""
    from . import collectives as _coll
    return _dispatch.fold_knobs("step", key, envs.fusion_threshold_bytes(),
                                _coll._pipeline_key(), envs.mesh_axes())


# Registry mirror of the capture lifecycle (docs/metrics.md): a numeric
# phase gauge plus per-event counters, with ONE phase vocabulary shared
# across the cached-program layers — ``ops/gspmd_cache.py`` mirrors its
# lifecycle through `_lifecycle_note` onto its own instruments with
# these same codes. The per-instance `_stats` dict stays the
# `fusion_stats()["capture"]` storage (tests build standalone
# schedulers whose capture counters must not mix); the registry mirror
# is the scrapeable view.
_PHASE_CODES = {"idle": 0, "record": 1, "replay": 2, "replayed": 3,
                "bypass": 4}


def _lifecycle_note(steps_counter, phase_gauge,
                    event: str | None = None,
                    state: str | None = None) -> None:
    """Shared lifecycle mirror of the cached-program architecture: one
    event counter bump and/or one phase-gauge transition (capture and
    gspmd plans use the same event names and phase codes, so the two
    execution modes read identically on the metrics surface)."""
    if event is not None:
        steps_counter.inc(labels={"event": event})
    if state is not None:
        phase_gauge.set(_PHASE_CODES.get(state, 0))


def _note_capture(event: str | None = None,
                  state: str | None = None) -> None:
    _lifecycle_note(_metrics.STEP_CAPTURE_STEPS,
                    _metrics.STEP_CAPTURE_PHASE, event, state)


class CaptureState:
    """Capture lifecycle controller owned by one
    :class:`~horovod_tpu.ops.fusion_cycle.FusionScheduler`.

    States: ``idle`` (no region open), ``record`` (first marked step:
    flushes execute eagerly and are recorded), ``replay`` (armed with a
    sealed plan: submissions are matched and held), ``replayed`` (the
    stream completed and the captured program executed), ``bypass`` (a
    divergence or abort dropped this step back to eager until the next
    boundary). Lock order: ``_mu`` may be held while taking the dispatch
    cache lock, never while taking the scheduler's ``_mu``/``_exec_cv``
    (fallback execution and replay dispatch run outside the lock)."""

    def __init__(self, sched):
        self._sched = sched
        self._mu = _inv.make_lock("step_capture.mu")
        # tests/models override; None = follow HVD_STEP_CAPTURE
        self.force_enabled = None
        self._state = "idle"
        self._region_open = False
        self._recording = False  # unlocked fast-path flag for note_flush
        self._replaying = False  # unlocked fast-path flag for offer
        self._records: list = []
        self._plan: StepPlan | None = None
        self._last_key: tuple | None = None
        self._expect: dict = {}
        self._held: dict = {}
        self._matched = 0
        self._total = 0
        self._stats = {
            "recorded_steps": 0, "captured_flushes": 0, "plan_builds": 0,
            "replayed_steps": 0, "replayed_entries": 0, "fallbacks": 0,
            "invalidations": 0, "uncapturable_steps": 0,
        }
        # instance attribute so tests/models can stub the constructor
        self._build_plan = self._default_build_plan

    # -- configuration -----------------------------------------------------

    def enabled(self) -> bool:
        if self.force_enabled is not None:
            return bool(self.force_enabled)
        return envs.step_capture_enabled()

    def region_open(self) -> bool:
        return self._region_open

    # -- step boundaries ---------------------------------------------------

    def boundary(self, closing: bool = False) -> None:
        """Close the current step region (seal a recording / verify a
        replay) and, unless ``closing``, open the next one — armed for
        replay when a plan for the last stream is still cached."""
        if not self.enabled() and self._state == "idle" \
                and not self._region_open:
            return
        prev_state = self._state
        fallback = None
        with self._mu:
            if self._state == "record":
                self._seal_locked()
            elif self._state == "replay":
                if self._matched == 0 and not self._held:
                    # EMPTY region: nothing was submitted at all (e.g.
                    # an eval iteration between marked train steps).
                    # Nothing diverged — keep the plan and _last_key so
                    # the next non-empty step re-arms instead of
                    # re-recording forever in a train/eval alternation.
                    self._expect = {}
                    self._total = 0
                else:
                    # the step ended before the recorded stream
                    # completed: divergence by omission — no
                    # stale-plan reuse
                    fallback = self._take_held_locked()
                    self._diverge_locked()
            self._state = "idle"
            self._replaying = self._recording = False
            self._region_open = False
        if fallback:
            self._run_fallback(fallback)
        if closing or not self.enabled():
            # Lockstep decision point (docs/conformance.md): every rank
            # must close the region from the same phase.
            _conformance.record(
                "ops/step_capture.py::CaptureState.boundary", "phase",
                (prev_state, "idle"))
            return
        with self._mu:
            self._region_open = True
            plan = None
            if self._last_key is not None:
                plan = _dispatch.lookup(_store_key(self._last_key),
                                        record_stats=False)
                if not isinstance(plan, StepPlan):
                    # epoch flush / eviction / capacity 0 dropped it
                    self._stats["invalidations"] += 1
                    _note_capture("invalidated")
                    self._last_key = None
                    plan = None
            if plan is not None:
                self._arm_locked(plan)
            elif _dispatch.enabled():
                self._records = []
                self._state = "record"
                self._recording = True
            else:
                # plan cache disabled (HVD_CACHE_CAPACITY=0): a sealed
                # plan could never be stored, so recording every step
                # would only burn bookkeeping — stay eager for the region
                self._state = "bypass"
        _note_capture(state=self._state)
        # Lockstep decision point (docs/conformance.md): the boundary's
        # phase move — seal/arm/record/bypass — is rank-deterministic.
        _conformance.record(
            "ops/step_capture.py::CaptureState.boundary", "phase",
            (prev_state, self._state))
        _timeline.record_capture(
            "REPLAY" if self._replaying
            else ("RECORD" if self._recording else "BYPASS"))

    def _seal_locked(self) -> None:
        records, self._records = self._records, []
        self._recording = False
        if not records:
            return
        self._stats["recorded_steps"] += 1
        self._stats["captured_flushes"] += len(records)
        _note_capture("recorded")
        key = tuple(r.signature() for r in records)
        # Lockstep decision point (docs/conformance.md): the sealed
        # stream key every rank must derive byte-identically (hashed —
        # full signatures are long; the ring keeps the quotable form).
        _conformance.record(
            "ops/step_capture.py::CaptureState._seal_locked", "seal",
            (len(records), _zlib.crc32(repr(key).encode()) & 0xFFFFFFFF))
        cached = _dispatch.lookup(_store_key(key), record_stats=False)
        if isinstance(cached, StepPlan):
            self._last_key = key  # alternating streams reuse their plan
            return
        try:
            plan = self._build_plan(key, records)
        except Exception as exc:
            hvd_logging.error("step capture plan build failed: %s", exc)
            plan = None
        if plan is None:
            self._stats["uncapturable_steps"] += 1
            _note_capture("uncapturable")
            self._last_key = None
            return
        self._stats["plan_builds"] += 1
        _dispatch.store(_store_key(key), plan)
        self._last_key = key
        _timeline.record_capture("SEAL")

    def _default_build_plan(self, key, records):
        """StepPlan for a sealed recording, or None when the stream is
        not capturable (non-fusable kinds, unplanned entries, mixed
        single-controller/negotiated flushes)."""
        svc = records[0].spec.svc
        for rec in records:
            if rec.spec.kind not in ("allreduce", "broadcast"):
                return None
            if any(t.sigs is None for t in rec.templates):
                return None
            if (rec.spec.svc is None) != (svc is None) \
                    or (svc is not None and rec.spec.svc is not svc):
                return None
        if svc is not None:
            # A user name repeated WITHIN the step needs the eager
            # path's name-reuse serialization (two sequential
            # negotiation batches); replay's single negotiate_step round
            # would orphan the first request and stall — such a stream
            # is uncapturable, not replayable-with-a-hang.
            names = [n for rec in records for t in rec.templates
                     for n in t.names]
            if len(names) != len(set(names)):
                return None
        if svc is None:
            run_step, nbytes = _make_jit_execute(records)
        else:
            run_step, nbytes = _make_svc_execute(records)
        return StepPlan(key, records, run_step, nbytes, len(records),
                        rebindable=svc is not None and all(
                            getattr(r.spec.pset, "is_global", False)
                            for r in records))

    def _arm_locked(self, plan: StepPlan) -> None:
        self._plan = plan
        self._expect = {}
        self._held = {}
        self._matched = 0
        self._total = 0
        for ri, rec in enumerate(plan.records):
            for ei, tmpl in enumerate(rec.templates):
                seq = self._expect.setdefault(
                    tmpl.key, {"templates": [], "pos": 0})
                seq["templates"].append((ri, ei, tmpl))
                self._total += 1
        self._state = "replay"
        self._replaying = True

    # -- recording ---------------------------------------------------------

    def note_flush(self, spec, entries, trigger) -> None:
        """Record one drained flush's composition (record mode only; the
        flush still executes eagerly through its normal path)."""
        if not self._recording:
            return
        with self._mu:
            if self._state != "record":
                return
            templates = [
                _EntryTemplate(e.queue_key, e.grouped, e.count,
                               getattr(e, "sigs", None), e.names)
                for e in entries
            ]
            # capturability (kinds, sigs, svc homogeneity, name
            # uniqueness) is decided once at seal by _build_plan — the
            # recording just captures composition
            self._records.append(_FlushRecord(spec, templates, trigger))

    # -- replay ------------------------------------------------------------

    def offer(self, key, spec, entry) -> bool:
        """Replay-mode submission intake: match the entry against the
        recorded stream and hold it for the captured program. Returns
        True when consumed; False sends the entry down the normal queue
        path (replay off, or this submission just diverged)."""
        del spec
        if not self._replaying:
            return False
        run = plan = None
        fallback = None
        diverged = False
        with self._mu:
            if self._state != "replay":
                return False
            seq = self._expect.get(key)
            tmpl = None
            if seq is not None and seq["pos"] < len(seq["templates"]):
                ri, ei, tmpl = seq["templates"][seq["pos"]]
            if tmpl is None or not tmpl.matches(entry):
                # shape/dtype drift, a new tensor, or a different
                # composition: invalidate and fall back to eager
                fallback = self._take_held_locked()
                self._diverge_locked()
                diverged = True
            else:
                seq["pos"] += 1
                entry.captured = True
                self._held[(ri, ei)] = entry
                self._matched += 1
                if self._matched == self._total:
                    plan = self._plan
                    run = self._take_held_locked()
                    self._state = "replayed"
                    self._replaying = False
        if diverged:
            if fallback:
                self._run_fallback(fallback)
            return False
        if run is not None:
            self._execute_replay(plan, run)
        return True

    def _take_held_locked(self) -> list:
        """Held entries grouped per record in stream order (partial
        groups when taken mid-stream for a fallback)."""
        held, self._held = self._held, {}
        plan = self._plan
        if not held or plan is None:
            return []
        groups = []
        for ri, rec in enumerate(plan.records):
            es = [held[(ri, ei)] for ei in range(len(rec.templates))
                  if (ri, ei) in held]
            if es:
                groups.append((rec, es))
        return groups

    def _diverge_locked(self) -> None:
        # Lockstep decision point (docs/conformance.md): a divergence
        # fallback is itself rank-deterministic — the stream mismatched
        # identically everywhere (a rank-local fallback IS a finding).
        _conformance.record(
            "ops/step_capture.py::CaptureState._diverge_locked", "phase",
            (self._state, "bypass"))
        self._stats["fallbacks"] += 1
        self._stats["invalidations"] += 1
        _note_capture("fallback", state="bypass")
        _note_capture("invalidated")
        self._plan = None
        self._last_key = None
        self._expect = {}
        self._matched = self._total = 0
        self._state = "bypass"
        self._replaying = False

    def _run_fallback(self, groups) -> None:
        """Execute held entries eagerly with their recorded composition
        (the transparent-fallback contract: correct results, no hang)."""
        _timeline.record_capture("FALLBACK")
        svc_names = {n for _rec, es in groups for e in es
                     if e.requests for n in e.names}
        if svc_names:
            # same cross-step name-reuse guard the replay path applies:
            # an earlier step's pipelined flush may still hold one of
            # these names in an in-flight negotiation
            self._sched._wait_names_clear(svc_names)
        for i, (rec, es) in enumerate(groups):
            try:
                # _execute marks the entries failed itself on error, so
                # a bad flush surfaces at synchronize like any eager
                # flush; only non-Exception BaseExceptions escape it
                self._sched._execute(rec.spec, es)
            except BaseException as exc:
                # a KeyboardInterrupt/SystemExit mid-loop must not
                # orphan the remaining groups — they are out of _held
                # and out of every queue, so nothing else can ever
                # settle their waiters
                for _rec2, es2 in groups[i:]:
                    self._sched._fail_entries(es2, exc)
                raise

    def _execute_replay(self, plan: StepPlan, groups) -> None:
        """Issue the whole step's collective work as the one captured
        program and distribute results to the held entries."""
        entries = [e for _rec, es in groups for e in es]
        svc_names = {n for e in entries if e.requests for n in e.names}
        if svc_names:
            # Cross-step name reuse (a user name stable per call site):
            # an earlier step's pipelined flush may still hold the same
            # name in an in-flight negotiation — the eager path
            # serializes via this same guard, and skipping it would turn
            # the reuse into a DuplicateNameError from negotiate_step.
            self._sched._wait_names_clear(svc_names)
        try:
            # same re-entrancy section as every other dispatch path: a
            # collective enqueued from INSIDE the replay execution trips
            # enqueue's assert_outside under HVD_DEBUG_INVARIANTS
            # instead of silently corrupting composition
            with _inv.section("fusion-cycle-flush"), \
                    _timeline.op_range("step", "STEP_REPLAY"), \
                    _dispatch.dispatch_source("step"):
                outs = plan.execute([es for _rec, es in groups])
            _dispatch.note_step_hit()
            if plan.nbytes:
                _autotune.record(plan.nbytes)
        except BaseException as exc:
            self._sched._fail_entries(entries, exc)
            hvd_logging.error("step replay failed: %s", exc)
            with self._mu:
                self._stats["invalidations"] += 1
                _note_capture("invalidated")
                self._plan = None
                self._last_key = None
            if not isinstance(exc, Exception):
                raise
            return
        for (rec, es), rec_outs in zip(groups, outs):
            i = 0
            for e in es:
                e.results = list(rec_outs[i:i + e.count])
                i += e.count
                e.tensors = ()
                e.run = None
                e.event.set()
        with self._mu:
            self._stats["replayed_steps"] += 1
            self._stats["replayed_entries"] += len(entries)
        # Lockstep decision point (docs/conformance.md): the replayed
        # whole-step program executed — same record count everywhere.
        _conformance.record(
            "ops/step_capture.py::CaptureState._execute_replay",
            "replayed", (len(groups),))
        _note_capture("replayed", state="replayed")
        _timeline.record_capture("REPLAY_DONE")

    # -- interception / teardown -------------------------------------------

    def intercept_flush(self, entry, trigger) -> bool:
        """A held entry's flush request. Dispatch hints (the bucketed
        optimizer's ``Handle.flush()``, threshold/cycle triggers) defer
        to the captured program — capture intentionally batches them. A
        BLOCKING observation (synchronize/poll) before the stream
        completed is a divergence: everything held executes eagerly so
        the caller can never hang on a dispatch that would only fire at
        stream completion."""
        if not getattr(entry, "captured", False) or entry.done:
            return False
        if trigger not in _BLOCKING_TRIGGERS:
            return True
        fallback = None
        with self._mu:
            if self._state == "replay" \
                    and any(e is entry for e in self._held.values()):
                fallback = self._take_held_locked()
                self._diverge_locked()
        if fallback:
            self._run_fallback(fallback)
        return True

    def flush_pending(self, trigger: str) -> None:
        """``flush_all`` (barrier/shutdown/backpressure) mid-replay: the
        caller needs everything *dispatched* on return, so the held
        prefix executes eagerly — divergence by early drain."""
        del trigger
        fallback = None
        with self._mu:
            if self._state == "replay" and self._held:
                fallback = self._take_held_locked()
                self._diverge_locked()
        if fallback:
            self._run_fallback(fallback)

    def abort(self, reason: str) -> int:
        """Scheduler abort (service reset, elastic re-form,
        ``PeerFailureError`` teardown): fail every held entry and drop
        both the recording and the armed plan — the world the capture
        was recorded against no longer exists. Returns the number of
        entries failed."""
        with self._mu:
            held = list(self._held.values())
            self._held = {}
            self._expect = {}
            self._records = []
            if (self._plan is not None or self._last_key is not None
                    or self._state in ("record", "replay")):
                self._stats["invalidations"] += 1
                _note_capture("invalidated")
            self._plan = None
            self._last_key = None
            self._matched = self._total = 0
            self._state = "bypass" if self._region_open else "idle"
            self._replaying = self._recording = False
        n = 0
        for e in held:
            if not e.done:
                e.error = RuntimeError(
                    f"captured collective {e.label!r} aborted: {reason}")
                e.tensors = ()
                e.run = None
                e.event.set()
                n += 1
        return n

    # -- stats -------------------------------------------------------------

    def stats(self) -> dict:
        with self._mu:
            out = dict(self._stats)
            out["enabled"] = self.enabled()
            out["state"] = self._state
            out["held_entries"] = len(self._held)
            out["armed"] = self._plan is not None
            return out

    def reset_stats(self) -> None:
        with self._mu:
            self._stats = {k: 0 for k in self._stats}


# ---------------------------------------------------------------------------
# public API (exported as hvd.step_marker)
# ---------------------------------------------------------------------------

class _Region:
    """Handle returned by :func:`step_marker`: usable bare (the call
    itself marked the boundary) or as a context manager closing the
    region on exit."""

    __slots__ = ("_cap",)

    def __init__(self, cap):
        self._cap = cap

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._cap.boundary(closing=True)
        return False


def step_marker() -> _Region:
    """Mark a training-step boundary for capture-and-replay
    (``HVD_STEP_CAPTURE``; docs/step_capture.md). Call once per loop
    iteration — each call seals/verifies the previous step region and
    opens the next — or use ``with hvd.step_marker():`` around the step
    body to close the region explicitly. A no-op (beyond closing an open
    region) while the knob is off."""
    from . import fusion_cycle
    cap = fusion_cycle.scheduler().capture
    cap.boundary()
    return _Region(cap)


class _AutoRegion:
    """The boundary pair ``optim.DistributedOptimizer`` wraps its eager
    bucketed gradient sync in: opens a capture region only when the knob
    is on and no user region is already active, so an explicit
    ``hvd.step_marker()`` spanning the whole step always wins."""

    __slots__ = ("_cap",)

    def __init__(self):
        self._cap = None

    def __enter__(self):
        from . import fusion_cycle
        cap = fusion_cycle.scheduler().capture
        if cap.enabled() and not cap.region_open():
            self._cap = cap
            cap.boundary()
        return self

    def __exit__(self, *exc):
        if self._cap is not None:
            self._cap.boundary(closing=True)
            self._cap = None
        return False


def auto_region() -> _AutoRegion:
    return _AutoRegion()
