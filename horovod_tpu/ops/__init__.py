from .reduce_ops import Adasum, Average, Max, Min, Product, ReduceOp, Sum
from .compression import Compression
from .collectives import (
    Handle,
    PerRank,
    allgather,
    allgather_async,
    allgather_object,
    allreduce,
    allreduce_async,
    alltoall,
    alltoall_async,
    barrier,
    broadcast,
    broadcast_async,
    broadcast_object,
    grouped_allreduce,
    grouped_allreduce_async,
    grouped_broadcast,
    grouped_broadcast_async,
    join,
    per_rank,
    poll,
    reducescatter,
    synchronize,
)
from .dispatch_cache import reset as reset_dispatch_cache
from .dispatch_cache import stats as dispatch_cache_stats
from .fusion_cycle import fusion_flush
from .fusion_cycle import reset as reset_fusion_cycle
from .fusion_cycle import stats as fusion_stats
from .gspmd_cache import cached_step
from .gspmd_cache import stats as gspmd_cache_stats
from .step_capture import step_marker
from .adasum import adasum_allreduce
from .hierarchical import (
    hierarchical_allgather,
    hierarchical_allreduce,
    hierarchical_mesh,
)
from .sparse import (
    SparseRows,
    rows_from_dense,
    rows_to_dense,
    sparse_allreduce,
    sparse_allreduce_async,
    sparse_allreduce_to_dense,
)

__all__ = [
    "Adasum", "Average", "Max", "Min", "Product", "ReduceOp", "Sum",
    "Compression", "Handle", "PerRank", "allgather", "allgather_async",
    "allgather_object", "allreduce", "allreduce_async", "alltoall",
    "alltoall_async", "barrier", "broadcast", "broadcast_async",
    "broadcast_object", "grouped_allreduce", "grouped_allreduce_async",
    "grouped_broadcast", "grouped_broadcast_async", "join", "per_rank",
    "poll", "reducescatter", "synchronize", "adasum_allreduce",
    "dispatch_cache_stats", "reset_dispatch_cache",
    "cached_step", "gspmd_cache_stats",
    "fusion_flush", "fusion_stats", "reset_fusion_cycle", "step_marker",
    "hierarchical_allgather", "hierarchical_allreduce", "hierarchical_mesh",
    "SparseRows", "rows_from_dense", "rows_to_dense", "sparse_allreduce", "sparse_allreduce_async",
    "sparse_allreduce_to_dense",
]
