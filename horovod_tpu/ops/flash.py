"""Pallas flash-attention block kernel for the sequence-parallel hot path.

The ring/Ulysses schedules (:mod:`horovod_tpu.parallel.sequence`) spend
their FLOPs in the blockwise online-softmax update. The jnp formulation
materializes the (batch, heads, sq, sk) logits in HBM every ring step;
this kernel keeps the whole update — QKᵀ, masking, the online-softmax
rescale, and the PV accumulation — in VMEM, one pass per (batch × head)
program, so HBM traffic per step drops from O(sq·sk) logits to the K/V
blocks themselves (the flash-attention I/O shape, which is what the MXU
needs to stay busy on long sequences).

The kernel carries the running (m, l, acc) statistics **between**
invocations, so the ring loop can rotate K/V with ``ppermute`` and call it
once per step. Inside one invocation the grid tiles BOTH dimensions —
(batch·head, q-tile, kv-tile), the kv sweep innermost so the VMEM scratch
carries per q-tile — bounding VMEM at O(q_tile·d) instead of O(sq·d) and
extending the kernel to sequence blocks far beyond one tile.

Backward: BOTH schedules' custom VJPs (the ring's re-rotating backward
and the Ulysses/local one) route through :func:`flash_block_grads` — a dq
pass sweeping kv tiles innermost and a dk/dv pass sweeping q tiles
innermost, logits recomputed per tile in VMEM — with
:func:`jnp_block_grads` (the same identities, KV-chunked) as the
non-Pallas fallback. ``block_attend``'s own ``custom_vjp`` (jnp recompute
of one block update) only covers code that differentiates the op
directly. CPU tests run every kernel with ``interpret=True``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def causal_mask_scores(s, qpos0, kpos0):
    """Mask future positions of a (bh|, sq, sk) score block to the NEG_INF
    sentinel. ``qpos0``/``kpos0`` are int32 global offsets of the blocks
    (int — f32 cannot represent token offsets past 2^24)."""
    sq, sk = s.shape[-2], s.shape[-1]
    qpos = qpos0 + jnp.arange(sq, dtype=jnp.int32)
    kpos = kpos0 + jnp.arange(sk, dtype=jnp.int32)
    keep = qpos[:, None] >= kpos[None, :]
    return jnp.where(jnp.expand_dims(keep, 0) if s.ndim == 3 else keep,
                     s, NEG_INF)


def zero_masked(p, s):
    """Zero softmax weights at sentinel-masked score positions. When every
    position seen so far is masked, the running max is still the NEG_INF
    sentinel and ``s - m == 0`` there — exp(0)=1 would silently admit
    garbage V rows. Zeroing explicitly makes any block visit order safe
    (a fully-masked row just keeps l == 0). Must stay in lockstep with
    the same guard inside the Pallas kernel (:func:`_flash_kernel`)."""
    return jnp.where(s > NEG_INF / 2, p, 0.0)


def _attend_jnp(q, k, v, qpos0, kpos0, causal, m, l, acc):
    """Reference jnp formulation of one block update (also the backward's
    recompute target). Shapes: q (bh, sq, d); k/v (bh, sk, d); m/l
    (bh, sq, 1); acc (bh, sq, d); qpos0/kpos0 int32 scalars."""
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32)
    if causal:
        s = causal_mask_scores(s, qpos0, kpos0)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    if causal:
        p = zero_masked(p, s)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * corr + jnp.einsum(
        "bqk,bkd->bqd", p.astype(v.dtype), v).astype(jnp.float32)
    return m_new, l_new, acc_new


DEFAULT_KV_TILE = 512
DEFAULT_Q_TILE = 1024  # bounds VMEM: scratch is O(q_tile*d), not O(sq*d)


def _tile_causal_mask(s, qpos_ref, kpos_ref, qi, j, q_tile, kv_tile):
    """Causal mask for one (q-tile, kv-tile) score block — THE masking
    rule, shared by the forward and both backward kernels so they cannot
    drift (the jnp twin is :func:`causal_mask_scores`). Mosaic iota must
    be integer-typed; int32 offsets are exact past 2^24."""
    tq, sk = s.shape
    qpos = (qpos_ref[0] + qi * q_tile
            + jax.lax.broadcasted_iota(jnp.int32, (tq, sk), 0))
    kpos = (kpos_ref[0] + j * kv_tile
            + jax.lax.broadcasted_iota(jnp.int32, (tq, sk), 1))
    return jnp.where(qpos >= kpos, s, NEG_INF)


def _flash_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref, m_ref, l_ref,
                  acc_ref, mo_ref, lo_ref, acco_ref, m_s, l_s, acc_s, *,
                  causal, q_tile, kv_tile, sk_valid):
    qi = pl.program_id(1)  # q-tile index (kv sweep is the innermost dim,
    j = pl.program_id(2)   # so scratch carries are per-(bh, q-tile))
    n_kv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():  # load this q-tile's incoming carries into scratch
        m_s[:] = m_ref[0]
        l_s[:] = l_ref[0]
        acc_s[:] = acc_ref[0]

    q = q_ref[0]          # (q_tile, d)
    k = k_ref[0]          # (kv_tile, d)
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # (q_tile, kv_tile), MXU
    if causal:
        s = _tile_causal_mask(s, qpos_ref, kpos_ref, qi, j, q_tile, kv_tile)
    if sk_valid is not None:
        s = _tile_pad_mask(s, j, kv_tile, sk_valid)
    m_prev = m_s[:]       # (q_tile, 1) f32
    l_prev = l_s[:]
    acc_prev = acc_s[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    if causal or sk_valid is not None:
        # fully-masked rows: m_new may still be the NEG_INF sentinel, making
        # exp(s - m_new) == 1 at masked entries — zero them (see _attend_jnp)
        p = jnp.where(s > NEG_INF / 2, p, 0.0)
    corr = jnp.exp(m_prev - m_new)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_s[:] = m_new
    l_s[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_s[:] = acc_prev * corr + pv

    @pl.when(j == n_kv - 1)
    def _flush():
        mo_ref[0] = m_s[:]
        lo_ref[0] = l_s[:]
        acco_ref[0] = acc_s[:]


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _tile_pad(size: int, default: int) -> tuple[int, int]:
    """``(tile, padded)``: tile <= default, ``padded`` the next tile
    multiple covering ``size``. Awkward (prime-ish) sizes PAD to the next
    tile boundary instead of shrinking the tile to a divisor — a divisor
    search hands e.g. sq=8191 a tile of 1, a grid of 1-row MXU ops and a
    Mosaic layout cliff (ADVICE r4). The padded tail is masked to the
    NEG_INF sentinel via ``sk_valid`` (kv) or zero inputs (q); sub-default
    sizes round up to the fp32 sublane quantum (8) so Mosaic gets an
    aligned block."""
    if size >= default:
        # A size just past a tile boundary would pay up to ~2x padded
        # compute at the full default tile (e.g. 1025 -> 2048): try the
        # default and two halvings, keep the least total padding (larger
        # tile on ties — fewer grid steps).
        cands = [t for t in (default, default // 2, default // 4)
                 if t >= 8] or [default]
        tile = min(cands, key=lambda t: (_round_up(size, t), -t))
        return tile, _round_up(size, tile)
    t = _round_up(size, 8)
    return t, t


def _pad_dim1(x, target: int):
    """Zero-pad dim 1 (the sequence dim of a (bh, s, d) block) to target."""
    if x.shape[1] == target:
        return x
    return jnp.pad(x, ((0, 0), (0, target - x.shape[1]), (0, 0)))


def _tile_pad_mask(s, j, kv_tile, sk_valid):
    """NEG_INF-mask score columns past the true (pre-padding) kv length.
    Shared by the forward and both backward kernels, like the causal
    twin :func:`_tile_causal_mask`."""
    tq, tk = s.shape
    kcol = j * kv_tile + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
    return jnp.where(kcol < sk_valid, s, NEG_INF)


def _flash_call(q, k, v, qpos0, kpos0, causal, m, l, acc, interpret):
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = q.shape
    sk = k.shape[1]
    kv_tile, sk_p = _tile_pad(sk, DEFAULT_KV_TILE)
    q_tile, sq_p = _tile_pad(sq, DEFAULT_Q_TILE)
    # Zero-pad to the tile grid; padded kv columns are NEG_INF-masked in
    # the kernel (sk_valid) and padded q rows are sliced off below (their
    # carries are well-defined: zero q rows give s=0 scores, no NaNs).
    q, k, v = _pad_dim1(q, sq_p), _pad_dim1(k, sk_p), _pad_dim1(v, sk_p)
    m, l, acc = (_pad_dim1(m, sq_p), _pad_dim1(l, sq_p),
                 _pad_dim1(acc, sq_p))
    n_kv = sk_p // kv_tile
    n_q = sq_p // q_tile
    kernel = functools.partial(_flash_kernel, causal=causal,
                               q_tile=q_tile, kv_tile=kv_tile,
                               sk_valid=sk if sk_p != sk else None)
    out = pl.pallas_call(
        kernel,
        grid=(bh, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1,), lambda i, qi, j: (0,)),       # qpos0
            pl.BlockSpec((1,), lambda i, qi, j: (0,)),       # kpos0
            pl.BlockSpec((1, q_tile, d), lambda i, qi, j: (i, qi, 0)),
            pl.BlockSpec((1, kv_tile, d), lambda i, qi, j: (i, j, 0)),
            pl.BlockSpec((1, kv_tile, d), lambda i, qi, j: (i, j, 0)),
            pl.BlockSpec((1, q_tile, 1), lambda i, qi, j: (i, qi, 0)),
            pl.BlockSpec((1, q_tile, 1), lambda i, qi, j: (i, qi, 0)),
            pl.BlockSpec((1, q_tile, d), lambda i, qi, j: (i, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, q_tile, 1), lambda i, qi, j: (i, qi, 0)),
            pl.BlockSpec((1, q_tile, 1), lambda i, qi, j: (i, qi, 0)),
            pl.BlockSpec((1, q_tile, d), lambda i, qi, j: (i, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq_p, 1), jnp.float32),
            jax.ShapeDtypeStruct((bh, sq_p, 1), jnp.float32),
            jax.ShapeDtypeStruct((bh, sq_p, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((q_tile, 1), jnp.float32),
            pltpu.VMEM((q_tile, 1), jnp.float32),
            pltpu.VMEM((q_tile, d), jnp.float32),
        ],
        interpret=interpret,
    )(jnp.asarray([qpos0], jnp.int32).reshape(1),
      jnp.asarray([kpos0], jnp.int32).reshape(1),
      q, k, v, m, l, acc)
    if sq_p != sq:
        out = [o[:, :sq] for o in out]
    return tuple(out)


# --------------------------------------------------------------------------
# backward kernels: block gradients with the normalized-softmax identities
# (dV += pT.dO, dS = p o (dO.VT - D), dQ += dS.K, dK += dST.Q with
# p = exp(s - lse), D = rowsum(dO o O)) — the flash-attention backward.
# Two passes so each accumulator lives in VMEM: dQ sweeps kv tiles
# innermost, dK/dV sweep q tiles innermost. Logits are recomputed per tile
# and never reach HBM (the jnp fallback materializes the block logits).
# --------------------------------------------------------------------------


def _bwd_scores(q, k, qpos_ref, kpos_ref, lse, qi, j, q_tile, kv_tile,
                causal, sk_valid):
    """Recompute the normalized softmax block p = exp(s - lse), masked by
    the SAME :func:`_tile_causal_mask` / :func:`_tile_pad_mask` the
    forward kernel uses."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if causal:
        s = _tile_causal_mask(s, qpos_ref, kpos_ref, qi, j, q_tile, kv_tile)
    if sk_valid is not None:
        s = _tile_pad_mask(s, j, kv_tile, sk_valid)
    p = jnp.exp(s - lse)
    if causal or sk_valid is not None:
        p = jnp.where(s > NEG_INF / 2, p, 0.0)
    return p


def _flash_bwd_dq_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref, lse_ref,
                         d_ref, do_ref, dq_ref, dq_s, *, causal, q_tile,
                         kv_tile, sk_valid):
    qi = pl.program_id(1)
    j = pl.program_id(2)  # kv sweep innermost: dq accumulates per q tile
    n_kv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    p = _bwd_scores(q_ref[0], k_ref[0], qpos_ref, kpos_ref, lse_ref[0],
                    qi, j, q_tile, kv_tile, causal, sk_valid)
    do = do_ref[0]
    dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - d_ref[0])
    dq_s[:] += jax.lax.dot_general(
        ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(j == n_kv - 1)
    def _flush():
        dq_ref[0] = dq_s[:]


def _flash_bwd_dkv_kernel(qpos_ref, kpos_ref, q_ref, k_ref, v_ref, lse_ref,
                          d_ref, do_ref, dk_ref, dv_ref, dk_s, dv_s, *,
                          causal, q_tile, kv_tile, sk_valid):
    j = pl.program_id(1)
    qi = pl.program_id(2)  # q sweep innermost: dk/dv accumulate per kv tile
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    q = q_ref[0]
    p = _bwd_scores(q, k_ref[0], qpos_ref, kpos_ref, lse_ref[0],
                    qi, j, q_tile, kv_tile, causal, sk_valid)
    do = do_ref[0]
    dv_s[:] += jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - d_ref[0])
    dk_s[:] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _flush():
        dk_ref[0] = dk_s[:]
        dv_ref[0] = dv_s[:]


def jnp_block_grads(qf, kf, vf, lse, dout, D, qpos0, kpos0, causal,
                    kv_chunk: int | None = None):
    """jnp twin of :func:`flash_block_grads` — the flash backward
    identities, shared by the ring and local custom VJPs so the two
    backward paths cannot drift. ``kv_chunk`` bounds peak logits memory
    at O(sq·kv_chunk) by looping KV slabs (None = one slab)."""
    sk = kf.shape[1]
    chunk = sk if not kv_chunk else min(kv_chunk, sk)
    if sk % chunk:
        chunk = sk
    dq = jnp.zeros(qf.shape[:2] + (qf.shape[2],), jnp.float32)
    dks, dvs = [], []
    for off in range(0, sk, chunk):
        k_c = kf[:, off:off + chunk]
        v_c = vf[:, off:off + chunk]
        s = jnp.einsum("bqd,bkd->bqk", qf, k_c,
                       preferred_element_type=jnp.float32)
        if causal:
            s = causal_mask_scores(s, qpos0, kpos0 + off)
        p = jnp.exp(s - lse)  # normalized attention weights
        if causal:
            p = zero_masked(p, s)
        dvs.append(jnp.einsum("bqk,bqd->bkd", p, dout,
                              preferred_element_type=jnp.float32))
        dp = jnp.einsum("bqd,bkd->bqk", dout, v_c.astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        ds = p * (dp - D)
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, k_c.astype(jnp.float32),
                             preferred_element_type=jnp.float32)
        dks.append(jnp.einsum("bqk,bqd->bkd", ds, qf.astype(jnp.float32),
                              preferred_element_type=jnp.float32))
    return dq, jnp.concatenate(dks, axis=1), jnp.concatenate(dvs, axis=1)


def flash_block_grads(q, k, v, lse, dout, D, qpos0, kpos0, causal,
                      interpret=False):
    """Pallas block gradients for the ring/local flash backward:
    ``(dq, dk, dv)`` for one K/V block against the full saved ``lse``.
    Shapes: q/dout (bh, sq, d); k/v (bh, sk, d); lse/D (bh, sq, 1), with
    ``D = rowsum(dout * out)``. Float32 outputs. The jnp equivalent is the
    einsum block in :func:`horovod_tpu.parallel.sequence._ring_core_bwd`.
    """
    from jax.experimental.pallas import tpu as pltpu

    bh, sq, d = q.shape
    sk = k.shape[1]
    q_tile, sq_p = _tile_pad(sq, DEFAULT_Q_TILE)
    kv_tile, sk_p = _tile_pad(sk, DEFAULT_KV_TILE)
    sk_valid = sk if sk_p != sk else None
    # Zero-pad to the tile grid (see _flash_call): padded kv columns are
    # sk_valid-masked; padded q rows contribute nothing because dout (and
    # hence dp, ds, and the dv outer product) is zero there.
    q, dout = _pad_dim1(q, sq_p), _pad_dim1(dout, sq_p)
    lse, D = _pad_dim1(lse, sq_p), _pad_dim1(D, sq_p)
    k, v = _pad_dim1(k, sk_p), _pad_dim1(v, sk_p)
    n_q, n_kv = sq_p // q_tile, sk_p // kv_tile
    qpos0 = jnp.asarray([qpos0], jnp.int32).reshape(1)
    kpos0 = jnp.asarray([kpos0], jnp.int32).reshape(1)
    pos_spec = pl.BlockSpec((1,), lambda i, a, b: (0,))

    def q_spec_dq(which):  # blocks indexed by the q-tile grid position
        return pl.BlockSpec((1, q_tile, which),
                            lambda i, qi, j: (i, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal,
                          q_tile=q_tile, kv_tile=kv_tile, sk_valid=sk_valid),
        grid=(bh, n_q, n_kv),
        in_specs=[pos_spec, pos_spec,
                  q_spec_dq(d),
                  pl.BlockSpec((1, kv_tile, d), lambda i, qi, j: (i, j, 0)),
                  pl.BlockSpec((1, kv_tile, d), lambda i, qi, j: (i, j, 0)),
                  q_spec_dq(1), q_spec_dq(1), q_spec_dq(d)],
        out_specs=q_spec_dq(d),
        out_shape=jax.ShapeDtypeStruct((bh, sq_p, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((q_tile, d), jnp.float32)],
        interpret=interpret,
    )(qpos0, kpos0, q, k, v, lse, D, dout)

    kv_spec = pl.BlockSpec((1, kv_tile, d), lambda i, j, qi: (i, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal,
                          q_tile=q_tile, kv_tile=kv_tile, sk_valid=sk_valid),
        grid=(bh, n_kv, n_q),
        in_specs=[pos_spec, pos_spec,
                  pl.BlockSpec((1, q_tile, d), lambda i, j, qi: (i, qi, 0)),
                  kv_spec, kv_spec,
                  pl.BlockSpec((1, q_tile, 1), lambda i, j, qi: (i, qi, 0)),
                  pl.BlockSpec((1, q_tile, 1), lambda i, j, qi: (i, qi, 0)),
                  pl.BlockSpec((1, q_tile, d), lambda i, j, qi: (i, qi, 0))],
        out_specs=[kv_spec, kv_spec],
        out_shape=[jax.ShapeDtypeStruct((bh, sk_p, d), jnp.float32),
                   jax.ShapeDtypeStruct((bh, sk_p, d), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((kv_tile, d), jnp.float32),
                        pltpu.VMEM((kv_tile, d), jnp.float32)],
        interpret=interpret,
    )(qpos0, kpos0, q, k, v, lse, D, dout)
    return dq[:, :sq], dk[:, :sk], dv[:, :sk]


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def block_attend(q, k, v, qpos0, kpos0, causal, interpret, m, l, acc):
    """One flash block update: returns the new (m, l, acc) carries.

    Layout: q (bh, sq, d) pre-scaled; k/v (bh, sk, d); m/l (bh, sq, 1)
    float32; acc (bh, sq, d) float32; qpos0/kpos0 int32 scalars (global
    token offsets of the blocks for causal masking — integers, so offsets
    past 2^24 stay exact).
    """
    qpos0 = jnp.asarray(qpos0, jnp.int32)
    kpos0 = jnp.asarray(kpos0, jnp.int32)
    return _flash_call(q, k, v, qpos0, kpos0, causal, m, l, acc, interpret)


def _block_attend_fwd(q, k, v, qpos0, kpos0, causal, interpret, m, l, acc):
    out = block_attend(q, k, v, qpos0, kpos0, causal, interpret, m, l, acc)
    return out, (q, k, v, qpos0, kpos0, m, l, acc)


def _block_attend_bwd(causal, interpret, res, cts):
    import numpy as np

    q, k, v, qpos0, kpos0, m, l, acc = res
    # flash-style backward: recompute the block through the jnp
    # formulation and differentiate that (nothing but the carries saved)
    _, vjp = jax.vjp(
        lambda q, k, v, m, l, acc: _attend_jnp(
            q, k, v, qpos0, kpos0, causal, m, l, acc),
        q, k, v, m, l, acc)
    dq, dk, dv, dm, dl, dacc = vjp(tuple(cts))
    zero_int = np.zeros((), jax.dtypes.float0)  # int operands: float0 ct
    return dq, dk, dv, zero_int, zero_int, dm, dl, dacc


block_attend.defvjp(_block_attend_fwd, _block_attend_bwd)


def supported() -> bool:
    """Whether the compiled kernel path is enabled: TPU backend and the
    ``HVD_FLASH_ATTENTION`` knob set. Opt-in because on v5e XLA's own
    fusion of the jnp formulation measures within ~10% of this kernel
    (e.g. bf16 bh=16 sq=sk=2048 d=128: 4.6 ms pallas vs 4.2 ms XLA) —
    the kernel's value is its bounded VMEM footprint (logits never
    materialize in HBM), which matters for very long blocks, and explicit
    control for future tuning."""
    from ..utils import envs
    return (jax.default_backend() == "tpu"
            and envs.get_bool(envs.FLASH_ATTENTION))
