"""Reduction-op enums and scaling semantics.

Mirrors the reference's ReduceOp surface (``hvd.Average``/``Sum``/``Adasum``/
``Min``/``Max``/``Product``, defined per-framework e.g.
``/root/reference/horovod/torch/mpi_ops.py`` and dispatched in
``EnqueueTensorAllreduces`` at
``/root/reference/horovod/common/operations.cc:1384-1512``, where Average is
implemented as Sum + postscale 1/size, ``operations.cc:1408-1416``).
"""

from __future__ import annotations

import enum


class ReduceOp(enum.IntEnum):
    AVERAGE = 0
    SUM = 1
    ADASUM = 2
    MIN = 3
    MAX = 4
    PRODUCT = 5


# Horovod-style module constants.
Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


def handle_average(op: ReduceOp, size: int, postscale_factor: float) -> tuple[ReduceOp, float]:
    """Lower AVERAGE to SUM + postscale (reference operations.cc:1408-1416)."""
    if op == ReduceOp.AVERAGE:
        return ReduceOp.SUM, postscale_factor / size
    return op, postscale_factor
