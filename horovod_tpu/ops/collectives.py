"""Collective operations over the rank mesh.

TPU-native rebuild of the reference's collective op layer
(``/root/reference/horovod/common/ops/collective_operations.h:38-308`` and the
enqueue API ``EnqueueTensorAllreduce(s)/Allgather/Broadcast/Alltoall/Barrier``
at ``/root/reference/horovod/common/operations.cc:1357-1795``), with the
design inversion of SURVEY.md §7: the XLA compiler — not a background
runtime thread — schedules collectives.

Two execution modes:

* **Traced mode** — the call happens inside user code already running under
  ``jax.shard_map`` (or ``pmap``) with the runtime's mesh axis bound. The op
  lowers directly to ``lax.psum``/``all_gather``/``all_to_all``/
  ``psum_scatter``; XLA fuses and overlaps them (this replaces the
  reference's fusion buffer + cycle machinery for the jit hot path).
  Process-set subsets lower to ``axis_index_groups`` partitions.

* **Eager mode** — the call happens on concrete arrays. Per-rank inputs are
  carried in a :class:`PerRank` bundle (leading axis = ranks, sharded across
  chips); the op runs a cached ``jit(shard_map(...))`` over the process
  set's sub-mesh. A plain (unbundled) array is treated as the same value
  contributed by every rank.

Eager collectives return plain arrays when the result is identical on every
rank (allreduce/allgather/broadcast) and :class:`PerRank` bundles when it
differs (alltoall/reducescatter).
"""

from __future__ import annotations

import functools
import pickle
import threading
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import autotune as _autotune
from .. import runtime
from .. import timeline as _timeline
from ..loopback import dispatch as _lb
from ..dynamic import (
    REQ_ALLGATHER,
    REQ_ALLREDUCE,
    REQ_ALLTOALL,
    REQ_BARRIER,
    REQ_BROADCAST,
    REQ_REDUCESCATTER,
)
from ..process_sets import ProcessSet, _resolve
from . import dispatch_cache as _dispatch
from . import hierarchical
from .program_issue import issue_serialized as _issue_serialized
from .reduce_ops import ReduceOp, handle_average
from ..utils import compat as _compat
from ..utils import envs
from ..utils import logging as hvd_logging


class PerRank:
    """Bundle of per-rank values: ``array[i]`` is rank *i*'s tensor (ranks
    ordered by position in the process set). The eager-mode analog of "each
    Horovod rank passes its local tensor".

    ``dim0s`` is set when the per-rank tensors have *different first
    dimensions* (the reference's ragged allgather/alltoall contract,
    ``collective_operations.h:143-178``): ``array`` is zero-padded to the
    max dim0 and ``dim0s[i]`` is rank *i*'s valid row count. ``None``
    means uniform."""

    __slots__ = ("array", "dim0s")

    def __init__(self, array, dim0s=None):
        self.array = array
        self.dim0s = tuple(int(d) for d in dim0s) if dim0s is not None \
            else None

    @property
    def shape(self):
        return self.array.shape

    @property
    def dtype(self):
        return self.array.dtype

    def __len__(self):
        return self.array.shape[0]

    def __getitem__(self, i):
        return self.array[i]

    def to_list(self):
        if self.dim0s is not None:
            return [self.array[i, :self.dim0s[i]]
                    for i in range(self.array.shape[0])]
        return [self.array[i] for i in range(self.array.shape[0])]

    def __repr__(self):
        ragged = f", dim0s={self.dim0s}" if self.dim0s is not None else ""
        return (f"PerRank(shape={tuple(self.array.shape)}, "
                f"dtype={self.array.dtype}{ragged})")


def per_rank(values, process_set: ProcessSet | None = None) -> PerRank:
    """Build a :class:`PerRank` bundle from a sequence of per-rank arrays
    (or an array whose leading axis already indexes ranks), sharded one
    slice per chip of the process set. Per-rank arrays whose *first*
    dimensions differ (trailing dims must match) produce a ragged bundle:
    zero-padded to the max first dim with ``dim0s`` recording the valid
    row counts — the input shape for ragged :func:`allgather` /
    :func:`alltoall`."""
    pset = _resolve(process_set)
    n = pset.size()
    dim0s = None
    if isinstance(values, (list, tuple)):
        arrs = [jnp.asarray(v) for v in values]
        if len(arrs) != n:
            raise ValueError(
                f"per_rank got {len(arrs)} arrays for process set size {n}")
        rests = {a.shape[1:] for a in arrs}
        ndims = {a.ndim for a in arrs}
        if len(ndims) > 1 or len(rests) > 1:
            raise ValueError(
                "per_rank arrays must agree on every dimension except the "
                f"first, got shapes {[tuple(a.shape) for a in arrs]}")
        d0s = [a.shape[0] if a.ndim else 1 for a in arrs]
        if arrs[0].ndim and len(set(d0s)) > 1:
            maxd = max(d0s)
            arrs = [jnp.concatenate(
                        [a, jnp.zeros((maxd - a.shape[0],) + a.shape[1:],
                                      a.dtype)]) if a.shape[0] < maxd else a
                    for a in arrs]
            dim0s = d0s
        arr = jnp.stack(arrs)
    else:
        arr = jnp.asarray(values)
    if arr.shape[0] != n:
        raise ValueError(
            f"per_rank leading axis {arr.shape[0]} != process set size {n}")
    sharding = NamedSharding(pset.mesh(), P(runtime.axis_name()))
    return PerRank(jax.device_put(arr, sharding), dim0s)


# ---------------------------------------------------------------------------
# mode detection
# ---------------------------------------------------------------------------

try:
    from jax.core import Tracer as _Tracer
except (ImportError, AttributeError):  # pragma: no cover
    from jax._src.core import Tracer as _Tracer


def _contains_tracer(x) -> bool:
    if isinstance(x, PerRank):
        x = x.array
    return isinstance(x, _Tracer)


def _axis_is_bound(axis) -> bool:
    """True when `axis` is a bound mapped axis (we are inside shard_map/pmap
    traced code). Outside any such context ``lax.axis_index`` raises
    NameError."""
    try:
        lax.axis_index(axis)
        return True
    except NameError:
        return False
    except TypeError:
        return False


def _resolve_axis(axis_name):
    return runtime.axis_name() if axis_name is None else axis_name


# ---------------------------------------------------------------------------
# traced-mode primitives (shared by eager inners, which run traced under
# shard_map over the sub-mesh with groups=None)
# ---------------------------------------------------------------------------

def _reduce(x, axis, op: ReduceOp, groups):
    if op == ReduceOp.SUM:
        return lax.psum(x, axis, axis_index_groups=groups)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis, axis_index_groups=groups)
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis, axis_index_groups=groups)
    if op == ReduceOp.PRODUCT:
        if groups is not None:
            # ring reduce-scatter + ring allgather over the member chips:
            # 2(k-1)/k·|x| per member, the allreduce bandwidth optimum,
            # matching the subset allgather/alltoall rings (r3 VERDICT
            # weak #7 replaced the k·|x| gather-then-multiply). Non-members
            # keep their own value (singleton-group semantics).
            members = list(groups[0])
            prod = _product_ring(x, axis, members)
            member = jnp.isin(lax.axis_index(axis), jnp.array(members))
            return jnp.where(member, prod, x)
        g = lax.all_gather(x, axis)
        return jnp.prod(g, axis=0)
    if op == ReduceOp.ADASUM:
        from .adasum import adasum_reduce
        return adasum_reduce(x, axis, groups)
    raise ValueError(f"unsupported reduce op {op!r}")


def _product_ring(x, axis, ranks):
    """Bandwidth-optimal PRODUCT allreduce over the member chips of a
    process set: classic ring reduce-scatter (k-1 multiply-forward steps
    on 1/k-size chunks) followed by a ring allgather of the reduced
    chunks — 2(k-1)/k·|x| per member for any k (XLA has no product
    allreduce primitive, so the schedule is explicit like the file's
    other member rings). Non-member lanes compute garbage that the caller
    masks out."""
    k = len(ranks)
    if k == 1:
        return x
    orig_dtype = x.dtype
    xv = x.astype(jnp.int8) if orig_dtype == jnp.bool_ else x
    shape = xv.shape
    flat = xv.reshape(-1)
    n = flat.shape[0]
    chunk = -(-n // k)  # ceil
    flat = jnp.pad(flat, (0, k * chunk - n),
                   constant_values=jnp.ones((), xv.dtype))  # prod identity
    pos = _member_pos(axis, ranks)
    perm = [(ranks[i], ranks[(i + 1) % k]) for i in range(k)]

    def chunk_at(idx):
        return lax.dynamic_slice_in_dim(flat, (idx % k) * chunk, chunk)

    # reduce-scatter: after step s each member's carry holds the partial
    # product of chunk (pos - s - 1); after k-1 steps member p owns the
    # fully reduced chunk (p + 1) % k
    cur = chunk_at(pos)
    for s in range(k - 1):
        cur = lax.ppermute(cur, axis, perm) * chunk_at(pos - s - 1)

    # allgather the reduced chunks around the same ring
    out = jnp.zeros((k * chunk,), xv.dtype)
    own_idx = (pos + 1) % k
    out = lax.dynamic_update_slice_in_dim(out, cur, own_idx * chunk, 0)
    rolling = cur
    for s in range(1, k):
        rolling = lax.ppermute(rolling, axis, perm)
        src_idx = (pos - s + 1) % k
        out = lax.dynamic_update_slice_in_dim(out, rolling,
                                              src_idx * chunk, 0)
    out = out[:n].reshape(shape)
    return out.astype(orig_dtype)


def _axis_denominator(x, axis, groups):
    """Number of participants in this member's reduction group: the bound
    axis size (NOT the world size — the user may reduce over a sub-axis of
    a multi-dim mesh), or the group size under a process-set partition."""
    return lax.psum(jnp.ones((), jnp.float32), axis, axis_index_groups=groups)


def _allreduce_traced(x, axis, op, pre, post, groups):
    if pre != 1.0:
        x = x * pre
    if op == ReduceOp.AVERAGE:
        out = lax.psum(x, axis, axis_index_groups=groups)
        out = out / _axis_denominator(x, axis, groups).astype(out.dtype)
    else:
        out = _reduce(x, axis, op, groups)
    if post != 1.0:
        out = out * post
    return out


def _allgather_traced(x, axis, groups, ranks, pset_size):
    if groups is None:
        return lax.all_gather(x, axis, tiled=True)
    # Subset gather as a ring of ppermutes over the member chips only
    # (lax.all_gather requires equal-size axis_index_groups, which a
    # members+singletons partition is not). Each member moves (k-1)*|x|
    # over the ring — the bandwidth-optimal allgather schedule — and
    # non-members move nothing, vs the O(world*k*|x|) zero-padded psum
    # this replaces (r2 VERDICT weak #4).
    k = pset_size
    pos = _member_pos(axis, ranks)  # my slot in the set
    d0 = x.shape[0]
    orig_dtype = x.dtype
    if orig_dtype == jnp.bool_:
        x = x.astype(jnp.int8)
    out = jnp.zeros((k * d0,) + x.shape[1:], dtype=x.dtype)
    out = lax.dynamic_update_slice(
        out, x, (pos * d0,) + (0,) * (x.ndim - 1))
    perm = [(ranks[i], ranks[(i + 1) % k]) for i in range(k)]
    cur = x
    for step in range(1, k):
        cur = lax.ppermute(cur, axis, perm)
        src_pos = (pos - step) % k
        out = lax.dynamic_update_slice(
            out, cur, (src_pos * d0,) + (0,) * (x.ndim - 1))
    return out.astype(orig_dtype)


def _broadcast_traced(x, axis, root_rank, groups, ranks):
    idx = lax.axis_index(axis)
    orig_dtype = x.dtype
    xv = x.astype(jnp.int8) if orig_dtype == jnp.bool_ else x
    masked = jnp.where(idx == root_rank, xv, jnp.zeros_like(xv))
    out = lax.psum(masked, axis, axis_index_groups=groups)
    if groups is not None:
        member = jnp.isin(idx, jnp.array(ranks))
        out = jnp.where(member, out, xv)
    if orig_dtype == jnp.bool_:
        out = out.astype(jnp.bool_)
    return out


def _member_pos(axis, ranks):
    """This chip's position within the sorted member list (garbage for
    non-members — their lanes are excluded from the member perms)."""
    idx = lax.axis_index(axis)
    return jnp.sum((jnp.array(ranks) < idx).astype(jnp.int32))


def _alltoall_traced(x, axis, groups):
    if groups is None:
        return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=True)
    # Subset alltoall as k-1 chunk rotations over the member ring
    # (lax.all_to_all needs the whole axis). Chunk j of member i travels
    # j-i hops forward; bandwidth (k-1)/k·|x| per member, the alltoall
    # optimum. Non-member lanes produce garbage (never consumed).
    ranks = list(groups[0])
    k = len(ranks)
    if x.shape[0] % k:
        raise ValueError(
            f"alltoall dim0 ({x.shape[0]}) must divide by the process-set "
            f"size ({k})")
    chunk = x.shape[0] // k
    pos = _member_pos(axis, ranks)
    out = jnp.zeros_like(x)
    own = lax.dynamic_slice_in_dim(x, pos * chunk, chunk)
    out = lax.dynamic_update_slice_in_dim(out, own, pos * chunk, 0)
    for r in range(1, k):
        # rotation r: my chunk for member (pos+r) travels r hops forward
        perm = [(ranks[i], ranks[(i + r) % k]) for i in range(k)]
        dest = (pos + r) % k
        send = lax.dynamic_slice_in_dim(x, dest * chunk, chunk)
        recv = lax.ppermute(send, axis, perm)
        src = (pos - r) % k
        out = lax.dynamic_update_slice_in_dim(out, recv, src * chunk, 0)
    return out


def _reducescatter_traced(x, axis, op, post, groups):
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise NotImplementedError("reducescatter supports SUM/AVERAGE")
    if groups is None:
        out = lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)
        if op == ReduceOp.AVERAGE:
            out = out / _axis_denominator(x, axis, groups).astype(out.dtype)
        if post != 1.0:
            out = out * post
        return out
    # Subset reduce-scatter as a k-1 step accumulate ring over the member
    # list: member p ends holding chunk p fully reduced, each chunk
    # visiting every member once ((k-1)/k·|x| per member — optimal).
    ranks = list(groups[0])
    k = len(ranks)
    if x.shape[0] % k:
        raise ValueError(
            f"reducescatter dim0 ({x.shape[0]}) must divide by the "
            f"process-set size ({k})")
    chunk = x.shape[0] // k
    pos = _member_pos(axis, ranks)
    # accumulate in the native dtype like the global psum_scatter path
    # (int sums stay exact; AVERAGE on ints is rejected upstream)
    perm = [(ranks[i], ranks[(i + 1) % k]) for i in range(k)]
    acc = lax.dynamic_slice_in_dim(x, ((pos - 1) % k) * chunk, chunk)
    for t in range(k - 1):
        recv = lax.ppermute(acc, axis, perm)
        idx = (pos - t - 2) % k
        acc = recv + lax.dynamic_slice_in_dim(x, idx * chunk, chunk)
    if op == ReduceOp.AVERAGE:
        acc = acc / jnp.asarray(k, acc.dtype)
    if post != 1.0:
        acc = acc * post
    return acc.astype(x.dtype)


# ---------------------------------------------------------------------------
# eager machinery: cached jitted shard_maps over the process-set sub-mesh
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _eager_allreduce_fn(mesh: Mesh, axis: str, op: ReduceOp, pre: float,
                        post: float, bundled: bool = True,
                        row0: bool = False):
    """``bundled``: x is a (n, ...) per-rank bundle, one row per chip.
    Replicated (``bundled=False``): x is the raw array every rank
    contributes identically — ``in_specs=P()`` lets shard_map replicate it
    without the ``broadcast_to`` + device transfer a bundle would cost.
    ``row0`` (dispatch plans): return the replicated result row directly
    (``out_specs=P()``) so the caller needs no eager ``[0]`` slice — a
    cross-device gather — per call."""
    def inner(x):
        out = _allreduce_traced(x, axis, op, pre, post, None)
        return out[0] if (bundled and row0) else out
    in_spec = P(axis) if bundled else P()
    out_spec = P() if (row0 or not bundled) else P(axis)
    return _issue_serialized(jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
        check_vma=False)))


def _grouped_allreduce_smap(mesh: Mesh, axis: str, op: ReduceOp, pre: float,
                            post: float, num_bufs: int, bundled: bool):
    """Raw shard-mapped fused reduction (not jitted) — composed into the
    jitted wire programs below and into dispatch-plan programs that fold
    the wire-buffer split into the same compiled call."""
    def inner(*xs):
        return tuple(_allreduce_traced(x, axis, op, pre, post, None) for x in xs)
    spec = P(axis) if bundled else P()
    specs = tuple(spec for _ in range(num_bufs))
    return jax.shard_map(inner, mesh=mesh, in_specs=specs, out_specs=specs,
                         check_vma=False)


@functools.lru_cache(maxsize=None)
def _eager_grouped_allreduce_fn(mesh: Mesh, axis: str, op: ReduceOp, pre: float,
                                post: float, num_bufs: int,
                                bundled: bool = True,
                                donate: tuple = ()):
    """Fused wire-buffer program. ``donate`` marks which fused inputs are
    dispatcher-owned temporaries (never user arrays) — those buffers are
    donated so the reduction reuses their HBM instead of holding input and
    output live simultaneously."""
    return _issue_serialized(jax.jit(
        _grouped_allreduce_smap(mesh, axis, op, pre, post, num_bufs, bundled),
        donate_argnums=tuple(i for i, d in enumerate(donate) if d)))


@functools.lru_cache(maxsize=None)
def _eager_allgather_fn(mesh: Mesh, axis: str, bundled: bool = True):
    if bundled:
        def inner(x):  # (1, d0, ...) -> (n*d0, ...) replicated
            return lax.all_gather(x[0], axis, tiled=True)
        in_spec = P(axis)
    else:
        def inner(x):  # replicated (d0, ...) -> (n*d0, ...)
            return lax.all_gather(x, axis, tiled=True)
        in_spec = P()
    return _issue_serialized(jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=in_spec, out_specs=P(), check_vma=False)))


@functools.lru_cache(maxsize=None)
def _eager_broadcast_fn(mesh: Mesh, axis: str, root_pos: int,
                        bundled: bool = True):
    def inner(x):  # -> (...) replicated
        return _broadcast_traced(x[0] if bundled else x, axis, root_pos,
                                 None, None)
    return _issue_serialized(jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=P(axis) if bundled else P(),
        out_specs=P(), check_vma=False)))


def _grouped_broadcast_smap(mesh: Mesh, axis: str, root_pos: int,
                            num_bufs: int, bundled: bool):
    def inner(*xs):
        return tuple(_broadcast_traced(x[0] if bundled else x, axis,
                                       root_pos, None, None)
                     for x in xs)
    spec = P(axis) if bundled else P()
    specs = tuple(spec for _ in range(num_bufs))
    return jax.shard_map(inner, mesh=mesh, in_specs=specs,
                         out_specs=tuple(P() for _ in specs),
                         check_vma=False)


@functools.lru_cache(maxsize=None)
def _eager_grouped_broadcast_fn(mesh: Mesh, axis: str, root_pos: int,
                                num_bufs: int, bundled: bool = True,
                                donate: tuple = ()):
    return _issue_serialized(jax.jit(
        _grouped_broadcast_smap(mesh, axis, root_pos, num_bufs, bundled),
        donate_argnums=tuple(i for i, d in enumerate(donate) if d)))


def _wire_dtype_of(t, compression):
    """The dtype a tensor travels the wire in: its own dtype, or the
    compressor's wire dtype for floating tensors routed through
    ``Compression.bf16``/``fp16`` (integers pass through uncompressed,
    matching ``_CastCompressor.compress``)."""
    dt = jnp.result_type(t.array if isinstance(t, PerRank) else t)
    wire = getattr(compression, "wire_dtype", None)
    if wire is not None and jnp.issubdtype(dt, jnp.floating):
        return jnp.dtype(wire)
    return jnp.dtype(dt)


def _fusion_buckets(tensors, threshold: int, elem_count, dtype_of=None):
    """THE fusion bucketing rule, shared by the eager wire buffers and the
    opt-in traced fusion: group indices by dtype, then split each group
    into buckets whose total bytes stay <= ``threshold`` (a single
    oversized tensor gets its own bucket). ``elem_count(t)`` gives the
    per-rank element count of one tensor. Buckets are keyed by the WIRE
    dtype — ``dtype_of(i)`` when given (tensors routed through
    ``Compression.bf16``/``fp16`` fuse together instead of fragmenting
    into per-source-dtype buckets), else the tensor's own dtype. Yields
    (dtype, [indices])."""
    by_dtype: dict = {}
    for i, t in enumerate(tensors):
        dt = jnp.dtype(dtype_of(i)) if dtype_of is not None \
            else jnp.dtype(jnp.result_type(t))
        by_dtype.setdefault(dt, []).append(i)
    for dt, idxs in by_dtype.items():
        itemsize = jnp.dtype(dt).itemsize
        bucket: list = []
        bucket_bytes = 0
        for i in idxs:
            nbytes = elem_count(tensors[i]) * itemsize
            if bucket and bucket_bytes + nbytes > threshold:
                yield dt, bucket
                bucket, bucket_bytes = [], 0
            bucket.append(i)
            bucket_bytes += nbytes
        if bucket:
            yield dt, bucket


def _fuse_by_dtype(bundles: list, n: int, wire_dtypes=None):
    """Pack (n, ...) bundles into flat (n, total) wire buffers per WIRE
    dtype (the XLA analog of the reference's fusion buffer,
    ``fusion_buffer_manager.h:30-50``), each bucket capped at the fusion
    threshold (``HVD_FUSION_THRESHOLD``; reference default 128 MB,
    ``operations.cc:491-496`` — the autotuner tunes this knob at runtime).
    ``wire_dtypes[i]`` (compression routing) keys the buckets and casts on
    pack; :func:`_split_fused` casts back to each tensor's source dtype
    after the split. Returns (fused_inputs, metas)."""
    fused_inputs, metas = [], []
    wire_of = (lambda i: wire_dtypes[i]) if wire_dtypes is not None else None
    for dt, bidxs in _fusion_buckets(
            bundles, envs.fusion_threshold_bytes(),
            lambda b: int(np.prod(b.shape[1:]) or 1), dtype_of=wire_of):
        flat = [(bundles[i] if bundles[i].dtype == dt
                 else bundles[i].astype(dt)).reshape(n, -1) for i in bidxs]
        fused_inputs.append(jnp.concatenate(flat, axis=1))
        metas.append((dt, bidxs, [bundles[i].shape[1:] for i in bidxs],
                      [jnp.dtype(bundles[i].dtype) for i in bidxs]))
    return fused_inputs, metas


def _fusion_metas(per_shapes, src_dtypes, wire_dtypes):
    """Bucket layout (metas) from shapes/dtypes alone — the pure-metadata
    twin of :func:`_fuse_by_dtype` (and of the replicated-strategy fuse
    closure in :func:`_plan_fused_programs`) for plan builders,
    which only need the layout: materializing throwaway device bundles
    just to read it back would cost an O(payload) allocation per plan
    build (and plans rebuild on every autotune epoch flush)."""
    idxs = list(range(len(per_shapes)))
    metas = []
    for dt, bidxs in _fusion_buckets(
            idxs, envs.fusion_threshold_bytes(),
            lambda i: int(np.prod(per_shapes[i]) or 1),
            dtype_of=lambda i: wire_dtypes[i]):
        metas.append((dt, bidxs, [tuple(per_shapes[i]) for i in bidxs],
                      [jnp.dtype(src_dtypes[i]) for i in bidxs]))
    return metas


def _split_fused(fused_outputs, metas, count: int) -> list:
    """Inverse of :func:`_fuse_by_dtype` on flat per-dtype result vectors
    (decompressing — casting back to the source dtype — any tensor that
    traveled in a different wire dtype)."""
    results: list = [None] * count
    for vec, (dt, idxs, shapes, srcs) in zip(fused_outputs, metas):
        offset = 0
        for i, shp, src in zip(idxs, shapes, srcs):
            sz = int(np.prod(shp)) if shp else 1
            piece = vec[offset:offset + sz].reshape(shp)
            results[i] = piece if src == dt else piece.astype(src)
            offset += sz
    return results


@functools.lru_cache(maxsize=None)
def _eager_alltoall_fn(mesh: Mesh, axis: str):
    def inner(x):  # (1, s, ...) -> (s, ...) per-rank
        return _alltoall_traced(x[0], axis, None)
    return _issue_serialized(jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False)))


@functools.lru_cache(maxsize=None)
def _eager_uneven_alltoall_fn(mesh: Mesh, axis: str):
    """Padded uneven alltoall: each rank gathers its per-destination chunks
    (host-precomputed indices), zero-pads them to the global max chunk, and
    exchanges them with one ``lax.all_to_all``; the ragged valid parts are
    sliced back out by the caller (the reference's MPI_Alltoallv becomes
    pad + all_to_all + slice under XLA's static shapes)."""

    def inner(x, idx, mask):
        # x: (1, d0, ...); idx/mask: (1, n, max_chunk)
        sel = x[0][idx[0]]  # (n, max_chunk, ...) chunk for each destination
        m = mask[0].reshape(mask.shape[1:] + (1,) * (sel.ndim - 2))
        sel = jnp.where(m, sel, jnp.zeros((), sel.dtype))
        # recv[j] = the chunk rank j addressed to me
        return lax.all_to_all(sel, axis, split_axis=0, concat_axis=0,
                              tiled=True)

    return _issue_serialized(jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=(P(axis), P(axis), P(axis)),
        out_specs=P(axis), check_vma=False)))


@functools.lru_cache(maxsize=None)
def _eager_reducescatter_fn(mesh: Mesh, axis: str, op: ReduceOp, post: float):
    def inner(x):  # (1, d0, ...) -> (d0/n, ...) per-rank
        return _reducescatter_traced(x[0], axis, op, post, None)
    return _issue_serialized(jax.jit(jax.shard_map(
        inner, mesh=mesh, in_specs=P(axis), out_specs=P(axis), check_vma=False)))


def _as_bundle(tensor, pset: ProcessSet, allow_ragged: bool = False):
    """Canonicalize eager input to a (pset.size, ...) bundle array.

    Returns (bundle, was_bundled). Ragged bundles (``PerRank.dim0s`` set)
    are rejected unless the op supports per-rank first dims — otherwise
    the zero padding would silently enter the reduction/exchange."""
    n = pset.size()
    if isinstance(tensor, PerRank):
        if tensor.dim0s is not None and not allow_ragged:
            raise ValueError(
                "this collective requires uniform per-rank shapes; got a "
                f"ragged per_rank bundle with first dims {tensor.dim0s} "
                "(ragged first dims are supported by allgather and uneven "
                "alltoall only, matching the reference's contract)")
        arr = tensor.array
        if arr.shape[0] != n:
            raise ValueError(
                f"PerRank bundle leading axis {arr.shape[0]} != process set size {n}")
        return arr, True
    arr = jnp.asarray(tensor)
    return jnp.broadcast_to(arr[None], (n,) + arr.shape), False


def _member_process_view(pset: ProcessSet):
    """(member_procs, one_to_one, my_pos): the process-level view of a
    process set's chip ranks. ``one_to_one`` when the set's chips map 1:1
    onto its member processes (engine world == set positions — devices are
    rank-ordered process-major); ``my_pos`` is this process's position
    among the members, -1 when not 1:1 or not a member."""
    member_procs = sorted({runtime.process_of_rank(r) for r in pset.ranks})
    one_to_one = (len(member_procs) == len(pset.ranks)
                  and runtime.process_rank() in member_procs)
    my_pos = member_procs.index(runtime.process_rank()) if one_to_one else -1
    return member_procs, one_to_one, my_pos


def _i64_digest(values) -> int:
    """Stable non-zero crc32 digest of an int sequence (cross-process
    validation of size metadata every member must agree on)."""
    import zlib
    return zlib.crc32(np.ascontiguousarray(
        np.asarray(values, np.int64)).tobytes()) & 0x7FFFFFFF or 1


def _gspmd_passthrough_check(op: ReduceOp, name: str) -> None:
    """Inside plain jit/pjit only AVERAGE is the identity: gradients of a
    globally-sharded computation are already globally *averaged* by the
    partitioner (a mean loss over the global batch). SUM would differ from
    the local value by a factor of size() and anything else has no GSPMD
    meaning — both must run under shard_map where the semantics are
    explicit."""
    if op != ReduceOp.AVERAGE:
        raise RuntimeError(
            f"{name}(op={op.name}) was called inside jit/pjit without a "
            "bound mesh axis; only AVERAGE (gradient reduction) is an "
            "identity under GSPMD. Run it under jax.shard_map over "
            "hvd.mesh() so the op lowers to an explicit XLA collective.")
    hvd_logging.debug(
        "%s inside jit/pjit without a bound axis: GSPMD passthrough "
        "(gradients are already globally reduced by the partitioner)", name)
    # Trace-time tally: every sync the partitioner absorbed is a sync the
    # cached-program fast path (ops/gspmd_cache.py) never pays again on
    # replay. Function-level import — gspmd_cache imports this module's
    # siblings.
    from . import gspmd_cache
    gspmd_cache.note_passthrough()


def _check_op_dtype(op: ReduceOp, dtype):
    if op == ReduceOp.AVERAGE and jnp.issubdtype(dtype, jnp.integer):
        raise TypeError(
            "ReduceOp.AVERAGE is not supported for integer tensors "
            "(matches the reference's restriction); use SUM.")


# ---------------------------------------------------------------------------
# multi-process eager negotiation (dynamic engine gate)
# ---------------------------------------------------------------------------

import itertools as _itertools

# Stable dtype ids for cross-process metadata agreement checks (only
# equality matters; the table must be identical on every process).
_DTYPE_IDS = {name: i for i, name in enumerate((
    "bool", "int8", "uint8", "int16", "uint16", "int32", "uint32", "int64",
    "uint64", "float16", "bfloat16", "float32", "float64", "complex64",
    "complex128"))}


def _dtype_id(dt) -> int:
    known = _DTYPE_IDS.get(dt.name)
    if known is not None:
        return known
    # Unlisted dtypes (fp8 variants etc.) get a deterministic id derived
    # from the name — crc32 is stable across processes, unlike hash().
    import zlib
    return 0x4000_0000 | (zlib.crc32(dt.name.encode()) & 0x3FFF_FFFF)


_auto_counters: dict = {}


def _auto_counter_table() -> dict:
    """Auto-name counters for this thread's world: loopback rank threads
    each advance their OWN counters (the per-process contract — a shared
    table would let one rank's traffic desynchronize every rank's
    negotiation names)."""
    from ..loopback import context as _lbctx
    ctx = _lbctx.current()
    return ctx.auto_counters if ctx is not None else _auto_counters


def _reset_auto_counters() -> None:
    """World reset (engine_service.reset_service): names restart from
    zero in this thread's world."""
    _auto_counter_table().clear()


def _auto_name(kind: str, pset: ProcessSet) -> str:
    """Deterministic per-(kind, set) auto names. Counters are keyed by the
    set so processes outside a subset (which never see its ops) don't fall
    behind on a shared counter — a shared one would desynchronize the names
    of later *global* ops across processes."""
    from .. import engine_service
    key = (kind, engine_service._set_key(pset))
    counter = _auto_counter_table().setdefault(key, _itertools.count())
    n = next(counter)
    if key[1] == "0":
        return f"{kind}.{n}"
    return f"{kind}.ps{key[1]}.{n}"


def _negotiate_eager(kind: str, request_type: int, name: str | None,
                     shape, dtype, pset: ProcessSet,
                     root_rank: int = -1, splits=(), reduce_op: int = -1,
                     prescale: float = 1.0, postscale: float = 1.0,
                     splits_crc: int = 0):
    """Gate a multi-process eager collective through the dynamic engine
    (no-op for single-process jobs). Guarantees identical per-set op order
    and turns metadata disagreements into informative errors instead of
    hangs/corrupt reductions (the reference's negotiation role,
    ``controller.cc:73-430``). Returns the negotiated Response (None when
    no service runs) — uneven alltoall reads ``recv_splits`` off it.

    Each process set negotiates through its own service spanning only its
    member processes (the reference's per-ProcessSet controller,
    ``process_set.h:26-84``), so non-members legally never submitting a
    subset op is not reported as a stall.

    Returns ``(response, negotiated name)`` — ``(None, None)`` when no
    service runs. The name keys the loopback execution rendezvous
    (``loopback/dispatch.py``): it is the one token guaranteed unique
    while in flight AND identical across every member.
    """
    from .. import engine_service
    svc = engine_service.get_service(pset)
    if svc is None:
        return None, None
    neg_name = name or _auto_name(kind, pset)
    dt = jnp.dtype(dtype)
    return svc.negotiate(neg_name, request_type,
                         dtype=_dtype_id(dt),
                         element_size=dt.itemsize, shape=tuple(shape),
                         root_rank=root_rank, splits=splits,
                         reduce_op=reduce_op, prescale=prescale,
                         postscale=postscale,
                         splits_crc=splits_crc), neg_name


def _request_dict(name: str, request_type: int, shape, dtype,
                  group_id: int = -1, **meta) -> dict:
    """ONE negotiation request in the engine's wire format — the single
    source of truth shared by the sync path, the dispatch plans, and the
    fusion-cycle queue (the engine cross-validates these fields across
    processes, so every emitter must agree byte-for-byte)."""
    dt = jnp.dtype(dtype)
    return dict(name=name, request_type=request_type, dtype=_dtype_id(dt),
                element_size=dt.itemsize,
                shape=tuple(int(d) for d in shape), group_id=group_id,
                **meta)


def _group_requests(base: str, request_type: int, shapes_dtypes,
                    **meta) -> list[dict]:
    """The grouped negotiation payload: per-tensor requests named
    ``{base}.{i}`` sharing a group id derived from the base (identical on
    every process), which lets a joined rank reconstruct the group
    boundary from the response stream (``_execute_joined_zeros``) and the
    engine enforce joint fusion."""
    import zlib
    gid = zlib.crc32(base.encode()) & 0x7FFFFFFF
    return [_request_dict(f"{base}.{i}", request_type, shape, dtype,
                          group_id=gid, **meta)
            for i, (shape, dtype) in enumerate(shapes_dtypes)]


def _negotiate_eager_group(kind: str, request_type: int, name: str | None,
                           shapes_dtypes, pset: ProcessSet,
                           **meta) -> list | None:
    """Batch variant for grouped ops: all members land in one cycle.
    Returns the negotiated member names (``base.i``), or None when no
    service runs — the first name keys the loopback rendezvous."""
    from .. import engine_service
    svc = engine_service.get_service(pset)
    if svc is None:
        return None
    reqs = _group_requests(name or _auto_name(kind, pset),
                           request_type, shapes_dtypes, **meta)
    svc.negotiate_many(reqs)
    return [r["name"] for r in reqs]


# ---------------------------------------------------------------------------
# dispatch plans: steady-state eager fast path (see ops/dispatch_cache.py)
# ---------------------------------------------------------------------------

def _plan_sig(t):
    """Cache-key signature of one eager input: ("b", bundle shape, dtype)
    for uniform PerRank bundles, ("r", shape, dtype) for raw arrays every
    rank contributes identically. None = not plan-cacheable (ragged
    bundles, python scalars/lists — those keep the generic path)."""
    if isinstance(t, PerRank):
        if t.dim0s is not None:
            return None
        a = t.array
        return ("b", tuple(a.shape), jnp.dtype(a.dtype).name)
    shape = getattr(t, "shape", None)
    dtype = getattr(t, "dtype", None)
    if shape is None or dtype is None:
        return None
    try:
        return ("r", tuple(shape), jnp.dtype(dtype).name)
    except TypeError:
        return None


def _check_bundle_axis(sig, pset: ProcessSet) -> None:
    """Plan-path twin of ``_as_bundle``'s leading-axis validation: a
    PerRank bundle whose leading axis is not the process-set size must
    raise the clear error, never silently drop/misroute rows (plans are
    keyed by the bundle shape, so one check at build time covers every
    hit)."""
    if sig[0] == "b" and sig[1][0] != pset.size():
        raise ValueError(
            f"PerRank bundle leading axis {sig[1][0]} != process set "
            f"size {pset.size()}")


def _plan_negotiation(kind: str, request_type: int, name: str | None,
                      shape, dtype, pset: ProcessSet, **meta):
    """Pinned negotiation decision for a plan: None when no service applies
    (the per-call ``get_service`` + auto-name round is skipped on every
    hit), else a closure re-negotiating the SAME tensor name with the same
    precomputed metadata — which the native engine serves from its response
    cache via the bitvector AND (the reference ``ComputeResponseList`` HIT
    path) instead of a full metadata exchange."""
    from .. import engine_service
    if engine_service.get_service(pset) is None:
        return None
    neg_name = name or _auto_name(kind, pset)
    dt = jnp.dtype(dtype)
    kwargs = dict(dtype=_dtype_id(dt), element_size=dt.itemsize,
                  shape=tuple(int(d) for d in shape), **meta)

    def negotiate():
        # Re-resolve the service per call instead of pinning the build-time
        # object: an elastic re-form rebuilds services, and lazy resolution
        # is what lets a warm-grafted plan (docs/elastic.md) negotiate
        # against the NEW world. Table-hit resolution costs ~1us against a
        # millisecond-scale KV round.
        svc = engine_service.get_service(pset)
        if svc is None:
            raise RuntimeError(
                f"negotiation service gone for plan {neg_name!r} (world "
                "reset mid-call?); re-issue the collective")
        resp = svc.negotiate(neg_name, request_type, **kwargs)
        if resp is not None and resp.from_cache:
            _dispatch.note_negotiation_skip()
        return resp

    negotiate.neg_name = neg_name  # loopback rendezvous key (per plan)
    return negotiate


def _plan_group_negotiation(kind: str, request_type: int, name: str | None,
                            shapes_dtypes, pset: ProcessSet, **meta):
    """Grouped twin of :func:`_plan_negotiation`: the request batch is
    assembled once and replayed with stable names on every hit."""
    from .. import engine_service
    if engine_service.get_service(pset) is None:
        return None
    reqs = _group_requests(name or _auto_name(kind, pset), request_type,
                           shapes_dtypes, **meta)

    def negotiate():
        # lazy per-call resolution — see _plan_negotiation
        svc = engine_service.get_service(pset)
        if svc is None:
            raise RuntimeError(
                "negotiation service gone for grouped plan "
                f"{reqs[0]['name'] if reqs else '?'!r} (world reset "
                "mid-call?); re-issue the collective")
        resps = svc.negotiate_many(reqs)
        if resps and all(r.from_cache for r in resps):
            _dispatch.note_negotiation_skip()
        return resps

    negotiate.neg_name = reqs[0]["name"] if reqs else None
    return negotiate


def _bundle_of(t, shape, n: int):
    """Per-call canonicalization for the bundle strategy: PerRank arrays
    pass through; raw arrays are expanded to the (n, ...) bundle (only the
    mixed PerRank+raw grouped case still pays this — all-raw groups use the
    replicated strategy with no expansion at all)."""
    if isinstance(t, PerRank):
        return t.array
    return jnp.broadcast_to(jnp.asarray(t)[None], (n,) + shape)


def _grouped_donate_mask(metas, alias_risk) -> tuple:
    """Which fused wire buffers are safe to donate. A fused buffer is a
    dispatcher-owned temporary (concatenate/reshape output) EXCEPT when its
    bucket has a single member whose flatten is a no-op — jnp's reshape and
    single-array concatenate fast paths then hand back the caller's own
    array object, which must never be donated. ``alias_risk(i)`` says
    whether member ``i``'s flatten can no-op onto a user-held array; a
    wire-dtype cast (source dtype != bucket dtype) always produces a fresh
    dispatcher-owned array, so those buckets stay donatable."""
    return tuple(
        not (len(bidxs) == 1 and srcs[0] == dt and alias_risk(bidxs[0]))
        for (dt, bidxs, _shapes, srcs) in metas)


def _sig_donate_mask(metas, sigs, bundled: bool) -> tuple:
    """Donate mask from plan signatures — THE alias-risk rule in one
    place, shared by the per-flush plan builders and the step-capture
    whole-step programs (drift here would re-introduce the donation
    aliasing bug on exactly one of the two paths)."""
    if bundled:
        return _grouped_donate_mask(
            metas, lambda i: sigs[i][0] == "b" and len(sigs[i][1]) == 2)
    return _grouped_donate_mask(metas, lambda i: len(sigs[i][1]) == 1)


def _fuse_closure(metas, n: int, bundled: bool):
    """Shared fuse body (list of canonicalized inputs -> per-dtype wire
    buffers), traced inside the per-flush plan programs AND the
    step-capture whole-step programs — one definition, so wire
    packaging can never drift between the two paths."""
    if bundled:
        def fuse(arrs):
            return [jnp.concatenate([arrs[i].astype(dt).reshape(n, -1)
                                     for i in bidxs], axis=1)
                    for (dt, bidxs, _s, _src) in metas]
    else:
        def fuse(arrs):
            return [jnp.concatenate([arrs[i].astype(dt).reshape(-1)
                                     for i in bidxs])
                    if len(bidxs) > 1
                    else arrs[bidxs[0]].astype(dt).reshape(-1)
                    for (dt, bidxs, _s, _src) in metas]
    return fuse


def _canon_closure(shapes, n: int, bundled: bool):
    """Shared input canonicalizer (user tensors -> fuse-program inputs):
    PerRank bundles pass through / raw arrays expand under the bundle
    strategy; everything to jnp arrays under the replicated strategy."""
    if bundled:
        def canon(ts):
            return [_bundle_of(t, shp, n) for t, shp in zip(ts, shapes)]
    else:
        def canon(ts):
            return [jnp.asarray(t) for t in ts]
    return canon


def _build_allreduce_plan(sig, pset: ProcessSet, axis, op: ReduceOp,
                          pre_f: float, post_f: float, name: str | None):
    _check_bundle_axis(sig, pset)
    lowered_op, post = handle_average(op, pset.size(), post_f)
    pre, post = float(pre_f), float(post)
    bundled = sig[0] == "b"
    per_shape = sig[1][1:] if bundled else sig[1]
    dtype = jnp.dtype(sig[2])
    negotiate = _plan_negotiation(
        "allreduce", REQ_ALLREDUCE, name, per_shape, dtype, pset,
        reduce_op=int(lowered_op), prescale=pre, postscale=post)
    nbytes = int(np.prod(per_shape) or 1) * dtype.itemsize
    if negotiate is not None:
        # Multi-process job: compose EXACTLY like the joined-rank zero
        # reconstruction (``_execute_joined_zeros``: wire-dtype (n, ...)
        # bundle through ``_execute_allreduce_bundle``) so active and
        # joined processes lower identical multiprocess computations —
        # the ROADMAP open item on plan-path/join alignment. The row-0
        # program variant and the chunk pipeline stay single-controller
        # optimizations: a joined rank cannot reconstruct them from
        # response metadata.
        lb_key = negotiate.neg_name

        def execute(t):
            bundle, _ = _as_bundle(t, pset)
            return _execute_allreduce_bundle(bundle, pset, axis,
                                             lowered_op, pre, post,
                                             lb_key=lb_key)
        return _dispatch.DispatchPlan(name or "allreduce", "ALLREDUCE",
                                      nbytes, negotiate, execute)
    if (lowered_op == ReduceOp.SUM
            and hierarchical.hierarchical_enabled_for(pset)):
        fn = hierarchical._eager_hier_allreduce_fn(
            hierarchical.hierarchical_mesh(), lowered_op, pre, post,
            bundled, row0=bundled)
    else:
        fn = _eager_allreduce_fn(pset.mesh(), axis, lowered_op, pre, post,
                                 bundled, row0=bundled)
    if bundled:
        def execute(t):  # row0 program: replicated result, no eager slice
            return fn(t.array)
    else:
        def execute(t):
            return fn(jnp.asarray(t))
    return _dispatch.DispatchPlan(name or "allreduce", "ALLREDUCE", nbytes,
                                  negotiate, execute)


def _plan_fused_programs(metas, smap, n: int, count: int, bundled: bool,
                         donate: tuple, row0: bool):
    """The plan's two compiled stages. Stage 1 (``fuse``) canonicalizes
    user tensors into the per-dtype wire buffers in ONE program (the eager
    reshape+concatenate op storm this replaces dominated steady-state
    dispatch). Stage 2 (``wire``) runs the shard-mapped collective AND the
    wire-buffer split in one program, with the wire buffers donated —
    they are stage-1 outputs, so donation can only recycle
    dispatcher-owned memory (``donate`` additionally excludes buffers a
    backend's input-output forwarding could alias to a user array:
    identity-reshape single-tensor buckets)."""
    body = _fuse_closure(metas, n, bundled)

    def fuse(*arrs):
        return tuple(body(list(arrs)))

    if bundled:
        def wire(*fused):
            outs = smap(*fused)
            if row0:
                outs = [o[0] for o in outs]
            return tuple(_split_fused(list(outs), metas, count))
    else:
        def wire(*fused):
            return tuple(_split_fused(list(smap(*fused)), metas, count))
    fuse_fn = _issue_serialized(jax.jit(fuse))
    wire_fn = _issue_serialized(jax.jit(
        wire, donate_argnums=tuple(i for i, d in enumerate(donate) if d)))
    return fuse_fn, wire_fn


# ---------------------------------------------------------------------------
# chunked wire pipeline (large fused buffers; see docs/pipeline.md)
# ---------------------------------------------------------------------------

def _pipeline_key():
    """Plan-cache key component for the chunk pipeline: the knobs that
    change a chunked plan's program composition — including the ping-pong
    setting, which swaps the fuse/piece program shapes — or None when
    chunking is off (so disabling the pipelined executor reuses the
    pre-pipeline plans byte-for-byte)."""
    if not envs.pipeline_chunking_enabled():
        return None
    return (envs.pipeline_threshold_bytes(), envs.pipeline_chunks(),
            (envs.get(envs.PIPELINE_PINGPONG, "auto") or "auto")
            .strip().lower())


def _chunk_layout(metas):
    """Piece layout for the software pipeline: each wire bucket whose
    payload exceeds ``HVD_PIPELINE_THRESHOLD`` is split into
    ``HVD_PIPELINE_CHUNKS`` contiguous flat ranges, each dispatched as
    its own collective program (the collective of chunk i then overlaps
    the fuse/split — and the neighbors' per-device execution — of chunks
    i±1, ByteScheduler's tensor-partitioning insight applied to the
    fusion buffer). Sub-threshold buckets stay one piece. Returns a list
    of ``(bucket_idx, start_elem, end_elem)`` or None when no bucket
    chunks (the plan then keeps the one-program wire stage)."""
    if not envs.pipeline_chunking_enabled():
        return None
    threshold = envs.pipeline_threshold_bytes()
    chunks = envs.pipeline_chunks()
    layout, any_chunked = [], False
    for bi, (dt, _bidxs, shapes, _srcs) in enumerate(metas):
        total = sum(int(np.prod(shp) or 1) for shp in shapes)
        if total * jnp.dtype(dt).itemsize <= threshold or total < chunks:
            layout.append((bi, 0, total))
            continue
        any_chunked = True
        step = -(-total // chunks)  # ceil: last chunk may be smaller
        layout.extend((bi, a, min(a + step, total))
                      for a in range(0, total, step))
    return layout if any_chunked else None


@functools.lru_cache(maxsize=None)
def _piece_allreduce_fn(mesh: Mesh, axis: str, op: ReduceOp, pre: float,
                        post: float, bundled: bool, donate: bool,
                        recycle: bool):
    """One chunk's wire program: single-buffer shard-mapped reduction
    with the row-0 extract INSIDE the shard_map (``out_specs=P()`` hands
    back the replicated chunk directly — extracting row 0 outside the
    shard_map lowers to a cross-device gather that measured ~6x the
    collective itself on the CPU mesh). ``donate`` recycles the chunk
    buffer's HBM into the reduction (chunk buffers are fuse-stage
    outputs, always dispatcher-owned). ``recycle`` additionally returns
    the donated input as a second output — with real donation the output
    aliases the input's buffer, handing its memory back to the caller as
    the next flush's ping-pong scratch."""
    def inner(x):
        out = _allreduce_traced(x, axis, op, pre, post, None)
        return out[0] if bundled else out

    smap = jax.shard_map(inner, mesh=mesh,
                         in_specs=P(axis) if bundled else P(),
                         out_specs=P(), check_vma=False)

    def one(x):
        out = smap(x)
        return (out, x) if recycle else out

    return _issue_serialized(jax.jit(
        one, donate_argnums=(0,) if donate else ()))


def _plan_chunked_programs(metas, layout, mesh: Mesh, axis, op: ReduceOp,
                           pre: float, post: float, n: int, count: int,
                           bundled: bool, pingpong: bool, donate: bool):
    """Program set for a chunk-pipelined grouped allreduce plan.

    Stage 1 (``fuse``) packs user tensors into the per-dtype wire buffers
    AND slices them into the pipeline pieces, all in one program. Stage 2
    is one collective program per piece, dispatched back-to-back — JAX
    dispatch is asynchronous, so piece i+1 is enqueued while piece i's
    collective runs; the per-device queues then pipeline the pieces
    (measured ~30-40% wall-time reduction for 4 MiB buffers on the CPU
    mesh vs one monolithic wire program). Stage 3 (``split``) reassembles
    the piece results and splits them back into per-tensor outputs.

    With ``pingpong`` the fuse program takes a tuple of donated scratch
    buffers (pure memory donors, never read) and each piece program
    returns its donated input as a recycled buffer — steady-state flushes
    then rotate ``HVD_MAX_INFLIGHT_FLUSHES`` buffer sets instead of
    allocating fresh wire memory per flush."""
    piece_shapes = []
    for bi, a, b in layout:
        dt = metas[bi][0]
        piece_shapes.append(((n, b - a) if bundled else (b - a,), dt))

    def _bufs(inputs):
        if bundled:
            return [jnp.concatenate([inputs[i].astype(dt).reshape(n, -1)
                                     for i in bidxs], axis=1)
                    for (dt, bidxs, _s, _src) in metas]
        return [jnp.concatenate([inputs[i].astype(dt).reshape(-1)
                                 for i in bidxs])
                if len(bidxs) > 1
                else inputs[bidxs[0]].astype(dt).reshape(-1)
                for (dt, bidxs, _s, _src) in metas]

    def _slices(bufs):
        if bundled:
            return tuple(bufs[bi][:, a:b] for (bi, a, b) in layout)
        return tuple(bufs[bi][a:b] for (bi, a, b) in layout)

    if pingpong:
        def fuse(scratch, *inputs):
            del scratch  # memory donors only; outputs reuse their HBM
            return _slices(_bufs(list(inputs)))
        fuse_fn = _issue_serialized(jax.jit(fuse, donate_argnums=(0,)))
    else:
        def fuse(*inputs):
            return _slices(_bufs(list(inputs)))
        fuse_fn = _issue_serialized(jax.jit(fuse))

    piece_fns = [
        _piece_allreduce_fn(mesh, axis, op, pre, post, bundled,
                            donate=donate, recycle=pingpong)
        for _ in layout
    ]

    def split(*piece_outs):
        vecs = []
        for bi in range(len(metas)):
            parts = [piece_outs[j] for j, (b, _a, _e) in enumerate(layout)
                     if b == bi]
            vecs.append(parts[0] if len(parts) == 1
                        else jnp.concatenate(parts))
        return tuple(_split_fused(vecs, metas, count))

    split_fn = _issue_serialized(jax.jit(split))
    return fuse_fn, piece_fns, split_fn, piece_shapes


def _chunked_execute(fuse_fn, piece_fns, split_fn, piece_shapes,
                     canonicalize, pingpong: bool):
    """Execute closure for a chunked plan. ``canonicalize`` maps the user
    tensor list to the fuse program's inputs. The scratch pool (ping-pong
    buffer sets recycled by the piece programs) is per-plan state — i.e.
    per flush signature — bounded by the executor's slot count so at most
    one spare set exists per in-flight flush."""
    pool: list = []
    pool_lock = threading.Lock()

    def execute(ts):
        inputs = canonicalize(ts)
        with _timeline.pipeline_stage("FUSE"):
            if pingpong:
                with pool_lock:
                    scratch = pool.pop() if pool else None
                if scratch is None:
                    scratch = tuple(jnp.zeros(shp, dt)
                                    for shp, dt in piece_shapes)
                pieces = fuse_fn(scratch, *inputs)
            else:
                pieces = fuse_fn(*inputs)
        outs, recycled = [], []
        with _timeline.pipeline_stage("DISPATCH"):
            for piece, fn in zip(pieces, piece_fns):
                r = fn(piece)
                if pingpong:
                    outs.append(r[0])
                    recycled.append(r[1])
                else:
                    outs.append(r)
        if pingpong:
            with pool_lock:
                if len(pool) < max(envs.max_inflight_flushes(), 1):
                    pool.append(tuple(recycled))
        with _timeline.pipeline_stage("SPLIT"):
            return list(split_fn(*outs))

    return execute


def _build_grouped_allreduce_plan(tensors, sigs, pset: ProcessSet, axis,
                                  op: ReduceOp, pre_f: float, post_f: float,
                                  name: str | None, compression=None):
    for s in sigs:
        _check_bundle_axis(s, pset)
    lowered_op, post = handle_average(op, pset.size(), post_f)
    pre, post = float(pre_f), float(post)
    n = pset.size()
    count = len(tensors)
    bundled = any(s[0] == "b" for s in sigs)
    shapes = [s[1][1:] if s[0] == "b" else s[1] for s in sigs]
    wire_dts = [_wire_dtype_of(t, compression) for t in tensors]
    hier = (lowered_op == ReduceOp.SUM
            and hierarchical.hierarchical_enabled_for(pset))
    metas = _fusion_metas(shapes, [s[2] for s in sigs], wire_dts)
    # Negotiation metadata carries the WIRE dtype — that is what peers
    # must agree on (and what a joined rank's zero buffers reduce in).
    negotiate = _plan_group_negotiation(
        "grouped_allreduce", REQ_ALLREDUCE, name,
        [(shp, dt) for shp, dt in zip(shapes, wire_dts)], pset,
        reduce_op=int(lowered_op), prescale=pre, postscale=post)
    nbytes = sum(int(np.prod(shp) or 1) * dt.itemsize
                 for shp, dt in zip(shapes, wire_dts))
    if negotiate is not None:
        # Multi-process job: compose EXACTLY like the joined-rank zero
        # reconstruction and the queued flush path — canonical wire-dtype
        # bundles through ``_execute_grouped_bundles`` (eager fuse, one
        # jit(shard_map) wire program per bucket set, eager split), the
        # one composition a joined process can rebuild from response
        # metadata alone. Split fuse/wire jits, donation, and the chunk
        # pipeline remain single-controller-only (ROADMAP alignment item).
        lb_key = negotiate.neg_name

        def execute(ts):
            bundles = [_as_bundle(t, pset)[0] for t in ts]
            wire = [_wire_dtype_of(b, compression) for b in bundles]
            return _execute_grouped_bundles(bundles, pset, axis, lowered_op,
                                            pre, post, count,
                                            wire_dtypes=wire, lb_key=lb_key)
        return _dispatch.DispatchPlan(name or "grouped_allreduce",
                                      "GROUPED_ALLREDUCE", nbytes,
                                      negotiate, execute)
    donate = _sig_donate_mask(metas, sigs, bundled)
    layout = None if hier else _chunk_layout(metas)
    if layout is not None:
        # Chunk pipeline: fuse emits per-chunk wire buffers, each chunk's
        # collective is its own back-to-back-dispatched program, one split
        # program reassembles (see _plan_chunked_programs). Donation and
        # ping-pong buffer recycling engage where donation is real
        # (off-CPU — the CPU backend ignores donation but still charges
        # per-call bookkeeping for it); forcing HVD_PIPELINE_PINGPONG=1
        # forces both (the recycle output needs the donate intent).
        platform = pset.mesh().devices.flat[0].platform
        pingpong = (all(donate)
                    and envs.pipeline_pingpong_enabled(platform))
        piece_donate = envs.donation_effective(platform) or pingpong
        fuse_fn, piece_fns, split_fn, piece_shapes = _plan_chunked_programs(
            metas, layout, pset.mesh(), axis, lowered_op, pre, post, n,
            count, bundled, pingpong, piece_donate)
        canonicalize = _canon_closure(shapes, n, bundled)
        execute = _chunked_execute(fuse_fn, piece_fns, split_fn,
                                   piece_shapes, canonicalize, pingpong)
        return _dispatch.DispatchPlan(name or "grouped_allreduce",
                                      "GROUPED_ALLREDUCE", nbytes,
                                      negotiate, execute, variant="chunked",
                                      pieces=len(layout))
    if hier:
        smap = hierarchical._hier_grouped_allreduce_smap(
            hierarchical.hierarchical_mesh(), lowered_op, pre, post,
            len(metas), bundled)
    else:
        smap = _grouped_allreduce_smap(pset.mesh(), axis, lowered_op, pre,
                                       post, len(metas), bundled)
    fuse_fn, wire_fn = _plan_fused_programs(metas, smap, n, count, bundled,
                                            donate, row0=bundled)
    canon = _canon_closure(shapes, n, bundled)

    def execute(ts):
        return list(wire_fn(*fuse_fn(*canon(ts))))
    return _dispatch.DispatchPlan(name or "grouped_allreduce",
                                  "GROUPED_ALLREDUCE", nbytes, negotiate,
                                  execute)


def _build_broadcast_plan(sig, pset: ProcessSet, axis, root_rank: int,
                          name: str | None):
    _check_bundle_axis(sig, pset)
    bundled = sig[0] == "b"
    per_shape = sig[1][1:] if bundled else sig[1]
    dtype = jnp.dtype(sig[2])
    root_pos = pset.ranks.index(root_rank)
    negotiate = _plan_negotiation("broadcast", REQ_BROADCAST, name,
                                  per_shape, dtype, pset,
                                  root_rank=root_rank)
    nbytes = int(np.prod(per_shape) or 1) * dtype.itemsize
    if negotiate is not None and _lb.active():
        # Loopback plan variant (per-context cache: never serves a real
        # multi-process world): rendezvous the rows, root's row wins.
        lb_key = negotiate.neg_name

        def execute(t):
            bundle, _ = _as_bundle(t, pset)
            return _execute_broadcast_bundle(bundle, pset, axis, root_pos,
                                             lb_key=lb_key)
        return _dispatch.DispatchPlan(name or "broadcast", "BROADCAST",
                                      nbytes, negotiate, execute)
    fn = _eager_broadcast_fn(pset.mesh(), axis, root_pos, bundled)
    if bundled:
        def execute(t):
            return fn(t.array)
    else:
        def execute(t):
            return fn(jnp.asarray(t))
    return _dispatch.DispatchPlan(name or "broadcast", "BROADCAST", nbytes,
                                  negotiate, execute)


def _build_grouped_broadcast_plan(tensors, sigs, pset: ProcessSet, axis,
                                  root_rank: int, name: str | None):
    for s in sigs:
        _check_bundle_axis(s, pset)
    n = pset.size()
    count = len(tensors)
    root_pos = pset.ranks.index(root_rank)
    bundled = any(s[0] == "b" for s in sigs)
    shapes = [s[1][1:] if s[0] == "b" else s[1] for s in sigs]
    src_dts = [jnp.dtype(s[2]) for s in sigs]
    negotiate = _plan_group_negotiation(
        "grouped_broadcast", REQ_BROADCAST, name,
        [(shp, jnp.dtype(s[2])) for shp, s in zip(shapes, sigs)], pset,
        root_rank=root_rank)
    if negotiate is not None and _lb.active():
        lb_key = negotiate.neg_name

        def execute(ts):
            bundles = [_as_bundle(t, pset)[0] for t in ts]
            ch = _lb.channel(pset, lb_key)
            if ch is None:  # world torn down mid-plan: plain bundles
                fi, ms = _fuse_by_dtype(bundles, n)
                f = _eager_grouped_broadcast_fn(pset.mesh(), axis,
                                                root_pos, len(fi))
                return _split_fused(f(*fi), ms, count)
            return _lb_grouped_broadcast(ch, bundles, pset, axis,
                                         root_pos, count)
        return _dispatch.DispatchPlan(name or "grouped_broadcast",
                                      "GROUPED_BROADCAST", None, negotiate,
                                      execute)
    metas = _fusion_metas(shapes, src_dts, src_dts)
    donate = _sig_donate_mask(metas, sigs, bundled)
    smap = _grouped_broadcast_smap(pset.mesh(), axis, root_pos, len(metas),
                                   bundled)
    fuse_fn, wire_fn = _plan_fused_programs(metas, smap, n, count, bundled,
                                            donate, row0=False)
    canon = _canon_closure(shapes, n, bundled)

    def execute(ts):
        return list(wire_fn(*fuse_fn(*canon(ts))))
    return _dispatch.DispatchPlan(name or "grouped_broadcast",
                                  "GROUPED_BROADCAST", None, negotiate,
                                  execute)


def _build_allgather_plan(sig, pset: ProcessSet, axis, name: str | None):
    """Uniform-shape eager allgather plan. Returns None when a negotiation
    service runs — the engine's recv_splits can resize the program per
    call (ragged peers / joined processes), so multi-process allgather
    keeps the response-driven path. NOTE: ``allgather()`` already skips
    plan lookup entirely when a service exists (per-call unique async
    names would churn the cache with UNPLANNABLE entries), so this check
    only guards the race of a service appearing between the two calls."""
    from .. import engine_service
    if engine_service.get_service(pset) is not None:
        return None
    _check_bundle_axis(sig, pset)
    bundled = sig[0] == "b"
    per_shape = sig[1][1:] if bundled else sig[1]
    dtype = jnp.dtype(sig[2])
    nbytes = int(np.prod(per_shape) or 1) * dtype.itemsize
    if len(per_shape) >= 1 and per_shape[0] == 0:
        rest = per_shape[1:]

        def execute(t):
            # uniform zero-row gather: no data moves (XLA forbids the
            # zero-size gather dim); result empty on every rank
            return jnp.zeros((0,) + rest, dtype)
        return _dispatch.DispatchPlan(name or "allgather", "ALLGATHER",
                                      nbytes, None, execute)
    if hierarchical.hierarchical_allgather_enabled_for(pset):
        fn = hierarchical._eager_hier_allgather_fn(
            hierarchical.hierarchical_mesh(), bundled)
    else:
        fn = _eager_allgather_fn(pset.mesh(), axis, bundled)
    scalar = len(per_shape) == 0
    if bundled:
        if scalar:  # (n,) bundle of scalars -> (n,) vector
            def execute(t):
                return fn(t.array[:, None]).reshape(-1)
        else:
            def execute(t):
                return fn(t.array)
    else:
        if scalar:
            def execute(t):
                return fn(jnp.asarray(t).reshape(1)).reshape(-1)
        else:
            def execute(t):
                return fn(jnp.asarray(t))
    return _dispatch.DispatchPlan(name or "allgather", "ALLGATHER", nbytes,
                                  None, execute)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def allreduce(tensor, *, op: ReduceOp = ReduceOp.AVERAGE,
              process_set: ProcessSet | None = None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              name: str | None = None, axis_name=None):
    """Allreduce (reference ``hvd.allreduce``; enqueue path
    ``operations.cc:1357-1512``). AVERAGE lowers to SUM + postscale 1/n
    (``operations.cc:1408-1416``). ``name`` labels the op in the timeline
    (``hvd.start_timeline``)."""
    pset = _resolve(process_set)
    axis = _resolve_axis(axis_name)
    _check_op_dtype(op, jnp.result_type(tensor if not isinstance(tensor, PerRank)
                                       else tensor.array))
    if op == ReduceOp.ADASUM:
        from .adasum import adasum_allreduce
        return adasum_allreduce(tensor, process_set=pset, axis_name=axis)
    if _compat.trace_state_clean():
        # definitely eager (no trace in progress): plan-cached dispatch.
        # HVD_CACHE_CAPACITY=0 (the off switch) keeps the original
        # build-everything-per-call path below.
        sig = _plan_sig(tensor) if _dispatch.enabled() else None
        if sig is not None:
            key = ("allreduce", name, sig, axis, pset.dispatch_key(),
                   int(op), float(prescale_factor), float(postscale_factor),
                   hierarchical.layout_key_for(pset))
            plan = _dispatch.lookup(key)
            if plan is None:
                plan = _build_allreduce_plan(sig, pset, axis, op,
                                             prescale_factor,
                                             postscale_factor, name)
                _dispatch.store(key, plan)
            return plan.run(tensor)
    elif _axis_is_bound(axis):
        return _allreduce_traced(tensor, axis, op, prescale_factor,
                                 postscale_factor, pset.axis_index_groups())
    elif _contains_tracer(tensor):
        # Inside jit/pjit with no named axis: GSPMD semantics — gradients of
        # a globally-sharded computation are already globally reduced by
        # XLA's partitioner, so the allreduce is the identity (the design
        # inversion of SURVEY.md §7; the reference's XLA bridge
        # xla_mpi_ops.cc:165-260 calls back into the runtime instead).
        # Only the gradient-reduction ops have this equivalence.
        _gspmd_passthrough_check(op, "allreduce")
        scale = prescale_factor * postscale_factor
        return tensor if scale == 1.0 else tensor * scale
    # non-plannable eager input (python scalars/lists, ragged misuse) or a
    # jax build without the trace-state probe: generic bundle path
    lowered_op, post = handle_average(op, pset.size(), postscale_factor)
    bundle, _ = _as_bundle(tensor, pset)
    _resp, neg_name = _negotiate_eager(
        "allreduce", REQ_ALLREDUCE, name, bundle.shape[1:],
        bundle.dtype, pset, reduce_op=int(lowered_op),
        prescale=float(prescale_factor), postscale=float(post))
    _autotune.record(bundle.nbytes // max(bundle.shape[0], 1))
    with _timeline.op_range(name or "allreduce", "ALLREDUCE"):
        return _execute_allreduce_bundle(bundle, pset, axis, lowered_op,
                                         float(prescale_factor), float(post),
                                         lb_key=neg_name)


def _execute_allreduce_bundle(bundle, pset, axis, lowered_op, pre, post,
                              lb_key=None):
    """Dispatch one eager allreduce program for a (n, ...) bundle — shared
    by the caller path and the joined-rank zero-contribution path, which
    must produce the identical SPMD program.

    ``lb_key`` (the negotiated tensor name) routes a loopback world's
    execution through the rendezvous hub: each rank contributes its OWN
    bundle row, and the completing rank runs this very function's body
    over the reconstructed true bundle — so loopback numerics are the
    single-controller program's, bit for bit."""
    ch = _lb.channel(pset, lb_key)
    if ch is not None:
        return ch.compute(
            bundle[ch.pos],
            lambda rows: _execute_allreduce_bundle(
                jnp.stack(rows), pset, axis, lowered_op, pre, post))
    if (lowered_op == ReduceOp.SUM
            and hierarchical.hierarchical_enabled_for(pset)):
        # HVD_HIERARCHICAL_ALLREDUCE: two-phase ICI/DCN schedule (the
        # reference's NCCLHierarchicalAllreduce analog).
        fn = hierarchical._eager_hier_allreduce_fn(
            hierarchical.hierarchical_mesh(), lowered_op, pre, post)
        return fn(bundle)[0]
    fn = _eager_allreduce_fn(pset.mesh(), axis, lowered_op, pre, post)
    return fn(bundle)[0]


# timer-boundary: the fusion-cycle timer only flushes single-controller
# queues (svc is None -> no negotiation, composition trivially rank-
# consistent), so timer-purity traversal stops at this entry point.
def grouped_allreduce(tensors: Sequence, *, op: ReduceOp = ReduceOp.AVERAGE,  # hvdlint: timer-boundary
                      process_set: ProcessSet | None = None,
                      prescale_factor: float = 1.0, postscale_factor: float = 1.0,
                      name: str | None = None, axis_name=None,
                      compression=None):
    """Fused allreduce of a tensor list (reference ``grouped_allreduce``,
    ``EnqueueTensorAllreduces`` with a group at ``operations.cc:1384-1512``).

    Eager mode performs explicit tensor fusion: tensors are flattened and
    concatenated per WIRE dtype into single wire buffers (the XLA analog of
    the reference's fusion buffer, ``fusion_buffer_manager.h:30-50``),
    reduced in one compiled program, then split back. ``compression``
    (``hvd.Compression.bf16``/``fp16``) routes floating tensors over the
    wire in the compressed dtype: mixed-source-dtype tensors sharing a wire
    dtype fuse into ONE buffer instead of fragmenting per source dtype, and
    each result is cast back (decompressed) after the split.
    """
    if not tensors:
        return []
    pset = _resolve(process_set)
    axis = _resolve_axis(axis_name)
    for t in tensors:
        _check_op_dtype(op, jnp.result_type(t if not isinstance(t, PerRank) else t.array))
    if op == ReduceOp.ADASUM:
        from .adasum import adasum_allreduce
        return [adasum_allreduce(t, process_set=pset, axis_name=axis) for t in tensors]
    if _is_custom_compressor(compression):
        # user Compressor subclass: only its compress/decompress pair
        # defines the wire format — wrap the call per leaf (the pre-wire-
        # fusion contract), no wire-dtype bucketing. Compressors see
        # arrays, so PerRank bundles are compressed through their array.
        def _comp(t):
            if isinstance(t, PerRank):
                c, ctx = compression.compress(t.array)
                return PerRank(c, t.dim0s), ctx
            return compression.compress(t)

        cs, ctxs = zip(*(_comp(t) for t in tensors))
        outs = grouped_allreduce(
            list(cs), op=op, process_set=pset,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, name=name, axis_name=axis)
        return [compression.decompress(o, ctx)
                for o, ctx in zip(outs, ctxs)]
    # plan/queue identity of the wire mapping: the wire dtype itself (a
    # class name would miss compressor instances and collide same-named
    # user classes with different wire formats)
    _wire = getattr(compression, "wire_dtype", None)
    comp_key = jnp.dtype(_wire).name if _wire is not None else None

    if _compat.trace_state_clean():
        sigs = (tuple(_plan_sig(t) for t in tensors)
                if _dispatch.enabled() else (None,))
        if all(s is not None for s in sigs):
            key = ("grouped_allreduce", name, sigs, axis,
                   pset.dispatch_key(), int(op), float(prescale_factor),
                   float(postscale_factor),
                   hierarchical.layout_key_for(pset),
                   envs.fusion_threshold_bytes(), comp_key,
                   _pipeline_key())
            plan = _dispatch.lookup(key)
            if plan is None:
                plan = _build_grouped_allreduce_plan(
                    tensors, sigs, pset, axis, op, prescale_factor,
                    postscale_factor, name, compression)
                _dispatch.store(key, plan)
            return plan.run(tensors)
    elif _axis_is_bound(axis):
        groups = pset.axis_index_groups()
        if comp_key is not None:
            # traced wire compression: cast, reduce, cast back per leaf
            # (XLA fuses the casts into the collective's producers)
            outs = []
            for t in tensors:
                wdt = _wire_dtype_of(t, compression)
                src = jnp.result_type(t)
                r = _allreduce_traced(t.astype(wdt) if src != wdt else t,
                                      axis, op, prescale_factor,
                                      postscale_factor, groups)
                outs.append(r.astype(src) if src != wdt else r)
            return outs
        traced_fusion = envs.get_int(envs.TRACED_FUSION_THRESHOLD, 0)
        if len(tensors) > 1 and traced_fusion > 0:
            return _grouped_allreduce_traced_fused(
                tensors, axis, op, prescale_factor, postscale_factor,
                groups, traced_fusion)
        return [_allreduce_traced(t, axis, op, prescale_factor,
                                  postscale_factor, groups)
                for t in tensors]
    elif any(_contains_tracer(t) for t in tensors):
        # GSPMD passthrough (see allreduce above). Nothing travels a wire,
        # so compression is the identity here too.
        _gspmd_passthrough_check(op, "grouped_allreduce")
        scale = prescale_factor * postscale_factor
        return list(tensors) if scale == 1.0 else [t * scale for t in tensors]
    lowered_op, post = handle_average(op, pset.size(), postscale_factor)

    # --- eager fusion path ---
    n = pset.size()
    bundles = [_as_bundle(t, pset)[0] for t in tensors]
    wire_dts = [_wire_dtype_of(b, compression) for b in bundles]
    neg_names = _negotiate_eager_group(
        "grouped_allreduce", REQ_ALLREDUCE, name,
        [(b.shape[1:], dt)
         for b, dt in zip(bundles, wire_dts)], pset,
        reduce_op=int(lowered_op),
        prescale=float(prescale_factor),
        postscale=float(post))
    _autotune.record(sum(int(np.prod(b.shape[1:]) or 1) * dt.itemsize
                         for b, dt in zip(bundles, wire_dts)))
    with _timeline.op_range(name or "grouped_allreduce", "GROUPED_ALLREDUCE"):
        return _execute_grouped_bundles(bundles, pset, axis, lowered_op,
                                        float(prescale_factor), float(post),
                                        len(tensors), wire_dtypes=wire_dts,
                                        lb_key=neg_names[0] if neg_names
                                        else None)


def _grouped_allreduce_traced_fused(tensors, axis, op, pre, post, groups,
                                    limit):
    """OPT-IN explicit tensor fusion on the TRACED path
    (``HVD_TRACED_FUSION_THRESHOLD`` > 0, bytes per fused buffer): pack
    same-dtype leaves into bounded flat buffers, ONE collective per
    buffer (every reduce op is elementwise, so fusing is exact) — the
    traced twin of the eager fusion buffer (reference
    ``fusion_buffer_manager.h:30-50``).

    OFF by default and deliberately so: inside one program the compiler's
    all-reduce combiner + latency-hiding scheduler interleave per-leaf
    collectives WITH the backward compute, while an explicit fused buffer
    serializes all communication after all compute — measured on the
    virtual-CPU scaling harness, a 96 MB fused buffer took Inception's
    n=8 collective efficiency from ~0.90 to 0.26. The knob exists for
    backends without a combiner pass and for experimentation."""
    out: list = [None] * len(tensors)
    for _dt, chunk in _fusion_buckets(tensors, limit,
                                      lambda t: int(t.size)):
        if len(chunk) == 1:  # nothing to fuse; skip the reshape round trip
            j = chunk[0]
            out[j] = _allreduce_traced(tensors[j], axis, op, pre, post,
                                       groups)
            continue
        fused = jnp.concatenate([jnp.ravel(tensors[j]) for j in chunk])
        red = _allreduce_traced(fused, axis, op, pre, post, groups)
        off = 0
        for j in chunk:
            size = tensors[j].size
            out[j] = red[off:off + size].reshape(jnp.shape(tensors[j]))
            off += size
    return out


def _execute_grouped_bundles(bundles, pset, axis, lowered_op, pre, post,
                             count, wire_dtypes=None, lb_key=None):
    """One fused eager grouped-allreduce program over (n, ...) bundles —
    shared by the caller path and the joined-rank zero path. ``lb_key``:
    see :func:`_execute_allreduce_bundle`."""
    ch = _lb.channel(pset, lb_key)
    if ch is not None:
        rows = tuple(b[ch.pos] for b in bundles)
        return ch.compute(
            rows,
            lambda allrows: _execute_grouped_bundles(
                [jnp.stack([r[i] for r in allrows])
                 for i in range(len(bundles))],
                pset, axis, lowered_op, pre, post, count,
                wire_dtypes=wire_dtypes))
    n = pset.size()
    fused_inputs, metas = _fuse_by_dtype(bundles, n, wire_dtypes=wire_dtypes)
    # No donation here: this generic path doubles as the HVD_CACHE_CAPACITY=0
    # reference behavior; buffer donation lives in the dispatch plans' wire
    # programs (_plan_fused_programs), where the wire buffers are provably
    # dispatcher-owned stage-1 outputs.
    if (lowered_op == ReduceOp.SUM
            and hierarchical.hierarchical_enabled_for(pset)):
        fn = hierarchical._eager_hier_grouped_allreduce_fn(
            hierarchical.hierarchical_mesh(), lowered_op, pre, post,
            len(fused_inputs))
    else:
        fn = _eager_grouped_allreduce_fn(pset.mesh(), axis, lowered_op,
                                         pre, post, len(fused_inputs))
    fused_outputs = fn(*fused_inputs)
    # row 0 of each (n, total) buffer: identical on every rank
    return _split_fused([buf[0] for buf in fused_outputs], metas, count)


# timer-boundary: the fusion-cycle timer never flushes svc allgather
# queues (_loop skips svc queues), and the single-controller path below
# has no negotiation — traversal stops here.
def allgather(tensor, *, process_set: ProcessSet | None = None,  # hvdlint: timer-boundary
              name: str | None = None, axis_name=None):
    """Allgather: concatenate per-rank tensors along dim 0 (reference
    ``hvd.allgather``; ``EnqueueTensorAllgather`` at ``operations.cc:1529``,
    displacement math at ``collective_operations.h:143-178``).

    Ragged first dimensions are supported in eager mode (the reference's
    allgatherv contract): pass a ragged :func:`per_rank` bundle
    (single-controller), or — in multi-process jobs — each process simply
    passes its local tensor and the per-rank row counts are exchanged
    through the dynamic engine (the displacement negotiation of
    ``collective_operations.h:143-178``). Joined processes contribute zero
    rows. Traced mode requires uniform shapes (SPMD static shapes).
    """
    pset = _resolve(process_set)
    axis = _resolve_axis(axis_name)
    if _compat.trace_state_clean():
        sig = _plan_sig(tensor) if _dispatch.enabled() else None
        if sig is not None:
            from .. import engine_service
            if engine_service.get_service(pset) is not None:
                # Response-driven path: the engine's recv_splits can
                # resize the program per call, so no plan can ever serve
                # — and per-call unique names (async queue entries) would
                # otherwise churn the cache with dead UNPLANNABLE keys,
                # evicting live plans.
                sig = None
        if sig is not None:
            key = ("allgather", name, sig, axis, pset.dispatch_key(),
                   hierarchical.allgather_layout_key_for(pset))
            plan = _dispatch.lookup(key)
            if plan is None:
                plan = (_build_allgather_plan(sig, pset, axis, name)
                        or _dispatch.UNPLANNABLE)
                _dispatch.store(key, plan)
            if plan is not _dispatch.UNPLANNABLE:
                return plan.run(tensor)
    elif _axis_is_bound(axis):
        return _allgather_traced(tensor, axis, pset.axis_index_groups(),
                                 pset.ranks, pset.size())
    elif _contains_tracer(tensor):
        raise RuntimeError(
            "allgather() was called inside jit/pjit without a bound mesh axis. "
            "Run it under jax.shard_map over hvd.mesh() (or pass axis_name=) "
            "so the op can lower to an XLA collective.")
    local_d0s = tensor.dim0s if isinstance(tensor, PerRank) else None
    bundle, _ = _as_bundle(tensor, pset, allow_ragged=True)

    # Negotiation shape: this process's own first dim (rank-local in the
    # engine, collective_operations.h:143-178); a digest of the full dim0s
    # vector cross-validates ragged per_rank bundles like the uneven
    # alltoall's splits matrix.
    member_procs, one_to_one, my_pos = _member_process_view(pset)
    crc = 0
    neg_shape = bundle.shape[1:]
    if local_d0s is not None:
        crc = _i64_digest(local_d0s)
        if one_to_one:
            neg_shape = (local_d0s[my_pos],) + bundle.shape[2:]
    resp, neg_name = _negotiate_eager("allgather", REQ_ALLGATHER, name,
                                      neg_shape, bundle.dtype, pset,
                                      splits_crc=crc)

    # Resolve the per-rank row counts. The routing rule must be a pure
    # function of the engine response so active and joined processes build
    # the SAME program (_execute_joined_zeros applies the identical rule):
    # all engine dims equal -> uniform program; otherwise ragged with the
    # padded dim = max over the ENGINE's rank view (not local padding).
    d0s = list(local_d0s) if local_d0s is not None else None
    maxd = max(d0s) if d0s else None
    if resp is not None and resp.recv_splits:
        pos = {p: i for i, p in enumerate(member_procs)}
        eng = [int(resp.recv_splits[pos[runtime.process_of_rank(r)]])
               for r in pset.ranks]
        if d0s is None:
            if len(set(eng)) > 1:
                d0s = eng  # peers contributed different first dims
                maxd = max(eng)
        else:
            if one_to_one:
                for i, (e, loc) in enumerate(zip(eng, d0s)):
                    if e not in (0, loc):
                        raise ValueError(
                            f"allgather dim0s disagree: engine negotiated "
                            f"{e} rows for rank {pset.ranks[i]} but the "
                            f"local per_rank bundle carries {loc}; processes "
                            "passed different ragged bundles")
            # engine view decides participation (0 = joined) AND the
            # program's padded dim — every process, including joined ones
            # reconstructing from recv_splits alone, derives the same value
            d0s = [0 if e == 0 else loc for e, loc in zip(eng, d0s)]
            maxd = max(eng)

    _autotune.record(bundle.nbytes // max(bundle.shape[0], 1))
    with _timeline.op_range(name or "allgather", "ALLGATHER"):
        if d0s is None and bundle.ndim >= 2 and bundle.shape[1] == 0:
            # uniform zero-row gather: no data moves and XLA forbids a
            # zero-size gather dim — the result is empty on every rank
            # (joined peers — and loopback ranks — skip identically:
            # the engine negotiated every dim 0, so the decision is
            # rank-consistent)
            return jnp.zeros((0,) + bundle.shape[2:], bundle.dtype)
        if d0s is not None and max(d0s) == 0 and _lb.active():
            # loopback: an all-zero ragged gather skips the exchange
            # BEFORE a channel is created (channel creation advances the
            # per-name occurrence counter, and the joined-rank zero path
            # skips on the same predicate — the counters must not drift)
            return jnp.zeros((0,) + tuple(bundle.shape[2:]), bundle.dtype)
        ch = _lb.channel(pset, neg_name)
        if ch is not None:
            return _loopback_allgather(ch, bundle, d0s)
        if d0s is not None:
            return _execute_ragged_allgather(bundle, d0s, maxd, pset, axis)
        if hierarchical.hierarchical_allgather_enabled_for(pset):
            # HVD_HIERARCHICAL_ALLGATHER: ICI-then-DCN two-phase gather.
            hmesh = hierarchical.hierarchical_mesh()
            if bundle.ndim == 1:
                bundle = bundle[:, None]
                return hierarchical._eager_hier_allgather_fn(hmesh)(
                    bundle).reshape(-1)
            return hierarchical._eager_hier_allgather_fn(hmesh)(bundle)
        if bundle.ndim == 1:  # scalars per rank: gather to a vector
            bundle = bundle[:, None]
            return _eager_allgather_fn(pset.mesh(), axis)(bundle).reshape(-1)
        return _eager_allgather_fn(pset.mesh(), axis)(bundle)


def _lb_gather_parts(rest, dtype):
    """THE loopback allgather combiner, shared by the active path and the
    joined-rank zero contribution — whichever rank completes the slot
    runs it, so both must supply the identical closure."""
    rest = tuple(rest)

    def gather(parts):
        parts = [p for p in parts if p.shape[0] > 0]
        if not parts:
            return jnp.zeros((0,) + rest, dtype)
        return jnp.concatenate(parts, axis=0)

    return gather


def _lb_stack_parts(parts):
    """Scalar-allgather combiner (one scalar per rank -> (n,) vector).
    Module-level so the active path and the joined-rank zero
    contribution supply the literally identical function."""
    return jnp.stack(parts)


def _lb_grouped_broadcast(ch, bundles, pset, axis, root_pos, count):
    """THE loopback grouped-broadcast execution, shared by the plan,
    immediate, and queued paths — one combiner, so leader-dependent
    results cannot drift between the three call sites."""
    n = pset.size()

    def compute(allrows):
        bs = [jnp.stack([r[i] for r in allrows])
              for i in range(len(bundles))]
        fi, ms = _fuse_by_dtype(bs, n)
        f = _eager_grouped_broadcast_fn(pset.mesh(), axis, root_pos,
                                        len(fi))
        return _split_fused(f(*fi), ms, count)

    return ch.compute(tuple(b[ch.pos] for b in bundles), compute)


def _loopback_allgather(ch, bundle, d0s):
    """Loopback allgather execution: each rank contributes its valid rows
    (ragged: trimmed to its negotiated first dim — a joined rank's zero
    rows included), and the completing rank concatenates in set order.
    No arithmetic happens, so the result is exact."""
    if bundle.ndim == 1:  # scalar per rank -> (n,) vector; a joined
        # peer contributes a zero scalar, like the real (n, 1) program
        return ch.compute(bundle[ch.pos], _lb_stack_parts)
    rows = bundle[ch.pos]
    if d0s is not None:
        rows = rows[:d0s[ch.pos]]
    return ch.compute(rows, _lb_gather_parts(bundle.shape[2:],
                                             bundle.dtype))


def _execute_ragged_allgather(bundle, d0s, maxd, pset: ProcessSet, axis):
    """Ragged eager allgather: pad every rank's block to the negotiated max
    first dim, exchange with the uniform all-gather program (identical SPMD
    computation on every process — ``maxd`` is derived from the engine's
    shared view, so joined processes rebuild the same shape), then slice
    the valid rows back out and concatenate (the pad/exchange/slice scheme
    of the uneven alltoall applied to MPI_Allgatherv,
    ``collective_operations.h:143-178``)."""
    n = pset.size()
    rest = bundle.shape[2:]
    maxd = max(int(maxd), 1)
    if bundle.shape[1] < maxd:
        # local-tensor multi-process path: this process's rows are fewer
        # than the global max — pad with zeros (never read back)
        pad = jnp.zeros((n, maxd - bundle.shape[1]) + rest, bundle.dtype)
        bundle = jnp.concatenate([bundle, pad], axis=1)
    elif bundle.shape[1] > maxd:
        # joined peers shrank the global max below the local padding
        bundle = bundle[:, :maxd]
    gathered = _eager_allgather_fn(pset.mesh(), axis)(bundle)  # (n*maxd,...)
    parts = [gathered[r * maxd:r * maxd + d0s[r]] for r in range(n)
             if d0s[r] > 0]
    if not parts:
        return jnp.zeros((0,) + rest, bundle.dtype)
    return jnp.concatenate(parts, axis=0)


def broadcast(tensor, root_rank: int, *, process_set: ProcessSet | None = None,
              name: str | None = None, axis_name=None):
    """Broadcast from ``root_rank`` (a *global* rank, as in the reference's
    ``hvd.broadcast``; ``operations.cc:1568``)."""
    pset = _resolve(process_set)
    axis = _resolve_axis(axis_name)
    if root_rank not in pset.ranks:
        raise ValueError(f"root_rank {root_rank} not in process set {pset.ranks}")
    if _compat.trace_state_clean():
        sig = _plan_sig(tensor) if _dispatch.enabled() else None
        if sig is not None:
            key = ("broadcast", name, sig, axis, pset.dispatch_key(),
                   root_rank)
            plan = _dispatch.lookup(key)
            if plan is None:
                plan = _build_broadcast_plan(sig, pset, axis, root_rank,
                                             name)
                _dispatch.store(key, plan)
            return plan.run(tensor)
    elif _axis_is_bound(axis):
        return _broadcast_traced(tensor, axis, root_rank,
                                 pset.axis_index_groups(), pset.ranks)
    elif _contains_tracer(tensor):
        raise RuntimeError(
            "broadcast() was called inside jit/pjit without a bound mesh axis. "
            "Run it under jax.shard_map over hvd.mesh() (or pass axis_name=) "
            "so the op can lower to an XLA collective.")
    bundle, _ = _as_bundle(tensor, pset)
    root_pos = pset.ranks.index(root_rank)
    _resp, neg_name = _negotiate_eager("broadcast", REQ_BROADCAST, name,
                                       bundle.shape[1:], bundle.dtype, pset,
                                       root_rank=root_rank)
    _autotune.record(bundle.nbytes // max(bundle.shape[0], 1))
    with _timeline.op_range(name or "broadcast", "BROADCAST"):
        return _execute_broadcast_bundle(bundle, pset, axis, root_pos,
                                         lb_key=neg_name)


def _execute_broadcast_bundle(bundle, pset, axis, root_pos, lb_key=None):
    """One eager broadcast program for a (n, ...) bundle; under loopback,
    rows rendezvous first (see :func:`_execute_allreduce_bundle`)."""
    ch = _lb.channel(pset, lb_key)
    if ch is not None:
        return ch.compute(
            bundle[ch.pos],
            lambda rows: _execute_broadcast_bundle(
                jnp.stack(rows), pset, axis, root_pos))
    return _eager_broadcast_fn(pset.mesh(), axis, root_pos)(bundle)


# timer-boundary: see grouped_allreduce — timer flushes are single-
# controller only, so no negotiation is reachable through this entry.
def grouped_broadcast(tensors: Sequence, root_rank: int, *,  # hvdlint: timer-boundary
                      process_set: ProcessSet | None = None,
                      name: str | None = None, axis_name=None):
    """Fused broadcast of a tensor list from ``root_rank``. Eager mode packs
    the tensors into one wire buffer per dtype (same fusion scheme as
    :func:`grouped_allreduce`, the analog of the reference's fusion buffer)
    so ``broadcast_parameters`` over a large model dispatches O(dtypes)
    programs instead of O(leaves)."""
    if not tensors:
        return []
    pset = _resolve(process_set)
    axis = _resolve_axis(axis_name)
    if root_rank not in pset.ranks:
        raise ValueError(f"root_rank {root_rank} not in process set {pset.ranks}")
    if _compat.trace_state_clean():
        sigs = (tuple(_plan_sig(t) for t in tensors)
                if _dispatch.enabled() else (None,))
        if all(s is not None for s in sigs):
            key = ("grouped_broadcast", name, sigs, axis,
                   pset.dispatch_key(), root_rank,
                   envs.fusion_threshold_bytes())
            plan = _dispatch.lookup(key)
            if plan is None:
                plan = _build_grouped_broadcast_plan(tensors, sigs, pset,
                                                     axis, root_rank, name)
                _dispatch.store(key, plan)
            return plan.run(tensors)
    elif _axis_is_bound(axis):
        groups = pset.axis_index_groups()
        return [_broadcast_traced(t, axis, root_rank, groups, pset.ranks)
                for t in tensors]
    elif any(_contains_tracer(t) for t in tensors):
        raise RuntimeError(
            "grouped_broadcast() was called inside jit/pjit without a bound "
            "mesh axis. Run it under jax.shard_map over hvd.mesh() (or pass "
            "axis_name=) so the ops can lower to XLA collectives.")
    n = pset.size()
    root_pos = pset.ranks.index(root_rank)
    bundles = [_as_bundle(t, pset)[0] for t in tensors]
    fused_inputs, metas = _fuse_by_dtype(bundles, n)
    neg_names = _negotiate_eager_group(
        "grouped_broadcast", REQ_BROADCAST, name,
        [(b.shape[1:], b.dtype) for b in bundles], pset,
        root_rank=root_rank)
    with _timeline.op_range(name or "grouped_broadcast", "GROUPED_BROADCAST"):
        ch = _lb.channel(pset, neg_names[0] if neg_names else None)
        if ch is not None:
            return _lb_grouped_broadcast(ch, bundles, pset, axis,
                                         root_pos, len(tensors))
        fn = _eager_grouped_broadcast_fn(pset.mesh(), axis, root_pos,
                                         len(fused_inputs))
        fused_outputs = fn(*fused_inputs)
    return _split_fused(fused_outputs, metas, len(tensors))


def alltoall(tensor, splits=None, *, process_set: ProcessSet | None = None,
             name: str | None = None, axis_name=None):
    """All-to-all along dim 0 (reference ``hvd.alltoall``,
    ``operations.cc:1642-1727``).

    Even mode (``splits=None``): rank *i*'s j-th of ``size`` equal chunks
    goes to rank *j*; returns a :class:`PerRank`.

    Uneven mode (``splits`` given): eager only (the reference likewise has
    no jit path — dynamic output shapes). ``splits`` is either one row of
    length ``size`` (every rank sends the same split pattern) or the full
    ``(size, size)`` matrix ``splits[i][j]`` = rows rank *i* sends rank *j*
    (the single-controller eager model sees every rank's metadata, like
    :func:`per_rank` bundles carry every rank's data). Row sums may be less
    than dim 0 — trailing rows are simply not sent, matching the
    reference's ``sum <= first_dim`` contract (``operations.cc:1703-1707``).
    Returns ``(outputs, recv_splits)``: ``outputs[r]`` is rank *r*'s
    received concatenation and ``recv_splits[r][j]`` the rows it got from
    rank *j* (the reference's second output tensor,
    ``collective_operations.h:261-269``). In multi-process jobs the splits
    metadata is cross-validated through the dynamic engine
    (``AlltoallGetRecvSplits`` analog)."""
    pset = _resolve(process_set)
    axis = _resolve_axis(axis_name)
    if splits is not None:
        return _alltoall_uneven(tensor, splits, pset, axis, name)
    if _axis_is_bound(axis):
        return _alltoall_traced(tensor, axis, pset.axis_index_groups())
    if _contains_tracer(tensor):
        raise RuntimeError(
            "alltoall() was called inside jit/pjit without a bound mesh axis. "
            "Run it under jax.shard_map over hvd.mesh() (or pass axis_name=) "
            "so the op can lower to an XLA collective.")
    bundle, _ = _as_bundle(tensor, pset)
    n = pset.size()
    if bundle.shape[1] % n != 0:
        raise ValueError(f"alltoall dim0 ({bundle.shape[1]}) must be divisible "
                         f"by process set size ({n})")
    _resp, neg_name = _negotiate_eager("alltoall", REQ_ALLTOALL, name,
                                       bundle.shape[1:], bundle.dtype, pset)
    with _timeline.op_range(name or "alltoall", "ALLTOALL"):
        ch = _lb.channel(pset, neg_name)
        if ch is not None:
            out = ch.compute(
                bundle[ch.pos],
                lambda rows: _eager_alltoall_fn(pset.mesh(), axis)(
                    jnp.stack(rows)))
        else:
            out = _eager_alltoall_fn(pset.mesh(), axis)(bundle)
    return PerRank(out.reshape((n, out.shape[0] // n) + out.shape[1:]))


def _alltoall_uneven(tensor, splits, pset: ProcessSet, axis,
                     name: str | None):
    """Uneven eager alltoall: pad each per-destination chunk to the global
    max split, exchange with one ``lax.all_to_all``, slice the ragged valid
    parts back out (MPI_Alltoallv under XLA's static shapes)."""
    if _contains_tracer(tensor) or _axis_is_bound(axis):
        raise RuntimeError(
            "alltoall with uneven splits is eager-only: output shapes "
            "depend on the splits, which XLA's static shapes cannot carry "
            "through jit (the reference's uneven path is likewise "
            "runtime-dispatched, operations.cc:1642-1727)")
    n = pset.size()
    local_d0s = tensor.dim0s if isinstance(tensor, PerRank) else None
    bundle, _ = _as_bundle(tensor, pset, allow_ragged=True)
    d0 = bundle.shape[1]
    smat = np.asarray(splits, dtype=np.int64)
    if smat.ndim == 1:
        smat = np.broadcast_to(smat, (n, n)).copy()
    if smat.shape != (n, n):
        raise ValueError(
            f"splits must be one row of length {n} or a ({n}, {n}) matrix, "
            f"got shape {tuple(smat.shape)}")
    if (smat < 0).any():
        raise ValueError("splits entries must be non-negative")
    if local_d0s is not None:
        # ragged per_rank bundle: each rank's row sum is bounded by that
        # rank's OWN first dimension, not the padded bundle's
        row_sums = smat.sum(axis=1)
        for i in range(n):
            if row_sums[i] > local_d0s[i]:
                raise ValueError(
                    f"sum of splits row {i} ({int(row_sums[i])}) exceeds "
                    f"rank {i}'s first dimension ({local_d0s[i]}) "
                    "(reference operations.cc:1703-1707)")
    elif (smat.sum(axis=1) > d0).any():
        raise ValueError(
            f"sum of splits entries exceeds the first dimension ({d0}) "
            "(reference operations.cc:1703-1707)")

    # The full matrix is always cross-validated symmetrically via its
    # digest (every process must fail, or none — a partial failure would
    # hang the processes whose columns happen to agree inside the XLA
    # collective). The per-row recv-splits negotiation additionally runs
    # when the set's chips map 1:1 onto its member processes (then the
    # engine's world == the matrix dimension; set positions and engine
    # ranks coincide because devices are rank-ordered process-major).
    crc = _i64_digest(smat)
    member_procs, one_to_one, my_pos = _member_process_view(pset)
    my_row = smat[my_pos] if one_to_one else ()
    resp, neg_name = _negotiate_eager(
        "alltoall", REQ_ALLTOALL, name, bundle.shape[1:],
        bundle.dtype, pset, splits=tuple(int(s) for s in my_row),
        splits_crc=crc)
    recv_splits = smat.T.copy()  # recv_splits[r][j] = rows rank j sends rank r
    if resp is not None and resp.recv_splits and one_to_one:
        mine = list(recv_splits[my_pos])
        if list(resp.recv_splits) != mine:
            raise ValueError(
                f"negotiated recv_splits {resp.recv_splits} disagree with "
                f"the local splits matrix column {mine}; processes passed "
                "different splits for the same alltoall")

    max_chunk = max(int(smat.max()), 1)
    offsets = np.zeros((n, n), np.int64)
    offsets[:, 1:] = np.cumsum(smat, axis=1)[:, :-1]
    k_range = np.arange(max_chunk)
    idx = np.minimum(offsets[:, :, None] + k_range[None, None, :], d0 - 1)
    mask = k_range[None, None, :] < smat[:, :, None]

    with _timeline.op_range(name or "alltoall", "ALLTOALL"):
        ch = _lb.channel(pset, neg_name)
        if ch is not None:
            # idx/mask derive from the cross-validated splits matrix, so
            # the leader's copies equal every rank's
            out = ch.compute(
                bundle[ch.pos],
                lambda rows: _eager_uneven_alltoall_fn(pset.mesh(), axis)(
                    jnp.stack(rows), jnp.asarray(idx, jnp.int32),
                    jnp.asarray(mask)))
        else:
            out = _eager_uneven_alltoall_fn(pset.mesh(), axis)(
                bundle, jnp.asarray(idx, jnp.int32), jnp.asarray(mask))
    # out: (n*n, max_chunk, ...); rows [r*n:(r+1)*n] = rank r's received
    # padded chunks, one per source rank
    out = out.reshape((n, n, max_chunk) + bundle.shape[2:])
    outputs = []
    for r in range(n):
        parts = [out[r, j, :int(recv_splits[r, j])] for j in range(n)]
        outputs.append(jnp.concatenate(parts, axis=0) if parts else
                       jnp.zeros((0,) + bundle.shape[2:], bundle.dtype))
    return outputs, recv_splits.astype(np.int32)


def reducescatter(tensor, *, op: ReduceOp = ReduceOp.SUM,
                  process_set: ProcessSet | None = None,
                  name: str | None = None, axis_name=None):
    """Reduce-scatter along dim 0: each rank receives one reduced chunk."""
    pset = _resolve(process_set)
    axis = _resolve_axis(axis_name)
    _check_op_dtype(op, jnp.result_type(tensor if not isinstance(tensor, PerRank)
                                       else tensor.array))
    if _axis_is_bound(axis):
        return _reducescatter_traced(tensor, axis, op, 1.0,
                                     pset.axis_index_groups())
    lowered_op, post = handle_average(op, pset.size(), 1.0)
    if _contains_tracer(tensor):
        raise RuntimeError(
            "reducescatter() was called inside jit/pjit without a bound mesh axis. "
            "Run it under jax.shard_map over hvd.mesh() (or pass axis_name=) "
            "so the op can lower to an XLA collective.")
    bundle, _ = _as_bundle(tensor, pset)
    n = pset.size()
    if bundle.shape[1] % n != 0:
        raise ValueError(f"reducescatter dim0 ({bundle.shape[1]}) must be "
                         f"divisible by process set size ({n})")
    _resp, neg_name = _negotiate_eager("reducescatter", REQ_REDUCESCATTER,
                                       name, bundle.shape[1:], bundle.dtype,
                                       pset)
    with _timeline.op_range(name or "reducescatter", "REDUCESCATTER"):
        ch = _lb.channel(pset, neg_name)
        if ch is not None:
            out = ch.compute(
                bundle[ch.pos],
                lambda rows: _eager_reducescatter_fn(
                    pset.mesh(), axis, lowered_op,
                    float(post))(jnp.stack(rows)))
        else:
            out = _eager_reducescatter_fn(pset.mesh(), axis, lowered_op,
                                          float(post))(bundle)
    return PerRank(out.reshape((n, out.shape[0] // n) + out.shape[1:]))


def barrier(*, process_set: ProcessSet | None = None, axis_name=None):
    """Block until every rank reaches the barrier (reference ``hvd.barrier``,
    ``operations.cc:1763-1795``). Under SPMD a device barrier is a tiny
    psum that we block on."""
    pset = _resolve(process_set)
    axis = _resolve_axis(axis_name)
    if _axis_is_bound(axis):
        return  # traced code is synchronous by construction
    # Queued async work must land before the barrier: every process
    # reaches this flush at the same program point, so the drain order is
    # rank-deterministic.
    from . import fusion_cycle
    fusion_cycle.flush_all("barrier")
    _negotiate_eager("barrier", REQ_BARRIER, None, (), jnp.int32, pset)
    fn = _eager_allreduce_fn(pset.mesh(), axis, ReduceOp.SUM, 1.0, 1.0)
    jax.block_until_ready(fn(jnp.zeros((pset.size(), 1), jnp.int32)))


_DTYPE_NAMES = {v: k for k, v in _DTYPE_IDS.items()}


def _execute_joined_zeros(responses) -> None:
    """Zero-contribution execution for a joined process (reference
    ``JoinOp``, ``collective_operations.h:275-290``: joined ranks allocate
    zero-filled buffers from response metadata and participate in the
    collective so the others can finish). Runs on the service cycle thread
    while the user thread blocks inside :func:`join`; programs are rebuilt
    through the same executors as the caller path so every process lowers
    the identical SPMD computation."""
    pset = _resolve(None)
    axis = _resolve_axis(None)
    n = pset.size()
    # ("barrier",) | ("allgather", dtype, rest, d0s, name) |
    # (dtype, shape, gid, op, pre, post, name) — the name is the
    # negotiated tensor name, which keys the loopback rendezvous so a
    # joined rank's zero contribution pairs with the active ranks'
    # executions (loopback/dispatch.py).
    items = []
    for resp in responses:
        if resp.type == REQ_BARRIER:
            items.append(("barrier",))
            continue
        if resp.type == REQ_ALLGATHER:
            dtype_name = _DTYPE_NAMES.get(resp.dtype)
            if dtype_name is None:
                raise RuntimeError(
                    f"hvd.join(): cannot reconstruct dtype id {resp.dtype} "
                    f"for zero contribution to {resp.tensor_names}")
            # This process is joined: its row count is 0 (the engine never
            # saw a request from it); peers' counts come on recv_splits.
            # The first enqueuer's full shape distinguishes scalar gathers
            # from zero-row tensor gathers and carries the trailing dims.
            first_shape = tuple(resp.shapes[0]) if resp.shapes else ()
            items.append(("allgather", jnp.dtype(dtype_name), first_shape,
                          tuple(int(s) for s in resp.recv_splits),
                          resp.tensor_names[0] if resp.tensor_names
                          else None))
            continue
        if resp.type != REQ_ALLREDUCE:
            raise RuntimeError(
                f"hvd.join(): another process scheduled a "
                f"{resp.type_name} ({resp.tensor_names}) while this one is "
                "joined; zero contribution is defined for allreduce/"
                "allgather/barrier only (reference JoinOp semantics)")
        dtype_name = _DTYPE_NAMES.get(resp.dtype)
        if dtype_name is None:
            raise RuntimeError(
                f"hvd.join(): cannot reconstruct dtype id {resp.dtype} for "
                f"zero contribution to {resp.tensor_names}")
        for tname, shape, gid in zip(resp.tensor_names, resp.shapes,
                                     resp.group_ids):
            items.append((jnp.dtype(dtype_name), tuple(shape), gid,
                          ReduceOp(resp.reduce_op), float(resp.prescale),
                          float(resp.postscale), tname))
    def _tensor_bytes(dt, shape):
        return int(np.prod(shape) or 1) * jnp.dtype(dt).itemsize

    i = 0
    while i < len(items):
        if items[i] == ("barrier",):
            fn = _eager_allreduce_fn(pset.mesh(), axis, ReduceOp.SUM,
                                     1.0, 1.0)
            jax.block_until_ready(fn(jnp.zeros((n, 1), jnp.int32)))
            i += 1
            continue
        if items[i][0] == "allgather":
            _, dt, first_shape, proc_d0s, tname = items[i]
            rest = first_shape[1:] if first_shape else ()
            # Expand per-process counts to per-rank rows and apply the
            # SAME routing rule as the active path (allgather() above):
            # all engine dims equal -> the uniform program; otherwise the
            # ragged program padded to max over the engine view.
            member_procs, _, _ = _member_process_view(pset)
            pos = {p: j for j, p in enumerate(member_procs)}
            d0s = [int(proc_d0s[pos[runtime.process_of_rank(r)]])
                   for r in pset.ranks]
            _autotune.record(int(np.prod(rest) or 1) * dt.itemsize
                             * max(max(d0s), 1))
            scalar = len(first_shape) == 0
            if scalar or max(d0s) > 0:
                ch = _lb.channel(pset, tname)
                if ch is not None:
                    # loopback: a joined rank contributes ZERO ROWS (a
                    # zero SCALAR for scalar gathers — the active path's
                    # ndim==1 branch stacks one value per rank, like the
                    # real (n, 1) program) and discards the result —
                    # participation parity with the active branch, which
                    # skips only the all-dims-zero non-scalar gather.
                    # The combiner must be the SAME closure the active
                    # side supplies: whichever rank completes the slot
                    # runs it.
                    if scalar:
                        ch.compute(jnp.zeros((), dt), _lb_stack_parts)
                    else:
                        ch.compute(jnp.zeros((0,) + tuple(rest), dt),
                                   _lb_gather_parts(rest, dt))
                    i += 1
                    continue
            if len(set(d0s)) == 1:
                # uniform (possibly zero-row) — mirror the active path's
                # uniform branch exactly, hierarchical knob included
                if len(first_shape) > 0 and d0s[0] == 0:
                    # zero-row uniform gather: active peers run NO program
                    i += 1
                    continue
                if len(first_shape) == 0:  # scalars: (n, 1) program
                    zb = jnp.zeros((n, 1), dt)
                else:
                    zb = jnp.zeros((n, d0s[0]) + tuple(rest), dt)
                if hierarchical.hierarchical_allgather_enabled_for(pset):
                    out = hierarchical._eager_hier_allgather_fn(
                        hierarchical.hierarchical_mesh())(zb)
                else:
                    out = _eager_allgather_fn(pset.mesh(), axis)(zb)
            else:
                maxd = max(d0s)
                out = _execute_ragged_allgather(
                    jnp.zeros((n, max(maxd, 1)) + tuple(rest), dt), d0s,
                    maxd, pset, axis)
            jax.block_until_ready(out)
            i += 1
            continue
        dt, shape, gid, op, pre, post, tname = items[i]
        if gid < 0:
            # mirror the caller path's autotune accounting so sample
            # boundaries (and the synced tuning decisions that ride them)
            # stay aligned across joined and active processes
            _autotune.record(_tensor_bytes(dt, shape))
            out = _execute_allreduce_bundle(
                jnp.zeros((n,) + shape, dt), pset, axis, op, pre, post,
                lb_key=tname)
            jax.block_until_ready(out)
            i += 1
        else:
            group = []
            while (i < len(items) and items[i] != ("barrier",)
                   and items[i][2] == gid):
                group.append(items[i])
                i += 1
            _autotune.record(sum(_tensor_bytes(d, shp)
                                 for d, shp, *_rest in group))
            bundles = [jnp.zeros((n,) + shp, d)
                       for d, shp, *_rest in group]
            outs = _execute_grouped_bundles(
                bundles, pset, axis, group[0][3], group[0][4], group[0][5],
                len(bundles), lb_key=group[0][6])
            jax.block_until_ready(outs)


def join() -> int:
    """Reference ``hvd.join`` (``operations.cc:1729-1761``): lets a process
    with uneven data drop out — until every process joins, it contributes
    zero-filled tensors to collectives the others schedule (allreduce and
    barrier; the reference's JoinOp covers the same). Returns the last
    joined rank.

    Single-process jobs (one controller sees every rank's data) have no
    uneven-participation problem; ``join`` degenerates to a barrier there.
    """
    pset = _resolve(None)
    from .. import engine_service
    svc = engine_service.get_service(pset)
    if svc is None:
        barrier()
        return runtime.size() - 1
    # A joining process first lands its own queued async work — after the
    # JOIN is negotiated it may only contribute zeros.
    from . import fusion_cycle
    fusion_cycle.flush_all("join")
    name = _auto_name("join", pset)
    last_proc = svc.join(name)
    if last_proc < 0:
        return runtime.size() - 1
    # last joined *process* -> its highest-owned chip rank
    return max(r for r in range(runtime.size())
               if runtime.process_of_rank(r) == last_proc)


# ---------------------------------------------------------------------------
# async handles (reference torch mpi_ops.py:914-953 poll/synchronize) over
# the cycle-driven fusion scheduler (ops/fusion_cycle.py): *_async calls
# enqueue into per-signature pending queues and dispatch at the next flush
# (threshold / cycle / synchronize / barrier), coalescing independently
# submitted small tensors into one grouped wire program — the reference's
# fusion-buffer cycle (operations.cc:385-806). HVD_CYCLE_TIME=0 restores
# immediate per-call dispatch.
# ---------------------------------------------------------------------------

def _result_arrays(result) -> list:
    """The device arrays carried by a handle result. PerRank bundles are
    opaque leaves to the jax.tree utilities — ``jax.block_until_ready``
    silently skips them and ``is_ready`` probes default to True — so
    readiness checks and device blocks must unwrap to ``.array``, inside
    grouped result lists too."""
    seq = result if isinstance(result, (list, tuple)) else [result]
    return [r.array if isinstance(r, PerRank) else r for r in seq]


class Handle:
    """Completion handle for *_async ops. The result may still be queued
    in the fusion cycle (dispatched at the next flush) or already in
    flight (JAX dispatch is itself asynchronous); ``synchronize`` flushes,
    blocks, and is idempotent — repeated calls return the cached result
    without re-walking the arrays."""

    __slots__ = ("_result", "_synced")

    def __init__(self, result=None):
        self._result = result
        self._synced = False

    def _materialize(self):
        """The dispatched result (queued subclass flushes first)."""
        return self._result

    def _dispatched(self) -> bool:
        return True

    def poll(self) -> bool:
        """True when the result landed. A still-queued handle first
        triggers a flush of its own entry — without that, polling an
        unflushed handle would spin forever waiting on a dispatch that
        nothing else triggers. A handle whose flush FAILED (or was
        aborted by a service reset) polls True — "synchronize() will not
        block" — and the error surfaces there; poll itself never raises
        (the reference's poll contract)."""
        if self._synced:
            return True
        if not self._dispatched():
            return False
        try:
            result = self._materialize()
        except Exception:
            return True  # completed in error; synchronize() raises it
        leaves = jax.tree.leaves(_result_arrays(result))
        return all(getattr(l, "is_ready", lambda: True)() for l in leaves)

    def synchronize(self):
        if self._synced:
            return self._result
        result = self._materialize()
        jax.block_until_ready(_result_arrays(result))
        self._result = result
        self._synced = True
        return self._result

    def flush(self) -> None:
        """Dispatch the op NOW if it is still queued in the fusion cycle
        (non-blocking; no-op on an already-dispatched handle). The
        bucketed optimizer path calls this after each bucket's submission
        so bucket k's collective is in flight while bucket k+1 fuses
        host-side — without waiting for a threshold or cycle trigger."""
        # immediate-path handles are already dispatched

    def result(self):
        """The dispatched result WITHOUT blocking on device completion
        (``synchronize()`` is the blocking wait): downstream eager ops
        chain on in-flight arrays through device-side data dependencies,
        so update math can run while later buckets' collectives are
        still on the wire. Re-raises a failed flush's error.

        On backends where that chaining is unsafe
        (``envs.eager_chain_enabled``: the XLA CPU client's shared
        thread pool lets consumer programs starve an in-flight
        collective's rendezvous — a reproduced deadlock) this degrades
        to ``synchronize()``."""
        if self._synced:
            return self._result
        if not envs.eager_chain_enabled(jax.devices()[0].platform):
            return self.synchronize()
        return self._materialize()


class _QueuedHandle(Handle):
    """Handle over a fusion-cycle queue entry (futures-style): the op has
    not dispatched yet; poll/synchronize flush the entry's queue."""

    __slots__ = ("_entry",)

    def __init__(self, entry):
        super().__init__(None)
        self._entry = entry

    def _dispatched(self) -> bool:
        from . import fusion_cycle
        return fusion_cycle.scheduler().poll_entry(self._entry)

    def _materialize(self):
        from . import fusion_cycle
        results = fusion_cycle.scheduler().wait_result(self._entry)
        return list(results) if self._entry.grouped else results[0]

    def flush(self) -> None:
        from . import fusion_cycle
        fusion_cycle.scheduler().flush_entry(self._entry, "bucket")


def _is_custom_compressor(compression) -> bool:
    """A user Compressor subclass with its own compress/decompress pair
    but no cast-style ``wire_dtype`` — only it knows the wire format, so
    it must wrap the call instead of routing through wire-dtype fusion."""
    from .compression import NoneCompressor
    return (compression is not None
            and getattr(compression, "wire_dtype", None) is None
            and hasattr(compression, "compress")
            and compression is not NoneCompressor)


def allreduce_async(tensor, *, compression=None, **kw) -> Handle:
    """Async allreduce (reference ``allreduce_async_``,
    ``torch/mpi_ops.py:124``): enqueues into the fusion cycle and returns
    immediately; the collective dispatches at the next flush, fused with
    other pending same-signature submissions. ``compression`` routes the
    tensor over the wire in the compressed dtype (decompressed on
    synchronize)."""
    from . import fusion_cycle
    h = fusion_cycle.queue_allreduce([tensor], grouped=False,
                                     compression=compression, **kw)
    if h is not None:
        return h
    if _is_custom_compressor(compression) \
            or getattr(compression, "wire_dtype", None) is not None:
        return Handle(grouped_allreduce([tensor], compression=compression,
                                        **kw)[0])
    return Handle(allreduce(tensor, **kw))


def grouped_allreduce_async(tensors, *, compression=None, **kw) -> Handle:
    """Handle over a fused grouped allreduce (reference
    ``grouped_allreduce_async``, ``torch/mpi_ops.py:375``). The group
    rides the fusion cycle atomically (never split across flushes) and
    may fuse further with other pending same-signature submissions."""
    if not tensors:
        return Handle([])
    from . import fusion_cycle
    h = fusion_cycle.queue_allreduce(list(tensors), grouped=True,
                                     compression=compression, **kw)
    if h is not None:
        return h
    return Handle(grouped_allreduce(tensors, compression=compression, **kw))


def allgather_async(tensor, **kw) -> Handle:
    from . import fusion_cycle
    h = fusion_cycle.queue_allgather(tensor, **kw)
    if h is not None:
        return h
    return Handle(allgather(tensor, **kw))


def broadcast_async(tensor, root_rank, **kw) -> Handle:
    from . import fusion_cycle
    h = fusion_cycle.queue_broadcast(tensor, root_rank, **kw)
    if h is not None:
        return h
    return Handle(broadcast(tensor, root_rank, **kw))


def grouped_broadcast_async(tensors, root_rank, *, process_set=None,
                            name=None, axis_name=None) -> Handle:
    """Handle over a fused broadcast of a tensor list: every leaf rides
    the broadcast queue (one entry per tensor, so independently-submitted
    broadcasts of the same root coalesce too); ``broadcast_parameters``
    synchronizes a whole model through one flush."""
    from . import fusion_cycle
    handles = []
    for i, t in enumerate(tensors):
        h = fusion_cycle.queue_broadcast(
            t, root_rank, process_set=process_set,
            name=None if name is None else f"{name}.{i}",
            axis_name=axis_name)
        if h is None:
            break
        handles.append(h)
    if len(handles) == len(tensors):
        return _MultiHandle(handles)
    # scheduler off / unplannable leaf: drain the queued prefix (keeps
    # submission order), then broadcast only the remaining tensors under
    # a distinct name base — reusing `name` would renegotiate the
    # prefix's "{name}.0..." names with the remainder's metadata
    prefix = [h.synchronize() for h in handles]
    rest = grouped_broadcast(tensors[len(handles):], root_rank,
                             process_set=process_set,
                             name=None if name is None else f"{name}.rest",
                             axis_name=axis_name)
    return Handle(prefix + rest)


class _MultiHandle(Handle):
    """Aggregate handle over per-tensor queued handles (grouped
    broadcast): synchronizes all, returns the result list."""

    __slots__ = ("_handles",)

    def __init__(self, handles):
        super().__init__(None)
        self._handles = handles

    def _dispatched(self) -> bool:
        return all(h._dispatched() for h in self._handles)

    def _materialize(self):
        # sub-handles' _materialize waits only on the dispatch event (no
        # device block) — poll() must stay non-blocking; synchronize()
        # adds the block_until_ready over the whole list in Handle
        return [h._materialize() for h in self._handles]

    def flush(self) -> None:
        for h in self._handles:
            h.flush()


def alltoall_async(tensor, splits=None, **kw) -> Handle:
    return Handle(alltoall(tensor, splits, **kw))


def poll(handle: Handle) -> bool:
    return handle.poll()


def synchronize(handle: Handle):
    return handle.synchronize()


# -- queued-entry executors (multi-process flush path: negotiation already
#    batched by the scheduler, program composition = submission-time) -------

# timer-boundary: queued-entry executors only run for svc-backed flushes,
# which the cycle timer never drains (rank-deterministic triggers only).
def _run_queued_allreduce(tensors, pset: ProcessSet, axis, op: ReduceOp,  # hvdlint: timer-boundary
                          pre_f: float, post_f: float, compression,
                          label: str) -> list:
    """Execute one queued allreduce entry (single tensor or atomic group)
    with its submission-time composition — the same program shape a joined
    rank reconstructs from response metadata (``_execute_joined_zeros``),
    so active and joined processes always lower identical SPMD programs."""
    lowered_op, post = handle_average(op, pset.size(), post_f)
    pre, post = float(pre_f), float(post)
    bundles = [_as_bundle(t, pset)[0] for t in tensors]
    wire_dts = [_wire_dtype_of(b, compression) for b in bundles]
    _autotune.record(sum(int(np.prod(b.shape[1:]) or 1) * dt.itemsize
                         for b, dt in zip(bundles, wire_dts)))
    with _timeline.op_range(label, "ALLREDUCE" if len(tensors) == 1
                            else "GROUPED_ALLREDUCE"):
        if len(bundles) == 1:
            # single entry: the un-fused program, the exact shape a joined
            # rank rebuilds from the response (wire-dtype zeros, gid=-1)
            b, src = bundles[0], bundles[0].dtype
            if wire_dts[0] != src:
                b = b.astype(wire_dts[0])
            out = _execute_allreduce_bundle(b, pset, axis, lowered_op,
                                            pre, post, lb_key=label)
            return [out.astype(src) if wire_dts[0] != src else out]
        return _execute_grouped_bundles(bundles, pset, axis, lowered_op,
                                        pre, post, len(tensors),
                                        wire_dtypes=wire_dts, lb_key=label)


def _run_queued_broadcast(tensors, pset: ProcessSet, axis, root_rank: int,  # hvdlint: timer-boundary
                          label: str) -> list:
    """Execute one queued broadcast entry (submission-time composition;
    see :func:`_run_queued_allreduce`)."""
    n = pset.size()
    root_pos = pset.ranks.index(root_rank)
    bundles = [_as_bundle(t, pset)[0] for t in tensors]
    _autotune.record(sum(b.nbytes // max(b.shape[0], 1) for b in bundles))
    with _timeline.op_range(label, "BROADCAST" if len(tensors) == 1
                            else "GROUPED_BROADCAST"):
        if len(bundles) == 1:
            return [_execute_broadcast_bundle(bundles[0], pset, axis,
                                              root_pos, lb_key=label)]
        ch = _lb.channel(pset, label)
        if ch is not None:
            return _lb_grouped_broadcast(ch, bundles, pset, axis,
                                         root_pos, len(tensors))
        fused_inputs, metas = _fuse_by_dtype(bundles, n)
        fn = _eager_grouped_broadcast_fn(pset.mesh(), axis, root_pos,
                                         len(fused_inputs))
        return _split_fused(fn(*fused_inputs), metas, len(tensors))


# ---------------------------------------------------------------------------
# object collectives (reference torch/functions.py broadcast_object /
# allgather_object)
# ---------------------------------------------------------------------------

def broadcast_object(obj, root_rank: int = 0, *, name: str | None = None):
    """Broadcast a picklable object from the process owning global chip
    ``root_rank`` (reference ``broadcast_object``, ``torch/functions.py``).
    Objects live per controller process, so this is a process-level
    broadcast; ``root_rank`` is a chip rank like everywhere else in the
    API and is mapped to its owning process."""
    del name
    if runtime.process_count() <= 1:
        return obj
    ch = _lb.object_channel()
    if ch is not None:
        # Loopback worlds exchange through the hub: jax's multihost
        # utilities need a real multi-process backend. Only the root's
        # payload travels.
        root_process = runtime.process_of_rank(root_rank)
        mine = pickle.dumps(obj) if runtime.process_rank() == root_process \
            else b""
        payloads = ch.gather(mine)
        return pickle.loads(payloads[root_process])
    from jax.experimental import multihost_utils
    root_process = runtime.devices()[root_rank].process_index
    is_source = runtime.process_rank() == root_process
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    size = multihost_utils.broadcast_one_to_all(
        np.array(len(payload), np.int64), is_source=is_source)
    buf = np.zeros(int(size), np.uint8)
    if is_source:
        buf[:] = payload
    out = multihost_utils.broadcast_one_to_all(buf, is_source=is_source)
    return pickle.loads(out.tobytes())


def allgather_object(obj, *, name: str | None = None) -> list:
    """Gather a picklable object from every *process* (reference
    ``allgather_object``)."""
    del name
    if runtime.process_count() <= 1:
        return [obj]
    ch = _lb.object_channel()
    if ch is not None:
        return [pickle.loads(b) for b in ch.gather(pickle.dumps(obj))]
    return [pickle.loads(b) for b in _gather_bytes(pickle.dumps(obj))]


def _gather_bytes(data: bytes) -> list:
    from jax.experimental import multihost_utils
    n = runtime.process_count()
    size = np.array(len(data), np.int64)
    sizes = multihost_utils.process_allgather(size)
    max_size = int(np.max(sizes))
    buf = np.zeros(max_size, np.uint8)
    buf[: len(data)] = np.frombuffer(data, np.uint8)
    bufs = multihost_utils.process_allgather(buf)
    return [bufs[i, : int(sizes[i])].tobytes() for i in range(n)]
