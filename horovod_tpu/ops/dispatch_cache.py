"""Steady-state dispatch plan cache for eager collectives.

The Python twin of the reference's ResponseCache fast path
(``response_cache.h:107-169``; served in ``ComputeResponseList``'s HIT
branch, ``controller.cc:73-430``): the native engine already skips the
*cross-process* metadata exchange for repeated collectives, but every
eager call still paid the full *per-call Python dispatch* — exception-probed
mode detection, bundle materialization, mesh hashing through several
``lru_cache`` layers, fusion re-bucketing, negotiation/autotune/timeline
bookkeeping. A :class:`DispatchPlan` captures all of those decisions on the
first call; subsequent calls with the same key go straight from user tensor
to the compiled ``jit(shard_map(...))`` invocation.

Keys cover (op kind, user name, per-rank shape, dtype, process-set key,
reduce op, pre/post scale, hierarchical flag) — anything that changes the
compiled program or the negotiated metadata. Capacity and the off switch
ride the existing ``HVD_CACHE_CAPACITY`` knob (reference default 1024,
``global_state.h:89``; 0 disables caching entirely). The whole cache is
flushed ("invalidated") when the runtime generation changes
(``shutdown()``/``init()``), when a process set is removed, when the
negotiation services reset, or when a knob override changes (the autotuner
retunes ``FUSION_THRESHOLD``/``HIERARCHICAL_ALLREDUCE``/… — any of which
changes plan contents).

Statistics surface through :func:`stats` (exported as
``hvd.dispatch_cache_stats()``) and, when a timeline is recording, as
instant ``PLAN_HIT``/``PLAN_MISS`` events per op lane.

The cache has two clients: direct eager calls, and the cycle-driven
fusion scheduler (``ops/fusion_cycle.py``), whose single-controller
flushes coalesce a pending queue into one ``grouped_allreduce`` /
``grouped_broadcast`` — a steady-state training loop's flush signature
repeats every step, so the coalesced dispatch is a plan HIT straight into
the compiled fuse+wire programs (this pairing is what makes the cycle
flush cheap enough to sit on the async hot path).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable

from .. import autotune as _autotune
from .. import timeline as _timeline
from ..utils import envs
from ..utils import invariants as _inv


class DispatchPlan:
    """One fully-resolved eager dispatch: negotiation decision, payload
    accounting, timeline labels, and the executor closure wrapping the
    compiled program. ``negotiate`` is ``None`` when the plan pinned the
    no-service decision (single-process job / non-member) — the per-call
    ``get_service`` + auto-name round is skipped entirely.

    ``variant`` distinguishes the one-wire-program composition
    (``"fused"``) from the chunk-pipelined one (``"chunked"``, fused wire
    buffers past ``HVD_PIPELINE_THRESHOLD`` split into ``pieces``
    back-to-back collective programs — see docs/pipeline.md)."""

    __slots__ = ("label", "activity", "nbytes", "negotiate", "execute",
                 "variant", "pieces")

    def __init__(self, label: str, activity: str, nbytes: int | None,
                 negotiate: Callable | None, execute: Callable,
                 variant: str = "fused", pieces: int = 1):
        self.label = label
        self.activity = activity
        self.nbytes = nbytes
        self.negotiate = negotiate
        self.execute = execute
        self.variant = variant
        self.pieces = pieces

    def run(self, arg):
        if self.negotiate is None:
            note_negotiation_skip()
        else:
            self.negotiate()
        if self.nbytes is not None:
            _autotune.record(self.nbytes)
        with _timeline.op_range(self.label, self.activity):
            return self.execute(arg)


# Cached negative decision: this signature can never be planned (e.g.
# multi-process allgather, whose program shape depends on the negotiated
# recv_splits). Stored like a plan so repeated calls skip both the rebuild
# attempt AND the miss counter.
UNPLANNABLE = object()

_lock = _inv.make_lock("dispatch_cache.lock")
_plans: "OrderedDict[tuple, DispatchPlan]" = OrderedDict()
_epoch: tuple | None = None
_hits = 0
_misses = 0
_invalidations = 0
_evictions = 0
_negotiation_skips = 0
_chunked_builds = 0


def capacity() -> int:
    """Live capacity from ``HVD_CACHE_CAPACITY`` (0 = caching off). Read
    per lookup so tests and the autotuner can flip it at runtime."""
    return envs.cache_capacity()


def enabled() -> bool:
    return capacity() > 0


def _current_epoch() -> tuple:
    from .. import runtime
    return (runtime.generation(), envs.override_epoch())


def _flush_locked(count_invalidation: bool) -> None:
    global _invalidations
    _inv.assert_holding(_lock, "dispatch_cache plan-map flush")
    if count_invalidation:
        _invalidations += len(_plans)
    _plans.clear()


def lookup(key: tuple) -> DispatchPlan | None:
    """Plan for ``key``, or None (miss / caching disabled). Epoch drift
    (re-init, knob override change) flushes before the lookup so a stale
    plan can never serve."""
    global _hits, _misses, _epoch
    if capacity() <= 0:
        return None
    epoch = _current_epoch()
    with _lock:
        if _epoch != epoch:
            _flush_locked(count_invalidation=_epoch is not None)
            _epoch = epoch
        plan = _plans.get(key)
        if plan is None:
            _misses += 1
            return None
        _plans.move_to_end(key)
        if plan is UNPLANNABLE:
            return plan  # negative decision: neither a hit nor a miss
        _hits += 1
    _timeline.record_dispatch(plan.label, hit=True)
    return plan


def store(key: tuple, plan: DispatchPlan) -> None:
    """Insert ``plan`` (LRU-evicting past capacity). No-op when caching is
    disabled, so the build-per-call path stays allocation-clean."""
    global _evictions, _epoch, _chunked_builds
    cap = capacity()
    if cap <= 0:
        return
    epoch = _current_epoch()
    with _lock:
        if plan is not UNPLANNABLE and plan.variant == "chunked":
            _chunked_builds += 1
        if _epoch != epoch:
            _flush_locked(count_invalidation=_epoch is not None)
            _epoch = epoch
        _plans[key] = plan
        _plans.move_to_end(key)
        while len(_plans) > cap:
            _plans.popitem(last=False)
            _evictions += 1
    if plan is not UNPLANNABLE:
        _timeline.record_dispatch(plan.label, hit=False)


def invalidate(reason: str | None = None) -> int:
    """Flush every cached plan (process-set removal, service reset,
    shutdown). Returns the number of plans dropped."""
    del reason
    with _lock:
        n = len(_plans)
        _flush_locked(count_invalidation=True)
    return n


def note_negotiation_skip() -> None:
    """Account one negotiation round skipped — either the plan pinned the
    no-service decision, or the engine served the round from its response
    cache (``from_cache``, the reference's bitvector HIT path)."""
    global _negotiation_skips
    _negotiation_skips += 1


def stats() -> dict:
    """Plan-cache counters (the ``hvd.dispatch_cache_stats()`` API)."""
    with _lock:
        return {
            "enabled": enabled(),
            "capacity": capacity(),
            "size": len(_plans),
            "hits": _hits,
            "misses": _misses,
            "invalidations": _invalidations,
            "evictions": _evictions,
            "negotiation_skips": _negotiation_skips,
            "chunked_builds": _chunked_builds,
        }


def reset_stats() -> None:
    global _hits, _misses, _invalidations, _evictions, _negotiation_skips
    global _chunked_builds
    with _lock:
        _hits = _misses = _invalidations = _evictions = 0
        _negotiation_skips = _chunked_builds = 0


def reset() -> None:
    """Tests / teardown: drop plans AND counters."""
    global _epoch
    with _lock:
        _plans.clear()
        _epoch = None
    reset_stats()
