"""Steady-state dispatch plan cache for eager collectives.

The Python twin of the reference's ResponseCache fast path
(``response_cache.h:107-169``; served in ``ComputeResponseList``'s HIT
branch, ``controller.cc:73-430``): the native engine already skips the
*cross-process* metadata exchange for repeated collectives, but every
eager call still paid the full *per-call Python dispatch* — exception-probed
mode detection, bundle materialization, mesh hashing through several
``lru_cache`` layers, fusion re-bucketing, negotiation/autotune/timeline
bookkeeping. A :class:`DispatchPlan` captures all of those decisions on the
first call; subsequent calls with the same key go straight from user tensor
to the compiled ``jit(shard_map(...))`` invocation.

Keys cover (op kind, user name, per-rank shape, dtype, process-set key,
reduce op, pre/post scale, hierarchical flag) — anything that changes the
compiled program or the negotiated metadata. Capacity and the off switch
ride the existing ``HVD_CACHE_CAPACITY`` knob (reference default 1024,
``global_state.h:89``; 0 disables caching entirely). The whole cache is
flushed ("invalidated") when the runtime generation changes
(``shutdown()``/``init()``), when a process set is removed, when the
negotiation services reset, or when a knob override changes (the autotuner
retunes ``FUSION_THRESHOLD``/``HIERARCHICAL_ALLREDUCE``/… — any of which
changes plan contents).

Statistics surface through :func:`stats` (exported as
``hvd.dispatch_cache_stats()``) and, when a timeline is recording, as
instant ``PLAN_HIT``/``PLAN_MISS`` events per op lane.

The cache has two clients: direct eager calls, and the cycle-driven
fusion scheduler (``ops/fusion_cycle.py``), whose single-controller
flushes coalesce a pending queue into one ``grouped_allreduce`` /
``grouped_broadcast`` — a steady-state training loop's flush signature
repeats every step, so the coalesced dispatch is a plan HIT straight into
the compiled fuse+wire programs (this pairing is what makes the cycle
flush cheap enough to sit on the async hot path).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable

import zlib as _zlib

from .. import autotune as _autotune
from .. import conformance as _conformance
from .. import metrics as _metrics
from .. import timeline as _timeline
from ..utils import envs
from ..utils import invariants as _inv


class DispatchPlan:
    """One fully-resolved eager dispatch: negotiation decision, payload
    accounting, timeline labels, and the executor closure wrapping the
    compiled program. ``negotiate`` is ``None`` when the plan pinned the
    no-service decision (single-process job / non-member) — the per-call
    ``get_service`` + auto-name round is skipped entirely.

    ``variant`` distinguishes the one-wire-program composition
    (``"fused"``) from the chunk-pipelined one (``"chunked"``, fused wire
    buffers past ``HVD_PIPELINE_THRESHOLD`` split into ``pieces``
    back-to-back collective programs — see docs/pipeline.md)."""

    __slots__ = ("label", "activity", "nbytes", "negotiate", "execute",
                 "variant", "pieces")

    def __init__(self, label: str, activity: str, nbytes: int | None,
                 negotiate: Callable | None, execute: Callable,
                 variant: str = "fused", pieces: int = 1):
        self.label = label
        self.activity = activity
        self.nbytes = nbytes
        self.negotiate = negotiate
        self.execute = execute
        self.variant = variant
        self.pieces = pieces

    def run(self, arg):
        if self.negotiate is None:
            note_negotiation_skip()
        else:
            self.negotiate()
        if self.nbytes is not None:
            _autotune.record(self.nbytes)
        with _timeline.op_range(self.label, self.activity):
            return self.execute(arg)


# Cached negative decision: this signature can never be planned (e.g.
# multi-process allgather, whose program shape depends on the negotiated
# recv_splits). Stored like a plan so repeated calls skip both the rebuild
# attempt AND the miss counter.
UNPLANNABLE = object()

_lock = _inv.make_lock("dispatch_cache.lock")
_plans: "OrderedDict[tuple, DispatchPlan]" = OrderedDict()
_epoch: tuple | None = None

# --------------------------------------------------------------------------
# Elastic warm re-form (docs/elastic.md): instead of dropping every plan
# when a world resizes, the re-form teardown SHELVES the store keyed by
# process-set shape (world scope, size, own rank), and a later re-form
# back to that shape adopts it as a WARM POOL. Warm plans are never
# served from the pool directly — negotiation names must be re-derived
# through the normal build path so auto-name counters stay in lockstep
# on every member (a fresh replacement rank has no pool and builds cold)
# — instead `store()` grafts a pool plan's compiled `execute` stage onto
# the newly built plan when the keys AND derived negotiation names
# match, skipping the first-call retrace/recompile. A genuinely new
# shape simply never matches its shelf entry; registered-process-set
# keys are excluded (their numeric ids are not stable across worlds), so
# a resize invalidates exactly those affected sets.
# --------------------------------------------------------------------------

# Shapes retained process-wide (LRU). One loopback elastic run touches
# up to world_max shapes per size it visits (one per (size, rank)): a
# world-W churn cycle keeps ~3W shape keys live at once (W at the old
# size, W-1 at the new, W re-shelved before the next round drains its
# takes). The static floor covers small worlds; past it the cap scales
# with the largest world currently shelved so a world-16 cycle cannot
# evict its own shapes mid-cycle (ISSUE 15 shelf sizing).
_SHELF_SHAPES = 32
_shelf: "OrderedDict[tuple, dict]" = OrderedDict()
_warm_plans: dict = {}  # non-loopback warm pool (loopback: ctx.warm_plans)


def _shelf_cap() -> int:
    """Caller holds ``_lock``. Shape layout: (scope, size, rank) —
    index 1 is the world size."""
    worlds = [k[1] for k in _shelf
              if len(k) > 1 and isinstance(k[1], int)]
    return max(_SHELF_SHAPES, 4 * max(worlds, default=0))


def _current_shape() -> tuple | None:
    """Shape key of this thread's world: (world scope, size, rank).
    Loopback scopes by the LoopbackWorld name so one world's re-forms
    reuse each other's shelves but distinct worlds never cross."""
    from .. import runtime
    if not runtime.is_initialized():
        return None
    from ..loopback import context as _lbctx
    ctx = _lbctx.current()
    scope = ctx.world.name if ctx is not None else "proc"
    return (scope, runtime.process_count(), runtime.process_rank())


def _restorable(key: tuple, plan) -> bool:
    if plan is UNPLANNABLE:
        return False
    if getattr(plan, "variant", None) == "gspmd":
        # A compiled GSPMD step bakes the old world's device assignment
        # into the executable; a re-formed world (even at the same
        # shape) may map ranks to different devices, so these never
        # ride the warm shelf — the first warm call re-lowers.
        return False
    if getattr(plan, "variant", None) == "step":
        return bool(getattr(plan, "rebindable", False))
    # Eager plan keys carry the pset dispatch_key at index 4: "g" (an
    # unregistered global view), id 0 (THE global set — every world
    # registers it as 0), and rank tuples are self-describing across
    # worlds; other registered ids are not (a re-formed world may hand
    # the same number to a different rank list) — those stay flushed,
    # which is the "invalidate exactly the affected process sets" rule.
    if len(key) > 4 and isinstance(key[4], int) \
            and not isinstance(key[4], bool):
        return key[4] == 0
    return True


def shelve_for_reform() -> int:
    """Move this world's restorable plans onto the shape-keyed shelf
    (called by the re-form teardown BEFORE the store is invalidated).
    Unconsumed warm-pool leftovers ride along — they are plans of this
    same shape a short incarnation never got to rebuild."""
    if not envs.elastic_warm_enabled() or capacity() <= 0:
        return 0
    shape = _current_shape()
    if shape is None:
        return 0
    global _warm_plans
    epoch = envs.override_epoch()
    ctx = _ctx_store()
    plans = ctx.plans if ctx is not None else _plans
    with _lock:
        keep = {k: p for k, p in plans.items() if _restorable(k, p)}
        for k in keep:
            plans.pop(k, None)
        pool = ctx.warm_plans if ctx is not None else _warm_plans
        for k, p in (pool or {}).items():
            keep.setdefault(k, p)
        if ctx is not None:
            ctx.warm_plans = None
        else:
            _warm_plans = {}
        if not keep:
            return 0
        merged = _shelf.get(shape)
        if merged is not None and merged["epoch"] == epoch:
            merged["plans"].update(keep)
        else:
            _shelf[shape] = {"plans": keep, "epoch": epoch}
        _shelf.move_to_end(shape)
        cap = _shelf_cap()
        while len(_shelf) > cap:
            _shelf.popitem(last=False)
        _conformance.record(
            "ops/dispatch_cache.py::shelve_for_reform", "shelve",
            (shape, len(keep)))
        return len(keep)


def restore_for_reform() -> int:
    """Adopt the shelf entry matching this (re-formed) world's shape as
    the warm pool (called at the end of init). Returns the pool size;
    0 when the shape was never seen, warm re-form is off, or a knob
    override changed the wire composition the shelved programs baked."""
    if not envs.elastic_warm_enabled() or capacity() <= 0:
        return 0
    shape = _current_shape()
    if shape is None:
        return 0
    global _warm_plans
    ctx = _ctx_store()
    with _lock:
        entry = _shelf.pop(shape, None)
        if entry is None:
            return 0
        if entry["epoch"] != envs.override_epoch():
            _metrics.DISPATCH_INVALIDATIONS.inc(len(entry["plans"]))
            return 0
        if ctx is not None:
            ctx.warm_plans = entry["plans"]
        else:
            _warm_plans = entry["plans"]
        _conformance.record(
            "ops/dispatch_cache.py::restore_for_reform", "restore",
            (shape, len(entry["plans"])))
        return len(entry["plans"])


def _warm_graft_locked(ctx, key: tuple, plan) -> None:
    """Graft a warm-pool plan's compiled ``execute`` onto the newly
    built ``plan`` for the same key — valid only when the re-derived
    negotiation name matches the shelved one (then the loopback
    rendezvous keys and wire composition are identical by construction).
    Caller holds ``_lock``."""
    pool = ctx.warm_plans if ctx is not None else _warm_plans
    if not pool or plan is UNPLANNABLE:
        return
    warm = pool.pop(key, None)
    if warm is None or warm is UNPLANNABLE:
        return
    if type(warm) is not type(plan) or warm.variant != plan.variant \
            or warm.pieces != plan.pieces:
        return
    if getattr(warm.negotiate, "neg_name", None) != \
            getattr(plan.negotiate, "neg_name", None):
        return
    plan.execute = warm.execute
    _metrics.ELASTIC_WARM_REUSE.inc(labels={
        "kind": "step" if plan.variant == "step" else "plan"})
    _conformance.record(
        "ops/dispatch_cache.py::_warm_graft_locked", "graft",
        (plan.variant, _zlib.crc32(repr(key).encode()) & 0xFFFFFFFF))


def _ctx_store():
    """Loopback rank threads get their own plan map: plan keys repeat
    across ranks (same op/name/shape/pset id) but the cached ``negotiate``
    closures pin each rank's OWN service and the execute closures pin its
    exchange identity — one rank's plan must never serve another.
    Counters stay process-wide (shared metrics)."""
    from ..loopback import context as _lbctx
    ctx = _lbctx.current()
    if ctx is None:
        return None
    if ctx.plans is None:
        ctx.plans = OrderedDict()
    return ctx


# Counter storage lives in the unified metrics registry (metrics.py,
# ``always=True`` instruments — recording survives HVD_METRICS=0 because
# these back the hvd.dispatch_cache_stats() API). A loopback rank's
# lookups land in its OWN registry store, matching its per-rank plan map:
# one rank's counters never bleed into a peer's view.
#
# Where a plan hit was served from: "call" (direct eager collective),
# "flush" (a fusion-cycle flush coalescing a queue), "step" (the step
# capture-and-replay program, ops/step_capture.py), or "gspmd" (a
# replayed compiled jit/pjit step, ops/gspmd_cache.py). Per-source hit
# counters keep the overlap/coalesce ratios honest when capture is on —
# a replayed step serves ONE step-plan hit where the per-flush path
# would have served one hit per flush — and put both execution modes'
# cached-program hits on one accounting surface.
_SOURCES = ("call", "flush", "step", "gspmd")
_tls = threading.local()


class dispatch_source:
    """Context manager tagging plan lookups on this thread with their
    dispatch source (see ``_SOURCES``); the default, untagged source is
    ``"call"``."""

    __slots__ = ("_source", "_prev")

    def __init__(self, source: str):
        self._source = source
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_tls, "source", None)
        _tls.source = self._source
        return self

    def __exit__(self, *exc):
        _tls.source = self._prev
        return False


def current_source() -> str:
    return getattr(_tls, "source", None) or "call"


def capacity() -> int:
    """Live capacity from ``HVD_CACHE_CAPACITY`` (0 = caching off). Read
    per lookup so tests and the autotuner can flip it at runtime."""
    return envs.cache_capacity()


def enabled() -> bool:
    return capacity() > 0


def _current_epoch() -> tuple:
    from .. import runtime
    return (runtime.generation(), envs.override_epoch())


def _flush_locked(count_invalidation: bool) -> None:
    _flush_store_locked(_plans, count_invalidation)


def _flush_store_locked(plans, count_invalidation: bool) -> None:
    _inv.assert_holding(_lock, "dispatch_cache plan-map flush")
    if count_invalidation and plans:
        _metrics.DISPATCH_INVALIDATIONS.inc(len(plans))
    plans.clear()


def _sync_epoch_locked(ctx, plans, epoch: tuple) -> None:
    """Epoch-drift flush for the resolved store (shared by lookup and
    store): a changed runtime generation / knob-override epoch drops
    every plan before the map is read or written."""
    global _epoch
    prior = ctx.plan_epoch if ctx is not None else _epoch
    if prior != epoch:
        _flush_store_locked(plans, count_invalidation=prior is not None)
        if ctx is not None:
            ctx.plan_epoch = epoch
        else:
            _epoch = epoch


def lookup(key: tuple, source: str | None = None,
           record_stats: bool = True) -> DispatchPlan | None:
    """Plan for ``key``, or None (miss / caching disabled). Epoch drift
    (re-init, knob override change) flushes before the lookup so a stale
    plan can never serve. ``source`` (default: the thread's ambient
    :class:`dispatch_source`) tags the hit counter so per-flush and
    replayed-step hits stay distinguishable. ``record_stats=False`` is
    for bookkeeping probes (the capture controller's seal/arm checks):
    the lookup itself stays silent and the hit is counted only when a
    replay actually serves (:func:`note_step_hit`), so the counters
    reflect work served, not state-machine traffic."""
    global _epoch
    if capacity() <= 0:
        return None
    epoch = _current_epoch()
    src = source or current_source()
    ctx = _ctx_store()
    plans = ctx.plans if ctx is not None else _plans
    with _lock:
        _sync_epoch_locked(ctx, plans, epoch)
        plan = plans.get(key)
        if plan is None:
            if record_stats:
                _metrics.DISPATCH_MISSES.inc()
            return None
        plans.move_to_end(key)
        if plan is UNPLANNABLE:
            return plan  # negative decision: neither a hit nor a miss
        if record_stats:
            _metrics.DISPATCH_HITS.inc(labels={"source": src})
    if record_stats:
        _timeline.record_dispatch(plan.label, hit=True)
    return plan


def note_step_hit() -> None:
    """Count one SERVED step-plan replay (``hits_by_source["step"]``):
    called by the capture controller when the whole-step program
    actually executes, so step hits equal replayed steps exactly — an
    armed-then-diverged step never counts."""
    _metrics.DISPATCH_HITS.inc(labels={"source": "step"})
    _timeline.record_dispatch("step", hit=True)


def note_gspmd_hit() -> None:
    """Count one SERVED compiled GSPMD step replay
    (``hits_by_source["gspmd"]``) — the gspmd twin of
    :func:`note_step_hit`: counted after the executable accepts its
    inputs, so a signature hit whose executable rejects (the divergence
    fallback) never counts."""
    _metrics.DISPATCH_HITS.inc(labels={"source": "gspmd"})
    _timeline.record_dispatch("gspmd", hit=True)


def fold_knobs(variant: str, key: tuple, *raw_knob_values) -> tuple:
    """THE store-key canonicalizer shared by the whole-step program
    caches (``step_capture._store_key`` / ``gspmd_cache``): prefix a
    content ``key`` with its plan ``variant`` and the RAW values of
    every knob the compiled program bakes in. Override-driven knob
    changes already invalidate via the cache epoch, but a raw
    ``os.environ`` change does not bump the epoch — folding the values
    into the key means a stale program can never replay.

    Axis-layout discipline: any plan whose compiled program bakes in a
    mesh-axis split carries the layout in its key — the eager
    allreduce/grouped-allreduce/allgather keys fold
    ``hierarchical.layout_key_for(pset)`` (the composed-mesh layout
    signature, ``parallel/mesh.py``), step capture folds the raw
    ``HVD_MESH_AXES`` carve, and the GSPMD cache fingerprints the full
    mesh (axis names + shape + device ids) through its shardings."""
    return (variant,) + tuple(raw_knob_values) + (key,)


def drop(key: tuple) -> bool:
    """Remove ONE plan from this thread's store (the gspmd divergence
    contract: an executable that rejected its inputs despite a
    signature hit must not serve again). Returns whether a plan was
    present. Unlike :func:`invalidate`, every other plan survives."""
    ctx = _ctx_store()
    plans = ctx.plans if ctx is not None else _plans
    with _lock:
        found = plans.pop(key, None)
        if found is not None:
            _metrics.DISPATCH_INVALIDATIONS.inc()
    return found is not None


def store(key: tuple, plan: DispatchPlan) -> None:
    """Insert ``plan`` (LRU-evicting past capacity). No-op when caching is
    disabled, so the build-per-call path stays allocation-clean."""
    global _epoch
    cap = capacity()
    if cap <= 0:
        return
    epoch = _current_epoch()
    ctx = _ctx_store()
    plans = ctx.plans if ctx is not None else _plans
    with _lock:
        if plan is not UNPLANNABLE and plan.variant == "chunked":
            _metrics.DISPATCH_CHUNKED_BUILDS.inc()
        if plan is not UNPLANNABLE and plan.variant == "step":
            _metrics.DISPATCH_STEP_BUILDS.inc()
        if plan is not UNPLANNABLE and plan.variant == "gspmd":
            _metrics.DISPATCH_GSPMD_BUILDS.inc()
        _sync_epoch_locked(ctx, plans, epoch)
        # Elastic warm re-form: adopt the shelved incarnation's compiled
        # execute stage before the first call pays the retrace/recompile.
        _warm_graft_locked(ctx, key, plan)
        plans[key] = plan
        plans.move_to_end(key)
        while len(plans) > cap:
            plans.popitem(last=False)
            _metrics.DISPATCH_EVICTIONS.inc()
    # Local event (docs/conformance.md): plan-key builds are
    # legitimately rank-asymmetric after a warm re-form (a survivor
    # hits where a fresh rank builds), so they are recorded per rank
    # but never chained cross-rank.
    _conformance.record(
        "ops/dispatch_cache.py::store", "plan_store",
        (getattr(plan, "variant", "unplannable"),
         _zlib.crc32(repr(key).encode()) & 0xFFFFFFFF))
    if plan is not UNPLANNABLE:
        _timeline.record_dispatch(plan.label, hit=False)


def invalidate(reason: str | None = None) -> int:
    """Flush every cached plan (process-set removal, service reset,
    shutdown) in this thread's world — a loopback rank invalidates its
    own store. Returns the number of plans dropped."""
    global _warm_plans
    del reason
    ctx = _ctx_store()
    plans = ctx.plans if ctx is not None else _plans
    with _lock:
        n = len(plans)
        _flush_store_locked(plans, count_invalidation=True)
        # the warm pool holds plans of THIS world's shape; whatever
        # invalidated the store (pset removal, service reset) applies
        if ctx is not None:
            ctx.warm_plans = None
        else:
            _warm_plans = {}
    return n


def note_negotiation_skip() -> None:
    """Account one negotiation round skipped — either the plan pinned the
    no-service decision, or the engine served the round from its response
    cache (``from_cache``, the reference's bitvector HIT path)."""
    _metrics.DISPATCH_NEGOTIATION_SKIPS.inc()


def stats() -> dict:
    """Plan-cache counters (the ``hvd.dispatch_cache_stats()`` API) —
    a view over the unified metrics registry, shape-identical to the
    pre-registry dicts. On a loopback rank thread the view (like the
    rank's plan map) is that rank's own."""
    by_source = {s: 0 for s in _SOURCES}
    for labelitems, v in _metrics.DISPATCH_HITS.series().items():
        by_source[dict(labelitems).get("source", "call")] = int(v)
    warm_reuses = 0
    for labelitems, v in _metrics.ELASTIC_WARM_REUSE.series().items():
        if dict(labelitems).get("kind") in ("plan", "step"):
            warm_reuses += int(v)
    ctx = _ctx_store()
    plans = ctx.plans if ctx is not None else _plans
    with _lock:
        size = len(plans)
        pool = ctx.warm_plans if ctx is not None else _warm_plans
        warm_pool = len(pool or {})
    return {
        "enabled": enabled(),
        "capacity": capacity(),
        "size": size,
        "hits": sum(by_source.values()),
        "hits_by_source": by_source,
        "misses": int(_metrics.DISPATCH_MISSES.value()),
        "invalidations": int(_metrics.DISPATCH_INVALIDATIONS.value()),
        "evictions": int(_metrics.DISPATCH_EVICTIONS.value()),
        "negotiation_skips": int(
            _metrics.DISPATCH_NEGOTIATION_SKIPS.value()),
        "chunked_builds": int(_metrics.DISPATCH_CHUNKED_BUILDS.value()),
        "step_builds": int(_metrics.DISPATCH_STEP_BUILDS.value()),
        "gspmd_builds": int(_metrics.DISPATCH_GSPMD_BUILDS.value()),
        # elastic warm re-form (docs/elastic.md): plans waiting in this
        # world's warm pool, and compiled stages grafted from it
        "warm_pool": warm_pool,
        "warm_reuses": warm_reuses,
    }


def reset_stats() -> None:
    for inst in (_metrics.DISPATCH_HITS, _metrics.DISPATCH_MISSES,
                 _metrics.DISPATCH_INVALIDATIONS,
                 _metrics.DISPATCH_EVICTIONS,
                 _metrics.DISPATCH_NEGOTIATION_SKIPS,
                 _metrics.DISPATCH_CHUNKED_BUILDS,
                 _metrics.DISPATCH_STEP_BUILDS,
                 _metrics.DISPATCH_GSPMD_BUILDS):
        inst.reset()


def reset() -> None:
    """Tests / teardown: drop plans, shelves, pools AND counters."""
    global _epoch, _warm_plans
    with _lock:
        _plans.clear()
        _epoch = None
        _shelf.clear()
        _warm_plans = {}
    reset_stats()
