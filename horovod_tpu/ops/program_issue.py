"""Cross-thread serialization of collective program *issue*.

With the pipelined flush executor (``ops/fusion_cycle.py``) a dedicated
thread dispatches queued collectives while user threads may concurrently
dispatch synchronous ones. Two multi-device collective programs whose
per-device enqueues interleave can deadlock the backend's collective
rendezvous — reproduced on the XLA CPU backend: two ``psum`` launches
from two threads each ended up waiting forever for participants that were
stuck inside the *other* launch (device 0 ran program A's participant
while device 1 ran program B's, and neither rendezvous could complete).
The reference runtime has the same invariant one layer down: all NCCL
launches happen on the single background thread (``operations.cc:385``).

The fix is a process-wide issue lock held around the *enqueue* of every
eager collective program. JAX dispatch is asynchronous — the lock covers
only the host-side enqueue (microseconds to low milliseconds), never
device execution or completion waits, so it serializes program ORDER
without serializing the work. This also gives multi-threaded eager
callers a well-defined cross-process program issue order, which the
multi-process determinism contract requires.
"""

from __future__ import annotations

from ..utils import invariants as _inv

# RLock: a wrapped program is never called from inside another wrapped
# program (compositions happen at trace time), but re-entrancy is cheap
# insurance against future nesting. Witness-tracked under
# HVD_DEBUG_INVARIANTS so program issue participates in the lock-order
# graph (docs/static_analysis.md).
_ISSUE_LOCK = _inv.make_rlock("program_issue.issue")


def issue_lock_held() -> bool:
    """Whether the current thread is inside a serialized program issue.
    The section counter is always maintained, so this works with the
    checker off too; the lock-based half additionally covers direct
    ``_ISSUE_LOCK`` holders when ``HVD_DEBUG_INVARIANTS=1`` makes the
    RLock witness-tracked (plain RLocks don't expose their owner)."""
    return _inv.holding(_ISSUE_LOCK) or _inv.inside("program-issue")


def issue_serialized(fn):
    """Wrap a compiled (jitted) program so concurrent callers enqueue
    their device work atomically. Returns a plain closure; the wrapped
    callable's only contract is ``__call__``."""

    def call(*args, **kwargs):
        with _ISSUE_LOCK, _inv.section("program-issue"):
            return fn(*args, **kwargs)

    return call
